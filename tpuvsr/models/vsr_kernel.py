"""jit+vmap transition kernel for VSR (reference: VSR.tla:366-918).

This is the TPU replacement for TLC's ``Tool.getNextStates`` (SURVEY.md
§2.5, §3.1): one XLA program that, given a dense state (vsr.py layout),
evaluates *every* action x bound-variable combination as one SIMD lane
and returns the stacked successor states plus an enabled mask.  The BFS
and simulation engines vmap it over a frontier batch.

Lane plan (one lane = one ``\\E`` binding of one action; VSR.tla Next
disjunct order at VSR.tla:896-918):

  action                          lanes     bound vars
  TimerSendSVC                    R         r            (VSR.tla:578)
  ReceiveHigherSVC                M         m (r=m.dest) (VSR.tla:602)
  ReceiveMatchingSVC              M         m            (VSR.tla:625)
  SendDVC                         R         r            (VSR.tla:648)
  ReceiveHigherDVC                M         m            (VSR.tla:677)
  ReceiveMatchingDVC              M         m            (VSR.tla:696)
  SendSV                          R         r            (VSR.tla:735)
  ReceiveSV                       M         m            (VSR.tla:773)
  ReceiveClientRequest            R*V       r, v (C=1)   (VSR.tla:366)
  ReceivePrepareMsg               M         m            (VSR.tla:405)
  ReceivePrepareOkMsg             M         m            (VSR.tla:437)
  ExecuteOp                       R         r            (VSR.tla:462)
  SendGetState                    M*R       m, rDest     (VSR.tla:496)
  ReceiveGetState                 M         m            (VSR.tla:526)
  ReceiveNewState                 M         m            (VSR.tla:551)
  RestartEmpty                    R         r            (VSR.tla:813)
  ReceivesRecoveryMsg             M         m            (VSR.tla:842)
  ReceivesRecoveryResponseMsg     M         m            (VSR.tla:864)
  CompleteRecovery                R         r            (VSR.tla:878)

Semantic fine print honored here (SURVEY.md §2.7):

* Bag upsert/discard/tombstones: SendFunc/DiscardFunc (VSR.tla:228-245)
  keep delivered messages in the domain at count 0; ``SendOnce`` fails on
  a tombstone (VSR.tla:250-252) — ``m_present`` vs ``m_count`` columns.
* Deterministic CHOOSE: the interpreter picks the value_key-least element
  satisfying the predicate (core/values.py).  The kernel reproduces the
  induced order for the record sets it choses over: records compare by
  field name alphabetically, so DVC records order by (commit_number,
  dest, last_normal_vn, log, op_number, source, ...) and recovery
  responses by (commit_number, dest, log, op_number, source, ...), with
  logs comparing entry-wise by (client_id, operation, request_number,
  view_number) and shorter-prefix-first — see _entry_sort_key/_lex_less.
* The dead ``m.commit`` arm of ReceivePrepareMsg (VSR.tla:421) is
  unreachable for C = 1 (enforced by the layout), so the kernel only
  implements the client's own arm.
* Unused array slots are kept all-zero (canonical-zero invariant) so
  whole-array equality and flat hashing are content-exact.

Also here: the fingerprint kernel (VIEW projection -> symmetry-least
128-bit hash; VSR.tla:149-151) and device invariant kernels for the VSR
property set (VSR.tla:926-952).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .vsr import (E_CLIENT, E_OPER, E_REQ, E_VIEW, ERR_BAG_OVERFLOW,
                  ERR_DVC_OVERFLOW, ERR_REC_OVERFLOW, H_COMMIT, H_DEST,
                  H_FIRST, H_LNV, H_OP, H_SRC, H_TYPE, H_VIEW, H_X,
                  M_DVC, M_GETSTATE, M_NEWSTATE, M_PREPARE, M_PREPAREOK,
                  M_RECOVERY, M_RECOVERYRESP, M_SV, M_SVC, NENT,
                  NORMAL, RECOVERING, T_EXEC, T_OP, T_REQ, VIEWCHANGE,
                  VSRCodec)

I32 = jnp.int32
INF = np.int32(0x7FFFFFFF)

ACTION_NAMES = (
    "TimerSendSVC", "ReceiveHigherSVC", "ReceiveMatchingSVC", "SendDVC",
    "ReceiveHigherDVC", "ReceiveMatchingDVC", "SendSV", "ReceiveSV",
    "ReceiveClientRequest", "ReceivePrepareMsg", "ReceivePrepareOkMsg",
    "ExecuteOp", "SendGetState", "ReceiveGetState", "ReceiveNewState",
    "RestartEmpty", "ReceivesRecoveryMsg", "ReceivesRecoveryResponseMsg",
    "CompleteRecovery",
)

# Replica-state array keys, in a fixed order used for hashing/stacking.
REP_KEYS = ("status", "view", "op", "commit", "lnv", "log", "log_len",
            "peer_op", "ct", "svc", "dvc", "dvc_lnv", "dvc_op",
            "dvc_commit", "dvc_log", "dvc_log_len", "sent_dvc", "sent_sv",
            "rec_number", "rec", "rec_view", "rec_has_log", "rec_log",
            "rec_log_len", "rec_op", "rec_commit")
MSG_KEYS = ("m_present", "m_count", "m_hdr", "m_entry", "m_log",
            "m_log_len", "m_has_log")
AUX_KEYS = ("aux_svc", "aux_restart", "aux_acked", "err")
ALL_KEYS = REP_KEYS + MSG_KEYS + AUX_KEYS


def _lex_less(a, b):
    """Lexicographic < on two equal-length int vectors."""
    ne = a != b
    first = jnp.argmax(ne)
    return ne.any() & (a[first] < b[first])


class VSRKernel:
    action_names = ACTION_NAMES
    # layout key tables as class attributes: the speclint drift pass
    # (analysis/passes/drift.py) checks them against codec.zero_state
    REP_KEYS = REP_KEYS
    MSG_KEYS = MSG_KEYS
    AUX_KEYS = AUX_KEYS
    # plane -> orbit table (ISSUE 11): which planes a symmetry value
    # permutation touches, and how — value ids live in the operation
    # column of every log-entry row (_permuted applies exactly this;
    # engine/canon.py and the speclint symmetry pass both consume the
    # table via canon.orbit_planes, so lint and kernel cannot drift)
    SYM_PLANES = {"log": ("col", E_OPER), "dvc_log": ("col", E_OPER),
                  "rec_log": ("col", E_OPER), "m_log": ("col", E_OPER),
                  "m_entry": ("col", E_OPER)}

    def __init__(self, codec: VSRCodec, perms: np.ndarray = None):
        self.codec = codec
        self.shape = s = codec.shape
        self.R, self.V, self.M = s.R, s.V, s.MAX_MSGS
        self.MAX_OPS = s.MAX_OPS
        self.NHDR = codec.NHDR
        # value-id permutation table for symmetry canonicalization
        # ([P, V+1], row 0 of each perm maps padding 0 -> 0)
        if perms is None:
            perms = np.arange(s.V + 1, dtype=np.int32)[None, :]
        self.perms = np.asarray(perms, dtype=np.int32)

        # lane -> (action_id, param) tables (host-side metadata)
        acts, params = [], []
        for aid, name in enumerate(ACTION_NAMES):
            n = self._lane_count(name)
            acts.append(np.full(n, aid, np.int32))
            params.append(np.arange(n, dtype=np.int32))
        self.lane_action = np.concatenate(acts)
        self.lane_param = np.concatenate(params)
        self.n_lanes = int(self.lane_action.size)

        # deterministic hash coefficients (4 x 32-bit lanes = 128-bit fp).
        # The fingerprint is decomposable (SURVEY.md §7.3.8 incremental
        # hashing): fp = mix(mix(sum_r rep_row_hash(r)
        #                        + sum_m present_m * slot_hash(m)) + seed),
        # so a transition that touches one replica row and a few message
        # slots updates the sums in O(touched) — the expand pass exploits
        # this; the full recompute path must produce identical values.
        rng = np.random.default_rng(0xC0FFEE)
        nrep = 1 + sum(int(np.prod(self._rep_shape(k))) // s.R
                       for k in REP_KEYS)      # replica id + per-r slices
        nmsg = self.NHDR + NENT + self.MAX_OPS * NENT + 3
        self._k_rep = jnp.asarray(
            rng.integers(1, 2**32, size=(4, nrep), dtype=np.uint64)
            .astype(np.uint32) | 1)
        self._k_msg = jnp.asarray(
            rng.integers(1, 2**32, size=(4, nmsg), dtype=np.uint64)
            .astype(np.uint32) | 1)
        self._seeds = jnp.asarray(
            rng.integers(1, 2**32, size=(4,), dtype=np.uint64)
            .astype(np.uint32))

        self.step_batch = jax.jit(jax.vmap(self.step_all))
        self.fingerprint_batch = jax.jit(jax.vmap(self.fingerprint))

    def _rep_shape(self, k):
        s = self.shape
        return {
            "status": (s.R,), "view": (s.R,), "op": (s.R,), "commit": (s.R,),
            "lnv": (s.R,), "log": (s.R, s.MAX_OPS, NENT), "log_len": (s.R,),
            "peer_op": (s.R, s.R), "ct": (s.R, s.C, 3), "svc": (s.R, s.R),
            "dvc": (s.R, s.R), "dvc_lnv": (s.R, s.R), "dvc_op": (s.R, s.R),
            "dvc_commit": (s.R, s.R),
            "dvc_log": (s.R, s.R, s.MAX_OPS, NENT),
            "dvc_log_len": (s.R, s.R), "sent_dvc": (s.R,), "sent_sv": (s.R,),
            "rec_number": (s.R,), "rec": (s.R, s.R), "rec_view": (s.R, s.R),
            "rec_has_log": (s.R, s.R), "rec_log": (s.R, s.R, s.MAX_OPS, NENT),
            "rec_log_len": (s.R, s.R), "rec_op": (s.R, s.R),
            "rec_commit": (s.R, s.R),
        }[k]

    def _lane_count(self, name):
        R, V, M = self.R, self.V, self.M
        return {"TimerSendSVC": R, "SendDVC": R, "SendSV": R, "ExecuteOp": R,
                "RestartEmpty": R, "CompleteRecovery": R,
                "ReceiveClientRequest": R * V, "SendGetState": M * R,
                }.get(name, M)

    # ==================================================================
    # message-bag primitives (VSR.tla:228-275)
    # ==================================================================
    def _row(self, type_, view=0, op=0, commit=0, dest=0, src=0, x=0,
             first=0, lnv=0, entry=None, log=None, log_len=0, has_log=0):
        z = jnp.zeros
        hdr = z((self.NHDR,), I32).at[:9].set(
            jnp.stack([jnp.asarray(v, I32) for v in
                       (type_, view, op, commit, dest, src, x, first,
                        lnv)]))
        return {
            "hdr": hdr,
            "entry": entry if entry is not None else z((NENT,), I32),
            "log": log if log is not None else z((self.MAX_OPS, NENT), I32),
            "log_len": jnp.asarray(log_len, I32),
            "has_log": jnp.asarray(has_log, I32),
        }

    def _row_eq(self, st, row):
        """[M] mask: domain entry equal to row (full record equality)."""
        return ((st["m_present"] == 1)
                & (st["m_hdr"] == row["hdr"]).all(-1)
                & (st["m_entry"] == row["entry"]).all(-1)
                & (st["m_log"] == row["log"]).all((-1, -2))
                & (st["m_log_len"] == row["log_len"])
                & (st["m_has_log"] == row["has_log"]))

    def _touch(self, st, idx, pred):
        """Record a touched message slot for incremental fingerprinting
        (no-op unless the caller seeded the "_ts" scratch keys)."""
        if "_ts" not in st:
            return st
        st = dict(st)
        n = jnp.clip(st["_tn"], 0, st["_ts"].shape[0] - 1)
        st["_ts"] = jnp.where(pred, st["_ts"].at[n].set(idx), st["_ts"])
        st["_tn"] = st["_tn"] + jnp.where(pred, 1, 0)
        return st

    def _bag_send(self, st, row, pred=None):
        """SendFunc upsert (VSR.tla:228-231): +1 if present (tombstones
        revive), else insert at the first free slot with count 1."""
        if pred is None:
            pred = jnp.asarray(True)
        eq = self._row_eq(st, row)
        found = eq.any()
        free = st["m_present"] == 0
        idx = jnp.where(found, jnp.argmax(eq), jnp.argmax(free))
        overflow = pred & ~found & ~free.any()
        st = self._touch(st, idx, pred)
        st = dict(st)
        st["m_count"] = st["m_count"].at[idx].add(jnp.where(pred, 1, 0))
        wr = pred & ~found

        def put(cur, val):
            return jnp.where(wr, cur.at[idx].set(val), cur)
        st["m_present"] = jnp.where(pred, st["m_present"].at[idx].set(1),
                                    st["m_present"])
        st["m_hdr"] = put(st["m_hdr"], row["hdr"])
        st["m_entry"] = put(st["m_entry"], row["entry"])
        st["m_log"] = put(st["m_log"], row["log"])
        st["m_log_len"] = put(st["m_log_len"], row["log_len"])
        st["m_has_log"] = put(st["m_has_log"], row["has_log"])
        st["err"] = st["err"] | jnp.where(overflow, ERR_BAG_OVERFLOW, 0)
        return st

    def _bag_send_once(self, st, row):
        """SendOnce (VSR.tla:250-252): guard fails if the record is in the
        domain at all — a count-0 tombstone blocks the resend."""
        ok = ~self._row_eq(st, row).any()
        return self._bag_send(st, row), ok

    def _bag_discard(self, st, k):
        st = self._touch(st, k, jnp.asarray(True))
        st = dict(st)
        st["m_count"] = st["m_count"].at[k].add(-1)
        return st

    def _broadcast(self, st, row, src):
        """BroadcastFunc (VSR.tla:233-240): upsert [msg EXCEPT !.dest = d]
        for every d != src.  Sequential upserts are equivalent because the
        per-destination records are distinct."""
        for d in range(1, self.R + 1):
            rd = dict(row)
            rd["hdr"] = row["hdr"].at[H_DEST].set(d)
            st = self._bag_send(st, rd, pred=(src != d))
        return st

    # ==================================================================
    # state helpers
    # ==================================================================
    @staticmethod
    def _primary(view, R):
        return 1 + ((view - 1) % R)

    def _is_primary(self, st, i, r):
        return self._primary(st["view"][i], self.R) == r

    def _clear_vc(self, st, i, svc=True, dvc=True):
        """ResetRecvMsgs (VSR.tla:299-301) with canonical-zero payloads."""
        if svc:
            st["svc"] = st["svc"].at[i].set(0)
        if dvc:
            st["dvc"] = st["dvc"].at[i].set(0)
            st["dvc_lnv"] = st["dvc_lnv"].at[i].set(0)
            st["dvc_op"] = st["dvc_op"].at[i].set(0)
            st["dvc_commit"] = st["dvc_commit"].at[i].set(0)
            st["dvc_log"] = st["dvc_log"].at[i].set(0)
            st["dvc_log_len"] = st["dvc_log_len"].at[i].set(0)
        return st

    def _clear_rec(self, st, i):
        st["rec"] = st["rec"].at[i].set(0)
        st["rec_view"] = st["rec_view"].at[i].set(0)
        st["rec_has_log"] = st["rec_has_log"].at[i].set(0)
        st["rec_log"] = st["rec_log"].at[i].set(0)
        st["rec_log_len"] = st["rec_log_len"].at[i].set(0)
        st["rec_op"] = st["rec_op"].at[i].set(0)
        st["rec_commit"] = st["rec_commit"].at[i].set(0)
        return st

    def _reset_sent(self, st, i):
        st["sent_dvc"] = st["sent_dvc"].at[i].set(0)
        st["sent_sv"] = st["sent_sv"].at[i].set(0)
        return st

    @staticmethod
    def _entry_sort_key(rows):
        """value_key order of a log entry record: fields compare
        alphabetically (client_id, operation, request_number, view_number).
        Packed big-endian into one int32; all-zero padding rows -> 0."""
        return (rows[..., E_CLIENT] * (1 << 20) + rows[..., E_OPER] * (1 << 16)
                + rows[..., E_REQ] * (1 << 8) + rows[..., E_VIEW])

    def _log_sort_key(self, log_rows):
        """[..., MAX_OPS] per-position keys; prefix-padding with 0 makes a
        shorter log order before any extension, matching FnVal item-tuple
        comparison (core/values.py value_key)."""
        return self._entry_sort_key(log_rows)

    # ==================================================================
    # the 19 actions.  Each takes (st, lane) and returns (succ, enabled);
    # successors are computed totally and masked by the engine.
    # ==================================================================
    def act_timer_send_svc(self, st, lane):       # VSR.tla:578-590
        i = lane
        r = i + 1
        en = ((st["aux_svc"] < self.shape.timer_limit)
              & ~self._is_primary(st, i, r))
        new_view = st["view"][i] + 1
        s2 = dict(st)
        s2["view"] = st["view"].at[i].set(new_view)
        s2["status"] = st["status"].at[i].set(VIEWCHANGE)
        s2 = self._clear_vc(s2, i)
        s2 = self._reset_sent(s2, i)
        s2["aux_svc"] = st["aux_svc"] + 1
        s2 = self._broadcast(s2, self._row(M_SVC, view=new_view, src=r), r)
        return s2, en

    def act_receive_higher_svc(self, st, lane):   # VSR.tla:602-613
        k = lane
        hdr = st["m_hdr"][k]
        r = hdr[H_DEST]
        i = jnp.clip(r - 1, 0, self.R - 1)
        en = ((st["m_present"][k] == 1) & (st["m_count"][k] > 0)
              & (hdr[H_TYPE] == M_SVC) & (hdr[H_VIEW] > st["view"][i]))
        s2 = dict(st)
        s2["view"] = st["view"].at[i].set(hdr[H_VIEW])
        s2["status"] = st["status"].at[i].set(VIEWCHANGE)
        s2 = self._clear_vc(s2, i)
        s2["svc"] = s2["svc"].at[i, jnp.clip(hdr[H_SRC] - 1, 0, self.R - 1)].set(1)
        s2 = self._reset_sent(s2, i)
        s2 = self._bag_discard(s2, k)
        s2 = self._broadcast(s2, self._row(M_SVC, view=hdr[H_VIEW], src=r), r)
        return s2, en

    def act_receive_matching_svc(self, st, lane):  # VSR.tla:625-634
        k = lane
        hdr = st["m_hdr"][k]
        r = hdr[H_DEST]
        i = jnp.clip(r - 1, 0, self.R - 1)
        en = ((st["m_present"][k] == 1) & (st["m_count"][k] > 0)
              & (hdr[H_TYPE] == M_SVC) & (hdr[H_VIEW] == st["view"][i])
              & (st["status"][i] == VIEWCHANGE))
        s2 = dict(st)
        s2["svc"] = st["svc"].at[i, jnp.clip(hdr[H_SRC] - 1, 0, self.R - 1)].set(1)
        s2 = self._bag_discard(s2, k)
        return s2, en

    def act_send_dvc(self, st, lane):             # VSR.tla:648-669
        i = lane
        r = i + 1
        view = st["view"][i]
        prim = self._primary(view, self.R)
        en = ((st["status"][i] == VIEWCHANGE) & (st["sent_dvc"][i] == 0)
              & (st["svc"][i].sum() >= self.R // 2))
        s2 = dict(st)
        s2["sent_dvc"] = st["sent_dvc"].at[i].set(1)
        # self-delivery: the new primary registers its own DVC directly;
        # set-union of an identical record is a no-op, a different one
        # needs the multi-slot layout (vsr.py docstring)
        self_case = prim == r
        same = ((st["dvc_lnv"][i, i] == st["lnv"][i])
                & (st["dvc_op"][i, i] == st["op"][i])
                & (st["dvc_commit"][i, i] == st["commit"][i])
                & (st["dvc_log_len"][i, i] == st["log_len"][i])
                & (st["dvc_log"][i, i] == st["log"][i]).all())
        collide = self_case & (st["dvc"][i, i] == 1) & ~same
        s2["dvc"] = jnp.where(self_case, s2["dvc"].at[i, i].set(1), s2["dvc"])
        s2["dvc_lnv"] = jnp.where(
            self_case, s2["dvc_lnv"].at[i, i].set(st["lnv"][i]), s2["dvc_lnv"])
        s2["dvc_op"] = jnp.where(
            self_case, s2["dvc_op"].at[i, i].set(st["op"][i]), s2["dvc_op"])
        s2["dvc_commit"] = jnp.where(
            self_case, s2["dvc_commit"].at[i, i].set(st["commit"][i]),
            s2["dvc_commit"])
        s2["dvc_log"] = jnp.where(
            self_case, s2["dvc_log"].at[i, i].set(st["log"][i]), s2["dvc_log"])
        s2["dvc_log_len"] = jnp.where(
            self_case, s2["dvc_log_len"].at[i, i].set(st["log_len"][i]),
            s2["dvc_log_len"])
        s2["err"] = s2["err"] | jnp.where(collide, ERR_DVC_OVERFLOW, 0)
        row = self._row(M_DVC, view=view, op=st["op"][i],
                        commit=st["commit"][i], dest=prim, src=r,
                        lnv=st["lnv"][i], log=st["log"][i],
                        log_len=st["log_len"][i], has_log=1)
        s2 = self._bag_send(s2, row, pred=~self_case)
        return s2, en

    def act_receive_higher_dvc(self, st, lane):   # VSR.tla:677-688
        k = lane
        hdr = st["m_hdr"][k]
        r = hdr[H_DEST]
        i = jnp.clip(r - 1, 0, self.R - 1)
        j = jnp.clip(hdr[H_SRC] - 1, 0, self.R - 1)
        en = ((st["m_present"][k] == 1) & (st["m_count"][k] > 0)
              & (hdr[H_TYPE] == M_DVC) & (hdr[H_VIEW] > st["view"][i]))
        s2 = dict(st)
        s2["view"] = st["view"].at[i].set(hdr[H_VIEW])
        s2["status"] = st["status"].at[i].set(VIEWCHANGE)
        s2 = self._clear_vc(s2, i)
        s2["dvc"] = s2["dvc"].at[i, j].set(1)
        s2["dvc_lnv"] = s2["dvc_lnv"].at[i, j].set(hdr[H_LNV])
        s2["dvc_op"] = s2["dvc_op"].at[i, j].set(hdr[H_OP])
        s2["dvc_commit"] = s2["dvc_commit"].at[i, j].set(hdr[H_COMMIT])
        s2["dvc_log"] = s2["dvc_log"].at[i, j].set(st["m_log"][k])
        s2["dvc_log_len"] = s2["dvc_log_len"].at[i, j].set(st["m_log_len"][k])
        s2 = self._reset_sent(s2, i)
        s2 = self._bag_discard(s2, k)
        s2 = self._broadcast(s2, self._row(M_SVC, view=hdr[H_VIEW], src=r), r)
        return s2, en

    def act_receive_matching_dvc(self, st, lane):  # VSR.tla:696-703
        k = lane
        hdr = st["m_hdr"][k]
        r = hdr[H_DEST]
        i = jnp.clip(r - 1, 0, self.R - 1)
        j = jnp.clip(hdr[H_SRC] - 1, 0, self.R - 1)
        en = ((st["m_present"][k] == 1) & (st["m_count"][k] > 0)
              & (hdr[H_TYPE] == M_DVC) & (hdr[H_VIEW] == st["view"][i]))
        # set-union: identical record already present is a no-op; a
        # *different* DVC from the same source needs the multi-slot layout
        same = ((st["dvc"][i, j] == 1)
                & (st["dvc_lnv"][i, j] == hdr[H_LNV])
                & (st["dvc_op"][i, j] == hdr[H_OP])
                & (st["dvc_commit"][i, j] == hdr[H_COMMIT])
                & (st["dvc_log_len"][i, j] == st["m_log_len"][k])
                & (st["dvc_log"][i, j] == st["m_log"][k]).all())
        collide = (st["dvc"][i, j] == 1) & ~same
        s2 = dict(st)
        s2["dvc"] = st["dvc"].at[i, j].set(1)
        s2["dvc_lnv"] = st["dvc_lnv"].at[i, j].set(hdr[H_LNV])
        s2["dvc_op"] = st["dvc_op"].at[i, j].set(hdr[H_OP])
        s2["dvc_commit"] = st["dvc_commit"].at[i, j].set(hdr[H_COMMIT])
        s2["dvc_log"] = st["dvc_log"].at[i, j].set(st["m_log"][k])
        s2["dvc_log_len"] = st["dvc_log_len"].at[i, j].set(st["m_log_len"][k])
        s2["err"] = st["err"] | jnp.where(collide & en, ERR_DVC_OVERFLOW, 0)
        s2 = self._bag_discard(s2, k)
        return s2, en

    def act_send_sv(self, st, lane):              # VSR.tla:716-758
        i = lane
        r = i + 1
        view = st["view"][i]
        mask = st["dvc"][i] == 1
        en = ((st["status"][i] == VIEWCHANGE) & (st["sent_sv"][i] == 0)
              & (mask.sum() >= self.R // 2 + 1))
        # HighestLog (VSR.tla:716-722): maximal by (last_normal_vn,
        # op_number); CHOOSE ties broken by value_key record order
        # (commit, dest=, lnv=, log, op=, source).
        pair = st["dvc_lnv"][i] * (self.MAX_OPS + 1) + st["dvc_op"][i]
        best_pair = jnp.max(jnp.where(mask, pair, -1))
        maximal = mask & (pair == best_pair)
        logk = self._log_sort_key(st["dvc_log"][i])          # [R, MAX_OPS]
        src_ids = jnp.arange(1, self.R + 1, dtype=I32)
        keys = jnp.concatenate(
            [st["dvc_commit"][i][:, None], logk, src_ids[:, None]], axis=1)
        keys = jnp.where(maximal[:, None], keys, INF)
        best_j = jnp.asarray(0, I32)
        best_key = keys[0]
        for j in range(1, self.R):
            less = _lex_less(keys[j], best_key)
            best_key = jnp.where(less, keys[j], best_key)
            best_j = jnp.where(less, j, best_j)
        new_log = st["dvc_log"][i, best_j]
        new_on = st["dvc_log_len"][i, best_j]   # HighestOpNumber = Len(log)
        new_cn = jnp.max(jnp.where(mask, st["dvc_commit"][i], -1))
        s2 = dict(st)
        s2["status"] = st["status"].at[i].set(NORMAL)
        s2["log"] = st["log"].at[i].set(new_log)
        s2["log_len"] = st["log_len"].at[i].set(new_on)
        s2["op"] = st["op"].at[i].set(new_on)
        s2["peer_op"] = st["peer_op"].at[i].set(0)
        s2["commit"] = st["commit"].at[i].set(new_cn)
        s2["sent_sv"] = st["sent_sv"].at[i].set(1)
        s2["lnv"] = st["lnv"].at[i].set(view)
        row = self._row(M_SV, view=view, op=new_on, commit=new_cn, src=r,
                        log=new_log, log_len=new_on, has_log=1)
        s2 = self._broadcast(s2, row, r)
        return s2, en

    def act_receive_sv(self, st, lane):           # VSR.tla:773-793
        k = lane
        hdr = st["m_hdr"][k]
        r = hdr[H_DEST]
        i = jnp.clip(r - 1, 0, self.R - 1)
        en = ((st["m_present"][k] == 1) & (st["m_count"][k] > 0)
              & (hdr[H_TYPE] == M_SV) & (hdr[H_VIEW] >= st["view"][i]))
        old_commit = st["commit"][i]
        s2 = dict(st)
        s2["status"] = st["status"].at[i].set(NORMAL)
        s2["view"] = st["view"].at[i].set(hdr[H_VIEW])
        s2["log"] = st["log"].at[i].set(st["m_log"][k])
        s2["log_len"] = st["log_len"].at[i].set(st["m_log_len"][k])
        s2["op"] = st["op"].at[i].set(hdr[H_OP])
        s2["commit"] = st["commit"].at[i].set(hdr[H_COMMIT])
        s2["lnv"] = st["lnv"].at[i].set(hdr[H_VIEW])
        s2 = self._clear_vc(s2, i)
        s2 = self._reset_sent(s2, i)
        s2 = self._bag_discard(s2, k)
        ack = self._row(M_PREPAREOK, view=hdr[H_VIEW], op=hdr[H_OP],
                        dest=self._primary(hdr[H_VIEW], self.R), src=r)
        s2 = self._bag_send(s2, ack, pred=(old_commit < hdr[H_OP]))
        return s2, en

    def act_receive_client_request(self, st, lane):  # VSR.tla:366-394
        i = lane // self.V
        v = lane % self.V + 1          # value id
        r = i + 1
        en = (self._is_primary(st, i, r) & (st["status"][i] == NORMAL)
              & (st["aux_acked"][v - 1] == 0) & (st["ct"][i, 0, T_EXEC] == 1))
        req = st["ct"][i, 0, T_REQ] + 1
        opn = st["log_len"][i] + 1
        entry = jnp.stack([st["view"][i], jnp.asarray(v, I32),
                           jnp.asarray(1, I32), req])
        pos = jnp.clip(st["log_len"][i], 0, self.MAX_OPS - 1)
        s2 = dict(st)
        s2["log"] = st["log"].at[i, pos].set(entry)
        s2["log_len"] = st["log_len"].at[i].set(opn)
        s2["op"] = st["op"].at[i].set(opn)
        s2["ct"] = st["ct"].at[i, 0].set(jnp.stack([req, opn, jnp.asarray(0, I32)]))
        row = self._row(M_PREPARE, view=st["view"][i], op=opn,
                        commit=st["commit"][i], src=r, entry=entry)
        s2 = self._broadcast(s2, row, r)
        s2["aux_acked"] = st["aux_acked"].at[v - 1].set(1)   # v :> FALSE
        return s2, en

    def act_receive_prepare(self, st, lane):      # VSR.tla:405-428
        k = lane
        hdr = st["m_hdr"][k]
        r = hdr[H_DEST]
        i = jnp.clip(r - 1, 0, self.R - 1)
        en = ((st["m_present"][k] == 1) & (st["m_count"][k] > 0)
              & (hdr[H_TYPE] == M_PREPARE) & (st["status"][i] == NORMAL)
              & (hdr[H_VIEW] == st["view"][i])
              & (hdr[H_OP] == st["op"][i] + 1))
        entry = st["m_entry"][k]
        pos = jnp.clip(st["log_len"][i], 0, self.MAX_OPS - 1)
        s2 = dict(st)
        s2["log"] = st["log"].at[i, pos].set(entry)
        s2["log_len"] = st["log_len"].at[i].set(hdr[H_OP])
        s2["op"] = st["op"].at[i].set(hdr[H_OP])
        s2["commit"] = st["commit"].at[i].set(hdr[H_COMMIT])
        # client table: C = 1, message's client arm only (VSR.tla:414-419;
        # the other-client arm is the dead m.commit branch)
        exec_ = (hdr[H_OP] <= hdr[H_COMMIT]).astype(I32)
        s2["ct"] = st["ct"].at[i, 0].set(
            jnp.stack([entry[E_REQ], hdr[H_OP], exec_]))
        s2 = self._bag_discard(s2, k)
        ack = self._row(M_PREPAREOK, view=st["view"][i], op=hdr[H_OP],
                        dest=hdr[H_SRC], src=r)
        s2 = self._bag_send(s2, ack)
        return s2, en

    def act_receive_prepare_ok(self, st, lane):   # VSR.tla:437-447
        k = lane
        hdr = st["m_hdr"][k]
        r = hdr[H_DEST]
        i = jnp.clip(r - 1, 0, self.R - 1)
        j = jnp.clip(hdr[H_SRC] - 1, 0, self.R - 1)
        en = ((st["m_present"][k] == 1) & (st["m_count"][k] > 0)
              & (hdr[H_TYPE] == M_PREPAREOK)
              & self._is_primary(st, i, r) & (st["status"][i] == NORMAL)
              & (hdr[H_VIEW] == st["view"][i])
              & (hdr[H_OP] > st["peer_op"][i, j]))
        s2 = dict(st)
        s2["peer_op"] = st["peer_op"].at[i, j].set(hdr[H_OP])
        s2 = self._bag_discard(s2, k)
        return s2, en

    def act_execute_op(self, st, lane):           # VSR.tla:457-476
        i = lane
        r = i + 1
        opn = st["commit"][i] + 1
        committed = ((st["peer_op"][i] >= opn).sum() >= self.R // 2)
        en = (self._is_primary(st, i, r) & (st["status"][i] == NORMAL)
              & (st["commit"][i] < st["op"][i]) & committed)
        entry = st["log"][i, jnp.clip(opn - 1, 0, self.MAX_OPS - 1)]
        s2 = dict(st)
        s2["commit"] = st["commit"].at[i].set(opn)
        s2["ct"] = st["ct"].at[i, 0, T_EXEC].set(1)
        s2["aux_acked"] = st["aux_acked"].at[
            jnp.clip(entry[E_OPER] - 1, 0, self.V - 1)].set(2)  # v :> TRUE
        return s2, en

    def act_send_get_state(self, st, lane):       # VSR.tla:491-516
        k = lane // self.R
        rdest = lane % self.R + 1
        hdr = st["m_hdr"][k]
        r = hdr[H_DEST]
        i = jnp.clip(r - 1, 0, self.R - 1)
        en = ((st["m_present"][k] == 1) & (st["m_count"][k] > 0)
              & (hdr[H_TYPE] == M_PREPARE)
              & ~self._is_primary(st, i, r) & (r != rdest)
              & (st["status"][i] == NORMAL)
              & (hdr[H_VIEW] > st["view"][i])
              & (hdr[H_OP] > st["op"][i] + 1))
        trunc = jnp.minimum(st["commit"][i], st["log_len"][i])
        keep = jnp.arange(self.MAX_OPS, dtype=I32) < trunc
        s2 = dict(st)
        s2["log"] = st["log"].at[i].set(
            jnp.where(keep[:, None], st["log"][i], 0))
        s2["log_len"] = st["log_len"].at[i].set(trunc)
        s2["op"] = st["op"].at[i].set(trunc)
        s2["view"] = st["view"].at[i].set(hdr[H_VIEW])
        s2["lnv"] = st["lnv"].at[i].set(hdr[H_VIEW])
        row = self._row(M_GETSTATE, view=hdr[H_VIEW], op=trunc,
                        dest=rdest, src=r)
        s2, ok = self._bag_send_once(s2, row)
        return s2, en & ok

    def act_receive_get_state(self, st, lane):    # VSR.tla:526-543
        k = lane
        hdr = st["m_hdr"][k]
        r = hdr[H_DEST]
        i = jnp.clip(r - 1, 0, self.R - 1)
        en = ((st["m_present"][k] == 1) & (st["m_count"][k] > 0)
              & (hdr[H_TYPE] == M_GETSTATE)
              & (st["view"][i] == hdr[H_VIEW]) & (st["status"][i] == NORMAL)
              & (st["op"][i] > hdr[H_OP]))
        # log slice m.op_number+1 .. rep_op_number[r], re-based to row 0
        n = st["op"][i] - hdr[H_OP]
        idx = jnp.arange(self.MAX_OPS, dtype=I32)
        src_pos = jnp.clip(hdr[H_OP] + idx, 0, self.MAX_OPS - 1)
        rows = jnp.where((idx < n)[:, None], st["log"][i][src_pos], 0)
        reply = self._row(M_NEWSTATE, view=st["view"][i], op=st["op"][i],
                          commit=st["commit"][i], first=hdr[H_OP] + 1,
                          dest=hdr[H_SRC], src=r, log=rows,
                          log_len=jnp.clip(n, 0, self.MAX_OPS), has_log=1)
        s2 = self._bag_discard(dict(st), k)
        s2 = self._bag_send(s2, reply)
        return s2, en

    def act_receive_new_state(self, st, lane):    # VSR.tla:551-567
        k = lane
        hdr = st["m_hdr"][k]
        r = hdr[H_DEST]
        i = jnp.clip(r - 1, 0, self.R - 1)
        en = ((st["m_present"][k] == 1) & (st["m_count"][k] > 0)
              & (hdr[H_TYPE] == M_NEWSTATE)
              & (st["view"][i] == hdr[H_VIEW]) & (st["status"][i] == NORMAL)
              & (st["op"][i] == hdr[H_FIRST] - 1))
        own_n = st["op"][i]
        idx = jnp.arange(self.MAX_OPS, dtype=I32)
        from_msg = st["m_log"][k][jnp.clip(idx - own_n, 0, self.MAX_OPS - 1)]
        rows = jnp.where((idx < own_n)[:, None], st["log"][i],
                         jnp.where((idx < hdr[H_OP])[:, None], from_msg, 0))
        s2 = dict(st)
        s2["log"] = st["log"].at[i].set(rows)
        s2["log_len"] = st["log_len"].at[i].set(hdr[H_OP])
        s2["op"] = st["op"].at[i].set(hdr[H_OP])
        s2 = self._bag_discard(s2, k)
        return s2, en

    def act_restart_empty(self, st, lane):        # VSR.tla:802-837
        i = lane
        r = i + 1
        en = st["aux_restart"] < self.shape.restart_limit
        # UniqueNumber: 1 + highest x over RecoveryMsg domain entries
        is_rec = (st["m_present"] == 1) & (st["m_hdr"][:, H_TYPE] == M_RECOVERY)
        unique = jnp.max(jnp.where(is_rec, st["m_hdr"][:, H_X], 0)) + 1
        s2 = dict(st)
        s2["log"] = st["log"].at[i].set(0)
        s2["log_len"] = st["log_len"].at[i].set(0)
        s2["view"] = st["view"].at[i].set(1)
        s2["op"] = st["op"].at[i].set(0)
        s2["commit"] = st["commit"].at[i].set(0)
        s2["peer_op"] = st["peer_op"].at[i].set(0)
        empty_row = jnp.zeros((self.shape.C, 3), I32).at[:, T_EXEC].set(1)
        s2["ct"] = st["ct"].at[i].set(empty_row)
        s2 = self._clear_vc(s2, i)
        s2 = self._reset_sent(s2, i)
        s2["lnv"] = st["lnv"].at[i].set(0)
        s2 = self._clear_rec(s2, i)
        s2["status"] = st["status"].at[i].set(RECOVERING)
        s2["rec_number"] = st["rec_number"].at[i].set(unique)
        s2["aux_restart"] = st["aux_restart"] + 1
        s2 = self._broadcast(s2, self._row(M_RECOVERY, x=unique, src=r), r)
        return s2, en

    def act_receive_recovery(self, st, lane):     # VSR.tla:842-858
        k = lane
        hdr = st["m_hdr"][k]
        r = hdr[H_DEST]
        i = jnp.clip(r - 1, 0, self.R - 1)
        en = ((st["m_present"][k] == 1) & (st["m_count"][k] > 0)
              & (hdr[H_TYPE] == M_RECOVERY) & (st["status"][i] == NORMAL))
        isp = self._is_primary(st, i, r)
        reply = self._row(
            M_RECOVERYRESP, view=st["view"][i], x=hdr[H_X], dest=hdr[H_SRC],
            src=r,
            op=jnp.where(isp, st["op"][i], -1),
            commit=jnp.where(isp, st["commit"][i], -1),
            log=jnp.where(isp, st["log"][i], 0),
            log_len=jnp.where(isp, st["log_len"][i], 0),
            has_log=jnp.where(isp, 1, 0))
        s2 = self._bag_discard(dict(st), k)
        s2 = self._bag_send(s2, reply)
        return s2, en

    def act_receive_recovery_response(self, st, lane):  # VSR.tla:864-872
        k = lane
        hdr = st["m_hdr"][k]
        r = hdr[H_DEST]
        i = jnp.clip(r - 1, 0, self.R - 1)
        j = jnp.clip(hdr[H_SRC] - 1, 0, self.R - 1)
        en = ((st["m_present"][k] == 1) & (st["m_count"][k] > 0)
              & (hdr[H_TYPE] == M_RECOVERYRESP)
              & (st["rec_number"][i] == hdr[H_X])
              & (st["status"][i] == RECOVERING))
        same = ((st["rec"][i, j] == 1)
                & (st["rec_view"][i, j] == hdr[H_VIEW])
                & (st["rec_has_log"][i, j] == st["m_has_log"][k])
                & (st["rec_op"][i, j] == hdr[H_OP])
                & (st["rec_commit"][i, j] == hdr[H_COMMIT])
                & (st["rec_log_len"][i, j] == st["m_log_len"][k])
                & (st["rec_log"][i, j] == st["m_log"][k]).all())
        collide = (st["rec"][i, j] == 1) & ~same
        s2 = dict(st)
        s2["rec"] = st["rec"].at[i, j].set(1)
        s2["rec_view"] = st["rec_view"].at[i, j].set(hdr[H_VIEW])
        s2["rec_has_log"] = st["rec_has_log"].at[i, j].set(st["m_has_log"][k])
        s2["rec_log"] = st["rec_log"].at[i, j].set(st["m_log"][k])
        s2["rec_log_len"] = st["rec_log_len"].at[i, j].set(st["m_log_len"][k])
        s2["rec_op"] = st["rec_op"].at[i, j].set(hdr[H_OP])
        s2["rec_commit"] = st["rec_commit"].at[i, j].set(hdr[H_COMMIT])
        s2["err"] = st["err"] | jnp.where(collide & en, ERR_REC_OVERFLOW, 0)
        s2 = self._bag_discard(s2, k)
        return s2, en

    def act_complete_recovery(self, st, lane):    # VSR.tla:878-894
        i = lane
        cand = (st["rec"][i] == 1) & (st["rec_has_log"][i] == 1)
        en = ((st["status"][i] == RECOVERING)
              & ((st["rec"][i] == 1).sum() > self.R // 2)
              & cand.any())
        # CHOOSE m : m.log # Nil — value_key-least response record:
        # (commit_number, dest=, log, op_number, source, type=, view, x=)
        logk = self._log_sort_key(st["rec_log"][i])
        src_ids = jnp.arange(1, self.R + 1, dtype=I32)
        keys = jnp.concatenate(
            [st["rec_commit"][i][:, None], logk, st["rec_op"][i][:, None],
             src_ids[:, None], st["rec_view"][i][:, None]], axis=1)
        keys = jnp.where(cand[:, None], keys, INF)
        best_j = jnp.asarray(0, I32)
        best_key = keys[0]
        for j in range(1, self.R):
            less = _lex_less(keys[j], best_key)
            best_key = jnp.where(less, keys[j], best_key)
            best_j = jnp.where(less, j, best_j)
        s2 = dict(st)
        s2["status"] = st["status"].at[i].set(NORMAL)
        s2["view"] = st["view"].at[i].set(st["rec_view"][i, best_j])
        s2["lnv"] = st["lnv"].at[i].set(st["rec_view"][i, best_j])
        s2["log"] = st["log"].at[i].set(st["rec_log"][i, best_j])
        s2["log_len"] = st["log_len"].at[i].set(st["rec_log_len"][i, best_j])
        s2["op"] = st["op"].at[i].set(st["rec_op"][i, best_j])
        s2["commit"] = st["commit"].at[i].set(st["rec_commit"][i, best_j])
        s2 = self._clear_rec(s2, i)
        return s2, en

    # ==================================================================
    # guard-only evaluation (the cheap pass of the two-phase expand)
    #
    # Each guard replicates exactly the `en` conjunction of its action —
    # reading a handful of scalars/rows — so the engine can evaluate
    # enabledness over the full [T, n_lanes] lane space at ~1% of the
    # cost of building successors, then expand only the enabled lanes.
    # Kept in lockstep with the action bodies; `test_guard_fns_match`
    # holds them to the actions differentially.
    # ==================================================================
    def _recv_guard(self, st, k, mtype):
        return ((st["m_present"][k] == 1) & (st["m_count"][k] > 0)
                & (st["m_hdr"][k, H_TYPE] == mtype))

    def _dest_i(self, st, k):
        return jnp.clip(st["m_hdr"][k, H_DEST] - 1, 0, self.R - 1)

    def guard_timer_send_svc(self, st, lane):
        i = lane
        return ((st["aux_svc"] < self.shape.timer_limit)
                & ~self._is_primary(st, i, i + 1))

    def guard_receive_higher_svc(self, st, k):
        i = self._dest_i(st, k)
        return (self._recv_guard(st, k, M_SVC)
                & (st["m_hdr"][k, H_VIEW] > st["view"][i]))

    def guard_receive_matching_svc(self, st, k):
        i = self._dest_i(st, k)
        return (self._recv_guard(st, k, M_SVC)
                & (st["m_hdr"][k, H_VIEW] == st["view"][i])
                & (st["status"][i] == VIEWCHANGE))

    def guard_send_dvc(self, st, lane):
        i = lane
        return ((st["status"][i] == VIEWCHANGE) & (st["sent_dvc"][i] == 0)
                & (st["svc"][i].sum() >= self.R // 2))

    def guard_receive_higher_dvc(self, st, k):
        i = self._dest_i(st, k)
        return (self._recv_guard(st, k, M_DVC)
                & (st["m_hdr"][k, H_VIEW] > st["view"][i]))

    def guard_receive_matching_dvc(self, st, k):
        i = self._dest_i(st, k)
        return (self._recv_guard(st, k, M_DVC)
                & (st["m_hdr"][k, H_VIEW] == st["view"][i]))

    def guard_send_sv(self, st, lane):
        i = lane
        return ((st["status"][i] == VIEWCHANGE) & (st["sent_sv"][i] == 0)
                & ((st["dvc"][i] == 1).sum() >= self.R // 2 + 1))

    def guard_receive_sv(self, st, k):
        i = self._dest_i(st, k)
        return (self._recv_guard(st, k, M_SV)
                & (st["m_hdr"][k, H_VIEW] >= st["view"][i]))

    def guard_receive_client_request(self, st, lane):
        i = lane // self.V
        v = lane % self.V + 1
        return (self._is_primary(st, i, i + 1) & (st["status"][i] == NORMAL)
                & (st["aux_acked"][v - 1] == 0)
                & (st["ct"][i, 0, T_EXEC] == 1))

    def guard_receive_prepare(self, st, k):
        i = self._dest_i(st, k)
        return (self._recv_guard(st, k, M_PREPARE)
                & (st["status"][i] == NORMAL)
                & (st["m_hdr"][k, H_VIEW] == st["view"][i])
                & (st["m_hdr"][k, H_OP] == st["op"][i] + 1))

    def guard_receive_prepare_ok(self, st, k):
        i = self._dest_i(st, k)
        j = jnp.clip(st["m_hdr"][k, H_SRC] - 1, 0, self.R - 1)
        return (self._recv_guard(st, k, M_PREPAREOK)
                & self._is_primary(st, i, st["m_hdr"][k, H_DEST])
                & (st["status"][i] == NORMAL)
                & (st["m_hdr"][k, H_VIEW] == st["view"][i])
                & (st["m_hdr"][k, H_OP] > st["peer_op"][i, j]))

    def guard_execute_op(self, st, lane):
        i = lane
        opn = st["commit"][i] + 1
        committed = (st["peer_op"][i] >= opn).sum() >= self.R // 2
        return (self._is_primary(st, i, i + 1) & (st["status"][i] == NORMAL)
                & (st["commit"][i] < st["op"][i]) & committed)

    def guard_send_get_state(self, st, lane):
        k = lane // self.R
        rdest = lane % self.R + 1
        hdr = st["m_hdr"][k]
        r = hdr[H_DEST]
        i = jnp.clip(r - 1, 0, self.R - 1)
        en = (self._recv_guard(st, k, M_PREPARE)
              & ~self._is_primary(st, i, r) & (r != rdest)
              & (st["status"][i] == NORMAL)
              & (hdr[H_VIEW] > st["view"][i])
              & (hdr[H_OP] > st["op"][i] + 1))
        # SendOnce: the GetState record must not already be in the bag
        # (VSR.tla:250-252); the bag is unchanged by the truncation, so
        # the membership test can run against the parent state
        trunc = jnp.minimum(st["commit"][i], st["log_len"][i])
        row = self._row(M_GETSTATE, view=hdr[H_VIEW], op=trunc,
                        dest=rdest, src=r)
        return en & ~self._row_eq(st, row).any()

    def guard_receive_get_state(self, st, k):
        i = self._dest_i(st, k)
        return (self._recv_guard(st, k, M_GETSTATE)
                & (st["view"][i] == st["m_hdr"][k, H_VIEW])
                & (st["status"][i] == NORMAL)
                & (st["op"][i] > st["m_hdr"][k, H_OP]))

    def guard_receive_new_state(self, st, k):
        i = self._dest_i(st, k)
        return (self._recv_guard(st, k, M_NEWSTATE)
                & (st["view"][i] == st["m_hdr"][k, H_VIEW])
                & (st["status"][i] == NORMAL)
                & (st["op"][i] == st["m_hdr"][k, H_FIRST] - 1))

    def guard_restart_empty(self, st, lane):
        del lane
        return st["aux_restart"] < self.shape.restart_limit

    def guard_receive_recovery(self, st, k):
        i = self._dest_i(st, k)
        return (self._recv_guard(st, k, M_RECOVERY)
                & (st["status"][i] == NORMAL))

    def guard_receive_recovery_response(self, st, k):
        i = self._dest_i(st, k)
        return (self._recv_guard(st, k, M_RECOVERYRESP)
                & (st["rec_number"][i] == st["m_hdr"][k, H_X])
                & (st["status"][i] == RECOVERING))

    def guard_complete_recovery(self, st, lane):
        i = lane
        cand = (st["rec"][i] == 1) & (st["rec_has_log"][i] == 1)
        return ((st["status"][i] == RECOVERING)
                & ((st["rec"][i] == 1).sum() > self.R // 2)
                & cand.any())

    def _guard_fns(self):
        return [
            self.guard_timer_send_svc, self.guard_receive_higher_svc,
            self.guard_receive_matching_svc, self.guard_send_dvc,
            self.guard_receive_higher_dvc, self.guard_receive_matching_dvc,
            self.guard_send_sv, self.guard_receive_sv,
            self.guard_receive_client_request, self.guard_receive_prepare,
            self.guard_receive_prepare_ok, self.guard_execute_op,
            self.guard_send_get_state, self.guard_receive_get_state,
            self.guard_receive_new_state, self.guard_restart_empty,
            self.guard_receive_recovery, self.guard_receive_recovery_response,
            self.guard_complete_recovery,
        ]

    # ==================================================================
    # full Next: all lanes of all actions, stacked
    # ==================================================================
    def _action_fns(self):
        return [
            self.act_timer_send_svc, self.act_receive_higher_svc,
            self.act_receive_matching_svc, self.act_send_dvc,
            self.act_receive_higher_dvc, self.act_receive_matching_dvc,
            self.act_send_sv, self.act_receive_sv,
            self.act_receive_client_request, self.act_receive_prepare,
            self.act_receive_prepare_ok, self.act_execute_op,
            self.act_send_get_state, self.act_receive_get_state,
            self.act_receive_new_state, self.act_restart_empty,
            self.act_receive_recovery, self.act_receive_recovery_response,
            self.act_complete_recovery,
        ]

    def lane_replica(self, name, st, lane):
        """The one replica a lane's action mutates (every VSR action
        updates through EXCEPT ![r] on a single replica)."""
        if name in ("TimerSendSVC", "SendDVC", "SendSV", "ExecuteOp",
                    "RestartEmpty", "CompleteRecovery"):
            return lane
        if name == "ReceiveClientRequest":
            return lane // self.V
        if name == "SendGetState":
            k = lane // self.R
        else:
            k = lane
        return jnp.clip(st["m_hdr"][k, H_DEST] - 1, 0, self.R - 1)

    def seed_touch(self, st):
        """Add the incremental-fingerprint scratch keys."""
        st = dict(st)
        st["_ts"] = jnp.full((self.R + 1,), -1, I32)
        st["_tn"] = jnp.asarray(0, I32)
        return st

    def step_all(self, st):
        """One state -> all lane successors.

        Returns (succs, enabled): succs is the state pytree with a leading
        lane axis [n_lanes, ...]; enabled is [n_lanes] bool.  Disabled
        lanes contain garbage and must be masked by the caller.
        """
        st = {k: jnp.asarray(v, I32) for k, v in st.items()}
        parts, ens = [], []
        for name, fn in zip(ACTION_NAMES, self._action_fns()):
            lanes = jnp.arange(self._lane_count(name), dtype=I32)
            succ, en = jax.vmap(fn, in_axes=(None, 0))(st, lanes)
            parts.append(succ)
            ens.append(en)
        succs = {k: jnp.concatenate([p[k] for p in parts], axis=0)
                 for k in st if not k.startswith("_")}
        return succs, jnp.concatenate(ens)

    # ==================================================================
    # fingerprinting: VIEW projection (excludes aux_vars, VSR.tla:149-150)
    # -> symmetry-least 4x32-bit hash (VSR.tla:151)
    # ==================================================================
    @staticmethod
    def _mix32(x):
        x = jnp.asarray(x, jnp.uint32)
        x = x ^ (x >> 16)
        x = x * jnp.uint32(0x85EBCA6B)
        x = x ^ (x >> 13)
        x = x * jnp.uint32(0xC2B2AE35)
        x = x ^ (x >> 16)
        return x

    def _permuted(self, st, perm):
        """Remap value ids through one symmetry permutation ([V+1] table,
        0 -> 0).  Value ids live in the operation column of every log-
        entry row (rep/dvc/rec logs, message entry and payload logs)."""
        st = dict(st)
        for k in ("log", "dvc_log", "rec_log", "m_log"):
            st[k] = st[k].at[..., E_OPER].set(perm[st[k][..., E_OPER]])
        st["m_entry"] = st["m_entry"].at[..., E_OPER].set(
            perm[st["m_entry"][..., E_OPER]])
        return st

    def _rep_rows(self, st):
        """[R, n_rep] uint32 content rows, one per replica: the replica
        id followed by every per-replica state slice."""
        R = self.R
        cols = [jnp.arange(R, dtype=jnp.uint32)[:, None]]
        for k in REP_KEYS:
            v = jnp.asarray(st[k], jnp.uint32)
            cols.append(v.reshape(R, -1))
        return jnp.concatenate(cols, axis=1)

    def _rep_hashes(self, st):
        """[R, 4] per-replica row hashes (position-keyed by replica id)."""
        rows = self._rep_rows(st)
        return self._mix32((rows[:, None, :] * self._k_rep[None]).sum(axis=2)
                           + self._seeds[None, :])

    def _slot_rows(self, st):
        """[M, n_msg] uint32 content rows, one per message slot (slot
        index NOT injected: the bag hash is slot-order-invariant)."""
        return jnp.concatenate(
            [jnp.asarray(st["m_hdr"], jnp.uint32),
             jnp.asarray(st["m_entry"], jnp.uint32),
             jnp.asarray(st["m_log"], jnp.uint32).reshape(self.M, -1),
             jnp.asarray(st["m_log_len"], jnp.uint32)[:, None],
             jnp.asarray(st["m_has_log"], jnp.uint32)[:, None],
             jnp.asarray(st["m_count"], jnp.uint32)[:, None]], axis=1)

    def _slot_hashes(self, st):
        rows = self._slot_rows(st)
        return self._mix32((rows[:, None, :] * self._k_msg[None]).sum(axis=2)
                           + self._seeds[None, :])       # [M, 4]

    def _fp_one(self, st, perm):
        st = self._permuted(st, perm)
        h_rep = self._rep_hashes(st).sum(axis=0)
        pres = jnp.asarray(st["m_present"], jnp.uint32)[:, None]
        h_msg = (self._slot_hashes(st) * pres).sum(axis=0)
        return self._mix32(self._mix32(h_rep + h_msg) + self._seeds)

    @staticmethod
    def _lex_min4(fps):
        """[P, 4] -> [4]: lexicographic least row."""
        best = fps[0]
        for p in range(1, fps.shape[0]):
            a, b = fps[p], best
            less = ((a[0] < b[0])
                    | ((a[0] == b[0]) & (a[1] < b[1]))
                    | ((a[0] == b[0]) & (a[1] == b[1]) & (a[2] < b[2]))
                    | ((a[0] == b[0]) & (a[1] == b[1]) & (a[2] == b[2])
                       & (a[3] < b[3])))
            best = jnp.where(less, a, best)
        return best

    def fingerprint(self, st):
        """[4] uint32 canonical fingerprint: least over symmetry perms."""
        st = {k: jnp.asarray(v) for k, v in st.items()}
        fps = jax.vmap(lambda p: self._fp_one(st, p))(jnp.asarray(self.perms))
        return self._lex_min4(fps)

    # -- incremental fingerprinting ------------------------------------
    # Every action mutates exactly ONE replica row (VSR.tla actions all
    # update through EXCEPT ![r]) plus at most R+1 message slots (a
    # discard + an R-1-destination broadcast).  The kernel records the
    # touched replica in succ["_ri"] and touched slots in succ["_ts"]
    # (engine strips them), and the expand pass reconstitutes the
    # successor fingerprint from the parent's per-row hash sums.

    def parent_parts(self, st):
        """Per-permutation hash parts of a parent state:
        rep [P, R, 4], slot [P, M, 4], total [P, 4] (pre-mix sums)."""
        def parts_one(perm):
            stp = self._permuted(st, perm)
            rep = self._rep_hashes(stp)
            slot = self._slot_hashes(stp)
            pres = jnp.asarray(stp["m_present"], jnp.uint32)[:, None]
            total = rep.sum(axis=0) + (slot * pres).sum(axis=0)
            return rep, slot, total
        return jax.vmap(parts_one)(jnp.asarray(self.perms))

    def _perm_entry_cols(self, rows, perm):
        """Apply a value permutation to the oper column of [..., NENT]
        log-entry rows."""
        return rows.at[..., E_OPER].set(perm[rows[..., E_OPER]])

    def _rep_row_one(self, st, i, perm):
        """[n_rep] content row of replica i with `perm` applied."""
        cols = [jnp.asarray(i, jnp.uint32)[None]]
        for k in REP_KEYS:
            v = st[k][i]
            if k in ("log", "dvc_log", "rec_log"):
                v = self._perm_entry_cols(v, perm)
            cols.append(jnp.asarray(v, jnp.uint32).reshape(-1))
        return jnp.concatenate(cols)

    def _slot_row_one(self, st, m, perm):
        """[n_msg] content row of message slot m with `perm` applied."""
        return jnp.concatenate([
            jnp.asarray(st["m_hdr"][m], jnp.uint32),
            jnp.asarray(self._perm_entry_cols(st["m_entry"][m], perm),
                        jnp.uint32),
            jnp.asarray(self._perm_entry_cols(st["m_log"][m], perm),
                        jnp.uint32).reshape(-1),
            jnp.asarray(st["m_log_len"][m], jnp.uint32)[None],
            jnp.asarray(st["m_has_log"][m], jnp.uint32)[None],
            jnp.asarray(st["m_count"][m], jnp.uint32)[None]])

    def fingerprint_incremental(self, succ, ri, parts, parent):
        """Successor fingerprint in O(touched rows) from parent parts.

        `ri` is the one replica the lane's action mutated
        (lane_replica); succ carries "_ts" ([R+1] touched slot indices,
        -1 padded, recorded by the bag primitives).  Produces values
        identical to `fingerprint(succ)`."""
        rep_h, slot_h, total = parts
        i = ri
        ts = succ["_ts"]
        perms = jnp.asarray(self.perms)
        p_pres = jnp.asarray(parent["m_present"], jnp.uint32)
        s_pres = jnp.asarray(succ["m_present"], jnp.uint32)

        def fp_p(p):
            perm = perms[p]
            d = total[p] - rep_h[p, i]
            row = self._rep_row_one(succ, i, perm)
            d = d + self._mix32((row[None, :] * self._k_rep).sum(axis=1)
                                + self._seeds)
            for t in range(ts.shape[0]):
                s = ts[t]
                ok = s >= 0
                sc = jnp.clip(s, 0, self.M - 1)
                d = d - jnp.where(ok, slot_h[p, sc] * p_pres[sc], 0)
                new_row = self._slot_row_one(succ, sc, perm)
                new_h = self._mix32(
                    (new_row[None, :] * self._k_msg).sum(axis=1)
                    + self._seeds)
                d = d + jnp.where(ok, new_h * s_pres[sc], 0)
            return self._mix32(self._mix32(d) + self._seeds)

        fps = jax.vmap(fp_p)(jnp.arange(self.perms.shape[0]))
        return self._lex_min4(fps)

    # ==================================================================
    # invariants (VSR.tla:926-952), vectorized
    # ==================================================================
    def _replica_has_op(self, st):
        """[R, V] bool: ReplicaHasOp(r, v) (VSR.tla:933-935)."""
        opers = st["log"][..., E_OPER]                   # [R, MAX_OPS]
        v_ids = jnp.arange(1, self.V + 1, dtype=I32)
        return (opers[:, :, None] == v_ids[None, None, :]).any(axis=1)

    def inv_acknowledged_write_not_lost(self, st):
        acked = st["aux_acked"] == 2                     # v |-> TRUE
        has = self._replica_has_op(st).any(axis=0)       # [V]
        return (~acked | has).all()

    def inv_acknowledged_writes_exist_on_majority(self, st):
        acked = st["aux_acked"] == 2
        n_has = self._replica_has_op(st).sum(axis=0)
        return (~acked | (n_has >= self.R // 2 + 1)).all()

    def inv_no_log_divergence(self, st):
        # Faithful to VSR.tla:926-931: the body compares rep_log[r1] with
        # itself, so the invariant is vacuously true (SURVEY.md §2.7.2).
        return jnp.asarray(True)

    def inv_test(self, st):
        return jnp.asarray(True)

    def pred_all_replicas_same_view(self, st):
        # AllReplicasMoveToSameView (VSR.tla:958-962): a state predicate
        # used by the liveness property []<>P; not an invariant in the
        # shipped cfg, but checkable as one
        return ((st["view"] == st["view"][0]).all()
                & (st["status"] == NORMAL).all())

    def hunt_score(self, st):
        """Defect-proximity score for guided simulation (importance
        splitting): how close is this state to losing an acknowledged
        write (AcknowledgedWriteNotLost, VSR.tla:945-950)?

        0 while nothing is acked; afterwards a shaped sum of milestones
        along the truncation path (VSR.tla:64-86):
          +2 per replica missing the worst acked value (reaches +2R at
             the violation),
          +1 if some Normal replica lags the max view while holding an
             acked value (the SendGetState truncation candidate),
          +1 if a GetState record is in the bag (truncation fired —
             VSR.tla:496-516 truncates on SEND).
        The intermediate milestones give the splitter gradient through
        the view-change phase, where the missing-count alone is flat."""
        acked = st["aux_acked"] == 2                      # [V]
        has = self._replica_has_op(st)                    # [R, V]
        missing = (~has).sum(axis=0)                      # [V]
        worst = jnp.max(jnp.where(acked, missing, -1))
        vmax = st["view"].max()
        has_acked_val = (has & acked[None, :]).any(axis=1)   # [R]
        lag = ((st["status"] == NORMAL) & (st["view"] < vmax)
               & has_acked_val).any()
        gs = ((st["m_present"] == 1)
              & (st["m_hdr"][:, H_TYPE] == M_GETSTATE)).any()
        score = 1 + 2 * worst + lag.astype(I32) + gs.astype(I32)
        return jnp.where(acked.any(), score, 0).astype(I32)

    INVARIANT_FNS = {
        "AcknowledgedWriteNotLost": "inv_acknowledged_write_not_lost",
        "AcknowledgedWritesExistOnMajority":
            "inv_acknowledged_writes_exist_on_majority",
        "NoLogDivergence": "inv_no_log_divergence",
        "TestInv": "inv_test",
        "AllReplicasMoveToSameView": "pred_all_replicas_same_view",
    }

    def invariant_fn(self, names):
        """Build st -> ok_bool over the named invariants (cfg INVARIANT
        block).  Raises KeyError for invariants with no device kernel."""
        fns = [getattr(self, self.INVARIANT_FNS[n]) for n in names]

        def check(st):
            ok = jnp.asarray(True)
            for f in fns:
                ok = ok & f(st)
            return ok
        return check
