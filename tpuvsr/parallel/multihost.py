"""Multi-host (DCN-tier) support for the sharded BFS engine.

The single-host story shards the frontier + FPSet over a device mesh
and exchanges states with one in-level ``all_to_all`` over ICI
(parallel/sharded_bfs.py).  Scaling past one host keeps the same SPMD
program — the mesh simply spans processes, and XLA routes the mesh
collectives over the cross-host fabric (DCN; gloo/TCP on the CPU
backend used for testing, per-host TPU slices over real DCN in
production).  TLC's analog is its distributed mode (unused by the
reference, which prescribes vertical scale — README:20); this tier is
what lets the flagship defect-config BFS outgrow one host's HBM.

What multi-process changes for the HOST program (and what this module
provides):

* every process runs the same driver loop (SPMD discipline) — control
  decisions must be taken on values all processes agree on;
* a globally-sharded ``jax.Array`` is only partially addressable from
  any one process, so ``np.asarray(global_arr)`` raises — host pulls
  must first reshard to fully-replicated (``replicate_to_host``);
* host->device scatters of globally-identical host data must go
  through ``jax.make_array_from_callback`` so each process only
  touches its addressable shards (``put_sharded`` / ``put_replicated``).

``jax.distributed`` is initialized from environment variables
(TPUVSR_MH_COORD/NPROC/PID) so the same worker entrypoint serves any
process count, and ``launch()`` spawns a local multi-process pack with
the CPU/gloo backend — the test harness for the DCN tier on a machine
with no second host.
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np

ENV_COORD = "TPUVSR_MH_COORD"
ENV_NPROC = "TPUVSR_MH_NPROC"
ENV_PID = "TPUVSR_MH_PID"


def init_from_env():
    """Initialize jax.distributed when the multi-host env vars are set.
    Must run before the backend is touched.  Returns the process id
    (0 when not multi-process)."""
    coord = os.environ.get(ENV_COORD)
    if not coord:
        return 0
    nproc = int(os.environ[ENV_NPROC])
    pid = int(os.environ[ENV_PID])
    import jax
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nproc, process_id=pid)
    return pid


def is_multiprocess():
    import jax
    return jax.process_count() > 1


def put_sharded(arr, sharding):
    """Host ndarray (identical on every process) -> global array with
    the given sharding; each process populates only its shards."""
    import jax
    arr = np.asarray(arr)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def make_replicator(mesh):
    """Returns pull(global_arr) -> host ndarray of the FULL value,
    valid on every process: reshards to fully-replicated (a broadcast
    over the mesh fabric — DCN across hosts) and reads the now locally
    addressable copy.  Single-process, np.asarray is already enough and
    the collective is skipped."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    if not is_multiprocess():
        return lambda garr: np.asarray(garr)
    rep = NamedSharding(mesh, P())
    gather = jax.jit(lambda x: x, out_shardings=rep)

    def pull(garr):
        return np.asarray(gather(garr))

    return pull


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch(worker_argv, nproc=2, local_devices=4, port=None,
           timeout=1800, extra_env=None):
    """Spawn `nproc` local worker processes forming one multi-process
    JAX job over the CPU/gloo backend (the DCN-tier test harness).
    Each worker runs `worker_argv` with the TPUVSR_MH_* env set; the
    worker is expected to call init_from_env() first thing.  Returns
    (returncodes, outputs).

    `port=None` picks a free coordinator port (a fixed default could
    collide with a concurrent multihost job and hang both until
    timeout); `timeout` is one shared deadline across the whole pack,
    not per-process (ADVICE r4)."""
    if port is None:
        port = _free_port()
    import time as _time
    deadline = _time.monotonic() + timeout
    procs = []
    for pid in range(nproc):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "JAX_NUM_CPU_DEVICES": str(local_devices),
            "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
            ENV_COORD: f"127.0.0.1:{port}",
            ENV_NPROC: str(nproc),
            ENV_PID: str(pid),
        })
        env.pop("PALLAS_AXON_POOL_IPS", None)
        # the baked-in XLA_FLAGS force a host device count; strip so
        # JAX_NUM_CPU_DEVICES is authoritative per process
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" in flags:
            env["XLA_FLAGS"] = " ".join(
                t for t in flags.split()
                if "xla_force_host_platform_device_count" not in t)
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            worker_argv, env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    rcs, outs = [], []
    for p in procs:
        try:
            out, _ = p.communicate(
                timeout=max(1.0, deadline - _time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out = (out or "") + "\n[TIMEOUT]"
        rcs.append(p.returncode)
        outs.append(out or "")
    return rcs, outs
