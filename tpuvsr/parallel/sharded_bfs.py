"""Multi-chip BFS expansion: frontier + fingerprint set sharded over a
device mesh (SURVEY.md §5 "distributed communication backend";
BASELINE.json configs[4]).

Design (the TPU answer to TLC's shared-memory worker pool):

* the frontier is data-parallel over a 1-D mesh axis ``d`` — each device
  expands its own tile of states with the vmapped transition kernel;
* the fingerprint space is ownership-partitioned: fingerprint ``fp``
  belongs to device ``route(fp) % n_devices``;
* after local expansion + fingerprinting, successors' fingerprints are
  bucketed by owner and exchanged with a single ``all_to_all`` over ICI;
* each device dedups and inserts the fingerprints it owns into its local
  HBM FPSet shard (engine/fpset.py), so the global visited set is the
  disjoint union of shards and no two devices ever race on a slot.

The exchange uses fixed-capacity buckets (XLA needs static shapes); a
bucket overflow is reported so the host can re-run the tile in halves.
Fresh successor *states* stay on the producing device in this step; the
ownership exchange moves only 16-byte fingerprints + lane indices, which
is what makes the collective cheap relative to HBM traffic.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.fpset import dedup_batch, insert_core

U32 = jnp.uint32


def route(fps):
    """Owner of each fingerprint ([.., 4] uint32 -> [..] uint32).  Uses a
    mixed word decorrelated from both the FPSet claim tag (word 0) and
    the slot hash so shard choice doesn't bias probe chains."""
    return (fps[..., 1] * jnp.uint32(0x9E3779B9)) ^ (fps[..., 3] >> 7)


def make_sharded_expand(kern, inv_fn, mesh: Mesh, axis: str = "d",
                        bucket_cap: int = None):
    """Build the jitted one-level expand step over `mesh`.

    Returns step(tables, frontier, valid) ->
        (tables, fresh_local, owned_fps, n_fresh, viol_any, err_any, ovf)
    where every output is sharded over `axis`:
      - fresh_local [n_dev tiles..]: per-device mask over the *local*
        lane space of successors that are globally fresh AND owned
        locally is not returned (states stay put) — instead
        `fresh_keep` marks local lanes accepted by their owners.
    """
    n_dev = mesh.shape[axis]
    L = kern.n_lanes

    def step_shard(tables, tile, valid):
        # tables arrive with the sharded leading axis of size 1:
        # {"slots": [1, cap, 5]}
        # tile:   state pytree [B_local, ...];  valid: [B_local]
        tables = {k: v[0] for k, v in tables.items()}
        B = valid.shape[0]
        succs, en = jax.vmap(kern.step_all)(tile)
        en = en & valid[:, None]
        flat = {k: v.reshape((B * L,) + v.shape[2:]) for k, v in succs.items()}
        en = en.reshape(-1)
        fps = jax.vmap(kern.fingerprint)(flat)
        inv_ok = jax.vmap(inv_fn)(flat)
        viol_any = (en & ~inv_ok).any()
        err_any = (en & (flat["err"] != 0)).any()

        # local pre-dedup shrinks the exchange
        perm, cand = dedup_batch(fps, en)
        fps_s = fps[perm]
        owner = (route(fps_s) % jnp.uint32(n_dev)).astype(jnp.int32)

        cap = bucket_cap or max(64, (B * L) // max(1, n_dev // 2))
        bucket = jnp.zeros((n_dev, cap, 4), U32)
        sent_mask = jnp.zeros((n_dev, cap), bool)
        bsrc = jnp.zeros((n_dev, cap), jnp.int32)      # index into fps_s
        ovf = jnp.asarray(False)
        for d in range(n_dev):
            m = cand & (owner == d)
            pos = jnp.cumsum(m) - 1
            ovf = ovf | (pos[-1] + 1 > cap) & m.any()
            idx = jnp.where(m & (pos < cap), pos, cap)  # cap row = dropped
            bucket = bucket.at[d, idx].set(fps_s, mode="drop")
            sent_mask = sent_mask.at[d, idx].set(m, mode="drop")
            bsrc = bsrc.at[d, idx].set(jnp.arange(B * L, dtype=jnp.int32),
                                       mode="drop")
        # exchange: row j of the result comes from device j
        inc_bucket = jax.lax.all_to_all(bucket, axis, 0, 0, tiled=False)
        inc_maskd = jax.lax.all_to_all(sent_mask, axis, 0, 0, tiled=False)

        # dedup + insert what I own (across the n_dev incoming chunks)
        inc_fps = inc_bucket.reshape(n_dev * cap, 4)
        inc_mask = inc_maskd.reshape(n_dev * cap)
        perm2, cand2 = dedup_batch(inc_fps, inc_mask)
        tables, fresh2, probe_ovf = insert_core(
            tables, inc_fps[perm2], cand2)
        # verdicts back to producers: un-permute, un-exchange
        verdict = jnp.zeros((n_dev * cap,), bool).at[perm2].set(fresh2)
        verdict = jax.lax.all_to_all(
            verdict.reshape(n_dev, cap), axis, 0, 0, tiled=False)
        # map bucket rows back to local sorted-lane indices; row i of the
        # returned verdict is device i's decision about the chunk *I*
        # sent it, so it pairs with my sent_mask/bsrc rows
        fresh_keep_s = jnp.zeros((B * L,), bool)
        for d in range(n_dev):
            fresh_keep_s = fresh_keep_s.at[bsrc[d]].max(
                verdict[d] & sent_mask[d])
        # un-sort to the original lane order
        fresh_keep = jnp.zeros((B * L,), bool).at[perm].set(fresh_keep_s)
        n_fresh = fresh_keep.sum()[None]    # [1] per device -> [n_dev]
        # global any-reduction for the diagnostics so every device (and
        # the replicated outputs) agree
        def par_any(x):
            return jax.lax.psum(x.astype(jnp.int32), axis) > 0
        tables = {k: v[None] for k, v in tables.items()}
        return (tables, flat, fps, fresh_keep, n_fresh, par_any(viol_any),
                par_any(err_any), par_any(ovf | probe_ovf))

    spec_d = P(axis)
    spec_tab = P(axis)     # each device holds its own shard row
    step = jax.jit(jax.shard_map(
        step_shard, mesh=mesh,
        in_specs=(spec_tab, spec_d, spec_d),
        out_specs=(spec_tab, spec_d, spec_d, spec_d, spec_d, P(), P(), P()),
        check_vma=False),
        donate_argnums=(0,))
    return step


def make_sharded_tables(mesh, axis, capacity_per_device):
    """Global FPSet: one independent shard per device, stacked on the
    leading (sharded) axis."""
    n = mesh.shape[axis]
    tabs = {"slots": jnp.zeros((n, capacity_per_device, 5), U32)}
    sh = NamedSharding(mesh, P(axis))
    return jax.device_put(tabs, sh)
