"""Multi-chip BFS expansion: frontier + fingerprint set sharded over a
device mesh (SURVEY.md §5 "distributed communication backend";
BASELINE.json configs[4]).

Design (the TPU answer to TLC's shared-memory worker pool):

* the frontier is data-parallel over a 1-D mesh axis ``d`` — each device
  expands its own tile of states with the vmapped transition kernel;
* the fingerprint space is ownership-partitioned: fingerprint ``fp``
  belongs to device ``route(fp) % n_devices``;
* after local expansion + fingerprinting, successors' fingerprints are
  bucketed by owner and exchanged with a single ``all_to_all`` over ICI;
* each device dedups and inserts the fingerprints it owns into its local
  HBM FPSet shard (engine/fpset.py), so the global visited set is the
  disjoint union of shards and no two devices ever race on a slot.

The exchange uses fixed-capacity buckets (XLA needs static shapes); a
bucket overflow pauses the level so the host can grow the bucket and
re-enter.  The exchange ships whole dense states (plus 16-byte
fingerprint and 12-byte trace meta) to their owner in ONE all_to_all —
chosen over a fps-only + verdict-round-trip design because owner-side
state residence is what keeps the frontier hash-balanced and the next
level's expansion collective-free; the measured cost is reported per
run as ``CheckResult.exchange`` (useful vs wire bytes — the wire moves
full ``D x bucket_cap`` buckets per tile regardless of occupancy).

Because wire volume is cap-bound, the bucket capacity is OCCUPANCY-
CALIBRATED by default (``bucket_cap=None``): start at a small cap and
let the existing overflow-pause-grow protocol converge it to the
run's real high-water bucket occupancy — r4 shipped 24x more bytes
than it used purely from a worst-case-sized static cap
(scripts/multihost.json; VERDICT r4 weak item 8).  Pass an explicit
``bucket_cap`` to pin it (pre-calibrated runs skip the growth
recompiles).  A fps-first exchange that ships only accepted states
would additionally cut the duplicate fraction at the price of a second
collective + owner-side re-materialization; revisit if ICI (not HBM)
ever profiles as the bottleneck.
"""

from __future__ import annotations

import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.device_bfs import _align8
from ..engine.fpset import dedup_batch, insert_core
from ..obs import closes_observer
from ..resilience.faults import InjectedExchangeDrop, fault_point
from ..resilience.supervisor import Preempted, preempt_signal
from .multihost import make_replicator, put_sharded

U32 = jnp.uint32


def _shard_map(f, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions.  The rep/vma-check kwarg was
    renamed (check_rep -> check_vma) independently of the API's
    promotion out of jax.experimental, so discriminate on the actual
    signature, not on where the function lives."""
    import inspect
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    params = inspect.signature(sm).parameters
    knob = ("check_vma" if "check_vma" in params else
            "check_rep" if "check_rep" in params else None)
    kw = {knob: False} if knob else {}
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              **kw)


def route(fps):
    """Owner of each fingerprint ([.., 4] uint32 -> [..] uint32).  Uses a
    mixed word decorrelated from both the FPSet claim tag (word 0) and
    the slot hash so shard choice doesn't bias probe chains."""
    return (fps[..., 1] * jnp.uint32(0x9E3779B9)) ^ (fps[..., 3] >> 7)


def make_sharded_tables(mesh, axis, capacity_per_device):
    """Global FPSet: one independent shard per device, stacked on the
    leading (sharded) axis."""
    n = mesh.shape[axis]
    sh = NamedSharding(mesh, P(axis))
    return {"slots": put_sharded(
        np.zeros((n, capacity_per_device, 5), np.uint32), sh)}


# ======================================================================
# Elastic resharding (ISSUE 5): host-side re-hash-partitioning of a
# snapshot's FPSet shards + frontier onto a different mesh size
# ======================================================================

def pool_shard_fingerprints(slots):
    """All occupied (keyed) fingerprint rows of a stacked [N, cap, 5]
    sharded table, shard-major.  The stored rows are the canonical
    keyed encoding (fpset._keyed: word 0 remapped 0 -> 1); re-keying
    is idempotent and ``route`` reads words 1/3 which the keying never
    touches, so the rows re-insert and re-route exactly like the raw
    fingerprints they came from."""
    s = np.asarray(slots)
    occ = s[..., 0] != 0
    return s[occ][:, :4].astype(np.uint32)


def build_shard_tables(fps, owner, n_shards, cap_start):
    """Rebuild per-shard FPSet tables from pooled keyed fingerprint
    rows and their new ownership: returns (slots [n_shards, cap, 5],
    per-shard counts).  The capacity is shared across shards (the
    stacked array is one global [D, cap, 5]) and grows — power of two,
    load factor <= 1/4 up front — until every shard inserts without a
    probe overflow."""
    counts = np.bincount(owner, minlength=n_shards).astype(np.int64)
    cap = int(cap_start)
    while cap < 4 * max(1, int(counts.max(initial=0))):
        cap *= 2
    chunk = 1 << 14
    while True:
        out = np.zeros((n_shards, cap, 5), np.uint32)
        ok = True
        for d in range(n_shards):
            tab = {"slots": jnp.zeros((cap, 5), U32)}
            part = fps[owner == d]
            for off in range(0, part.shape[0], chunk):
                p = part[off:off + chunk]
                pad = np.zeros((chunk - p.shape[0], 4), np.uint32)
                batch = jnp.asarray(np.concatenate([p, pad]))
                m = jnp.asarray(np.arange(chunk) < p.shape[0])
                tab, _, ovf = insert_core(tab, batch, m)
                if bool(ovf):
                    ok = False
                    break
            if not ok:
                break
            out[d] = np.asarray(tab["slots"])
        if ok:
            return out, counts
        cap *= 2


def convert_sharded_snapshot(path, spec, log=None):
    """Rewrite an N-shard sharded snapshot at ``path`` into the
    single-device engine format IN PLACE: merge the FPSet shards into
    one table (re-inserting every occupied keyed row) and drop the
    sharded ``extra`` — the frontier/trace payloads are already
    global.  The supervisor's sharded -> paged fallback calls this so
    the final rung of the mesh degrade ladder keeps the run's
    progress.  ``expand_mults`` is written empty; the single-device
    engines keep their own defaults when a snapshot carries none.
    Returns True when a conversion happened (False: the snapshot was
    not written by the sharded engine)."""
    from ..engine.checkpoint import (load_checkpoint, save_checkpoint,
                                     spec_digest)
    digest = spec_digest(spec)
    ck = load_checkpoint(path, expect_digest=digest, log=log)
    ex = ck.get("extra") or {}
    if not ex.get("sharded"):
        return False
    fps = pool_shard_fingerprints(ck["slots"])
    merged, _ = build_shard_tables(
        fps, np.zeros(fps.shape[0], np.int64), 1,
        int(np.asarray(ck["slots"]).shape[1]))
    if log:
        log(f"converted sharded snapshot {path} "
            f"({np.asarray(ck['slots']).shape[0]} shards, "
            f"{fps.shape[0]} fingerprints) to single-device format")
    save_checkpoint(
        path, slots=merged[0], frontier=ck["frontier"],
        n_front=ck["n_front"], h_parent=ck["h_parent"],
        h_action=ck["h_action"], h_param=ck["h_param"],
        init_dense=ck["init_dense"], level_sizes=ck["level_sizes"],
        depth=ck["depth"], fp_count=ck["fp_count"],
        states_generated=ck["states_generated"],
        max_msgs=ck["max_msgs"], expand_mults=[],
        elapsed=ck["elapsed"], digest=digest,
        # the identity manifests ride the conversion unchanged: the
        # merged fingerprints are still canon/bounds-dependent, and
        # the resuming engine's policy checks compare against them
        pack=ck.get("pack"), canon=ck.get("canon"),
        bounds=ck.get("bounds"), por=ck.get("por"), extra=None)
    return True


# ======================================================================
# Multi-chip BFS driver: sharded frontier run to fixpoint
# ======================================================================
#
# The full distributed BFS loop (SURVEY.md §5 "distributed communication
# backend"; BASELINE.json configs[4]).  The driver routes each fresh
# successor STATE to the device that owns its fingerprint, in the same
# single all_to_all as the fingerprint itself:
#
#   * the frontier is hash-partitioned: state S lives on device
#     route(fp(S)) % D — so load stays balanced for free and dedup,
#     storage, and the next level's expansion of S are all owner-local;
#   * per tile: expand all lanes -> fingerprint -> invariant -> local
#     dedup -> bucket (state + parent gid + action + param) by owner ->
#     ONE all_to_all -> owner inserts into its FPSet shard and scatters
#     the fresh rows straight into its next-frontier buffer;
#   * abort protocol: a tile commits nothing unless every device agrees
#     — sender-side flags (violation, bag overflow, layout slot error,
#     bucket overflow) are psum'd BEFORE the exchange, receiver-side
#     capacity (next-buffer headroom) is psum'd AFTER the exchange but
#     before any insert; on abort the level pauses with a reason code,
#     the host grows the relevant structure and re-enters the tile.
#     Within a committed tile, insert and scatter are atomic per lane
#     (claim-based insert: the lane that wins the slot is the one whose
#     row is scattered), so re-entry after an in-insert FPSet probe
#     overflow loses nothing: winners dedup on re-run, losers get a
#     bigger table.
#
# Trace pointers (parent gid, action, lane param) ride with the state
# rows; the host keeps only those per level (10 B/state) and
# reconstructs counterexamples by replaying the recorded action chain —
# exactly the single-device DeviceBFS protocol.

RUNNING = 0
R_VIOLATION = 2
R_BAG_GROW = 3
R_FPSET_GROW = 4
R_NEXT_GROW = 5
R_SLOT_ERR = 6
R_DEADLOCK = 7
R_BUCKET_GROW = 8
R_EXPAND_GROW = 9   # fused commit: per-action compaction cap overflow


def make_sharded_level(kern, inv_fn, mesh: Mesh, axis: str,
                       tile: int, bucket_cap: int,
                       check_deadlock: bool = False, pack_spec=None,
                       commit: str = "fused", expand_caps=None,
                       canon=None, por=None):
    """Build the jitted one-tile sharded BFS step.

    step(tables, frontier, n_front, start_t, nb, nbp, nba, nbprm, nn,
         base_gid)
      -> (tables, nb, nbp, nba, nbprm, nn, t, reason, viol, gen, sent,
          dead, act, need, gfull, amp)
    Every array is sharded over `axis`; scalars come back as [D] arrays
    (one per device; identical where globally agreed).  With
    ``check_deadlock`` a frontier state with no enabled successor
    pauses the level with R_DEADLOCK and its device-local row index in
    the `dead` output (-1 on devices without a witness).

    With a ``pack_spec`` (engine/pack.PackSpec, ISSUE 9) the frontier
    and next-frontier are ``[D*cap, words]`` uint32 planes and — the
    lever that matters here — the all_to_all ships PACKED rows: the
    tile is unpacked on entry, successors are packed once right after
    expansion, and the exchange buckets/receive buffers/next frontier
    all carry the packed row, cutting wire and at-rest bytes by the
    pack ratio (~11x on the defect layout).  Receivers never unpack:
    dedup/insert work on the fingerprints that ride alongside.

    The jit DONATES the FPSet shards and the next-frontier buffer set
    (the ISSUE 9 donation lever): each dispatch consumes the previous
    one's buffers instead of holding K generations of them in HBM,
    which is what lets ``pipeline=2`` be the sharded default.  The
    read-only frontier and base_gid are NOT donated (the level's
    dispatch chain re-reads them).

    Fused commit (ISSUE 10): with ``commit="fused"`` the per-tile
    expansion is guard-compacted — a guard matrix over every lane of
    the tile picks the enabled (state, lane) items, which are packed
    into dense per-action segments sized by ``expand_caps`` and ONLY
    those are expanded/fingerprinted (``step_all`` expanded all T x L
    lanes, mostly disabled padding).  A per-action cap overflow is a
    new rank-agreed R_EXPAND_GROW pause carrying the exact per-action
    ``need`` so the host grows once to the true count.  The dedup that
    feeds the exchange tie-breaks on the canonical state-major flat
    index, so bucket contents — and every downstream result — are
    bit-identical to ``commit="per-action"`` (the step_all path).

    Ample-set partial-order reduction (ISSUE 16): with a ``por``
    filter (engine/por.PORFilter built with ``sharded=True``) the
    fused stage 1 masks the guard segments BEFORE compaction — on
    frontier states where a conflict-free candidate action exists,
    only that action's lanes enter the work queue.  Pre-expansion
    masking is what the owner-partitioned FPSet forces: successor
    freshness cannot be probed locally (the fingerprints live on
    other shards), so the C3 no-ignoring proviso is fully static —
    the filter only admits actions carrying a monotone progress
    witness (see engine/por.py).  Deadlock detection reads the
    UNMASKED guard matrix; the reduction is weaker than the
    single-device engines' level-marker proviso but deterministic
    and collective-free.  ``gfull``/``amp`` carry the unreduced
    generated count and the shortcut-state tally (equal to ``gen`` /
    zero when POR is off)."""
    n_dev = mesh.shape[axis]
    L = kern.n_lanes
    T = tile
    # symmetry canonicalization (ISSUE 11): fingerprints are taken on
    # the orbit-least image BEFORE ownership bucketing, so orbit-mates
    # hash — and therefore route — to the same shard and dedup there;
    # the exchanged STATE stays the generated representative
    fpf = (canon.fingerprint_fn(kern) if canon is not None
           else kern.fingerprint)
    n_act = len(kern.action_names)
    lane_aid = jnp.asarray(kern.lane_action)
    lane_prm = jnp.asarray(kern.lane_param)
    from ..models.vsr import ERR_BAG_OVERFLOW
    fused = commit == "fused"
    if fused:
        lane_counts = [kern._lane_count(n) for n in kern.action_names]
        seg_off = np.concatenate(
            [[0], np.cumsum(lane_counts)[:-1]]).astype(np.int32)
        caps = [min(T * lc, max(8, int(c)))
                for lc, c in zip(lane_counts,
                                 expand_caps or [T] * n_act)]
        E_tot = sum(caps)
        caps_v = jnp.asarray(caps, jnp.int32)
        guards = kern._guard_fns()
        fns = kern._action_fns()
    por_amat = (jnp.asarray(por.amat) if por is not None else None)

    def step_shard(tables, frontier, n_front, start_t,
                   nb, nbp, nba, nbprm, nn0, base_gid):
        tables = {k: v[0] for k, v in tables.items()}
        N = nbp.shape[0]
        n_loc = n_front[0]
        n_max = jax.lax.pmax(n_loc, axis)
        n_tiles = (n_max + T - 1) // T

        def cond(c):
            return (c["t"] < n_tiles) & (c["reason"] == RUNNING)

        def body(c):
            slots = c["slots"]
            nb, nbp, nba, nbprm = c["nb"], c["nbp"], c["nba"], c["nbprm"]
            nn = c["nn"]
            t = c["t"]
            base = t * T
            sidx = base + jnp.arange(T, dtype=jnp.int32)
            valid = sidx < n_loc
            if pack_spec is not None:
                tile_st = jax.vmap(pack_spec.unpack)(
                    frontier[jnp.clip(sidx, 0, frontier.shape[0] - 1)])
            else:
                tile_st = {k: v[jnp.clip(sidx, 0, v.shape[0] - 1)]
                           for k, v in frontier.items()}
            if fused:
                # -- stage 1 (ISSUE 10): guard matrix, exact counts --
                en_segs = []
                for name, guard in zip(kern.action_names, guards):
                    lanes = jnp.arange(kern._lane_count(name),
                                       dtype=jnp.int32)
                    seg = jax.vmap(lambda st: jax.vmap(
                        lambda ln, g=guard: g(st, ln))(lanes))(tile_st)
                    en_segs.append(seg & valid[:, None])
                # deadlock witness from the UNMASKED matrix (POR must
                # not manufacture deadlocks), before any ample masking
                en_state = jnp.zeros((T,), bool)
                for e in en_segs:
                    en_state = en_state | e.any(axis=1)
                if por_amat is not None:
                    # ample-set stage-1 masking (ISSUE 16): rows with
                    # a conflict-free candidate keep ONLY that
                    # action's lanes; everything downstream (counts,
                    # caps, compaction, exchange) sees the reduced
                    # queue.  aid_star = lowest candidate id — a
                    # deterministic pick keeps runs reproducible
                    en_act_m = jnp.stack(
                        [e.any(axis=1) for e in en_segs], axis=1)
                    n_full = jnp.stack(
                        [e.sum(dtype=jnp.int32)
                         for e in en_segs]).sum()
                    conflict = (en_act_m.astype(jnp.int32)
                                @ (~por_amat).astype(jnp.int32).T) > 0
                    cand_m = en_act_m & ~conflict
                    has_cand = cand_m.any(axis=1)
                    aid_star = jnp.argmax(cand_m, axis=1
                                          ).astype(jnp.int32)
                    en_segs = [e & (~has_cand
                                    | (aid_star == a))[:, None]
                               for a, e in enumerate(en_segs)]
                    amp_t = (has_cand
                             & (en_act_m.sum(axis=1, dtype=jnp.int32)
                                > 1)).sum(dtype=jnp.int32)
                cnts = jnp.stack([e.sum(dtype=jnp.int32)
                                  for e in en_segs])
                n_en = cnts.sum()
                act_seg = cnts.astype(U32)
                ovf_vec = cnts > caps_v
                ovf_e = ovf_vec.any()
                need = jnp.maximum(c["need"], cnts.astype(U32))

                # -- stage 2: per-action work-queue compaction; only
                # REAL items are expanded (step_all expanded all T x L
                # lanes, mostly padding)
                succ_segs, en_q_segs, pos_segs = [], [], []
                for a, (name, fn) in enumerate(
                        zip(kern.action_names, fns)):
                    L_a = lane_counts[a]
                    TL_a = T * L_a
                    off = int(seg_off[a])
                    en_fa = en_segs[a].reshape(TL_a)
                    (sel,) = jnp.nonzero(en_fa, size=caps[a],
                                         fill_value=TL_a)
                    sel_ok = sel < TL_a
                    pidx = jnp.clip(sel // L_a, 0, T - 1
                                    ).astype(jnp.int32)
                    lane_loc = (sel % L_a).astype(jnp.int32)
                    st_sel = {k: v[pidx] for k, v in tile_st.items()}
                    s_a, en2 = jax.vmap(fn, in_axes=(0, 0))(
                        st_sel, lane_loc)
                    succ_segs.append({k: v for k, v in s_a.items()
                                      if not k.startswith("_")})
                    en_q_segs.append(en2 & sel_ok)
                    # canonical state-major flat position: the dense
                    # [T, L] index this item would occupy in the
                    # step_all path — the dedup tie-break and all
                    # trace metadata derive from it, which is what
                    # keeps compacted results bit-identical
                    pos_segs.append(pidx * L + off + lane_loc)
                flat = {k: jnp.concatenate([s[k] for s in succ_segs])
                        for k in succ_segs[0]}
                en_f = jnp.concatenate(en_q_segs)
                flatpos = jnp.concatenate(pos_segs)
            else:
                succs, en = jax.vmap(kern.step_all)(tile_st)
                en = en & valid[:, None]
                en_state = en.any(axis=1)
                flat = {k: v.reshape((T * L,) + v.shape[2:])
                        for k, v in succs.items()}
                en_f = en.reshape(-1)
                n_en = en_f.sum()
                act_seg = jax.ops.segment_sum(
                    en_f.astype(U32), jnp.tile(lane_aid, T),
                    num_segments=n_act)
                ovf_e = jnp.asarray(False)
                need = c["need"]
                flatpos = jnp.arange(T * L, dtype=jnp.int32)
            if por_amat is None:
                n_full = n_en
                amp_t = jnp.asarray(0, jnp.int32)
            if pack_spec is not None:
                # pack successors ONCE, right after expansion: the
                # buckets, the wire, and the next frontier all move
                # the packed row from here on
                flat_rows = jax.vmap(pack_spec.pack)(flat)
            fps = jax.vmap(fpf)(flat)
            iok = jax.vmap(inv_fn)(flat)
            errv = jnp.where(en_f, flat["err"], 0)
            viol_l = en_f & ~iok & (errv == 0)
            bag_err = ((errv & ERR_BAG_OVERFLOW) != 0).any()
            slot_err = ((errv & ~ERR_BAG_OVERFLOW) != 0).any()

            # first violating lane by CANONICAL state-major position
            # (== argmax over the dense flat order; the fused queue is
            # a reordering, so it minimizes flatpos explicitly), as
            # (parent gid, action, param).  The lane tables (length L)
            # are indexed by flatpos % L — a bare lane_aid[i] silently
            # CLAMPS for i >= L and records the wrong action/param in
            # the trace metadata
            vidx = jnp.argmin(jnp.where(viol_l, flatpos,
                                        jnp.int32(2**31 - 1)))
            vpos = flatpos[vidx]
            vinfo = jnp.stack([
                base_gid[0] + base + (vpos // L).astype(jnp.int32),
                lane_aid[vpos % L], lane_prm[vpos % L]])
            viol = jnp.where(viol_l.any() & (c["viol"][0] < 0), vinfo,
                             c["viol"])

            # local dedup, ownership bucketing (state + meta ride
            # along).  The tie key makes the winner among equal
            # fingerprints the canonically-first item, so the fused
            # (compacted) queue buckets exactly what the dense batch
            # would
            perm, cand = dedup_batch(fps, en_f,
                                     tie=flatpos if fused else None)
            fps_s = fps[perm]
            owner = (route(fps_s) % jnp.uint32(n_dev)).astype(jnp.int32)
            pos_s = flatpos[perm]
            meta_p = base_gid[0] + (pos_s // L).astype(jnp.int32) + base
            meta_a = lane_aid[pos_s % L]
            meta_m = lane_prm[pos_s % L]

            cap = bucket_cap
            b_fps = jnp.zeros((n_dev, cap, 4), U32)
            b_mask = jnp.zeros((n_dev, cap), bool)
            b_p = jnp.zeros((n_dev, cap), jnp.int32)
            b_a = jnp.zeros((n_dev, cap), jnp.int32)
            b_m = jnp.zeros((n_dev, cap), jnp.int32)
            if pack_spec is not None:
                b_st = {"rows": jnp.zeros(
                    (n_dev, cap, pack_spec.words), U32)}
                flat_src = {"rows": flat_rows}
            else:
                b_st = {k: jnp.zeros((n_dev, cap) + v.shape[1:],
                                     v.dtype)
                        for k, v in flat.items()}
                flat_src = flat
            ovf_b = jnp.asarray(False)
            for d in range(n_dev):
                m = cand & (owner == d)
                pos = jnp.cumsum(m) - 1
                ovf_b = ovf_b | ((pos[-1] + 1 > cap) & m.any())
                idx = jnp.where(m & (pos < cap), pos, cap)
                b_fps = b_fps.at[d, idx].set(fps_s, mode="drop")
                b_mask = b_mask.at[d, idx].set(m, mode="drop")
                b_p = b_p.at[d, idx].set(meta_p, mode="drop")
                b_a = b_a.at[d, idx].set(meta_a, mode="drop")
                b_m = b_m.at[d, idx].set(meta_m, mode="drop")
                for k in b_st:
                    b_st[k] = b_st[k].at[d, idx].set(
                        flat_src[k][perm], mode="drop")

            # deadlock: a valid frontier state with no enabled lane
            # (en_state comes from the guard matrix in fused commit,
            # from step_all's enabled matrix in per-action)
            dead_l = valid & ~en_state if check_deadlock else \
                jnp.zeros((T,), bool)
            dead_i = jnp.where(dead_l.any() & (c["dead"] < 0),
                               base + jnp.argmax(dead_l), c["dead"]
                               ).astype(jnp.int32)

            # global pre-exchange abort vote (ovf_e: a fused-commit
            # compaction cap overflowed — the staged queue is
            # truncated, so nothing may commit until the exact-need
            # growth recompiles)
            flags = jnp.stack([viol_l.any(), bag_err, slot_err, ovf_b,
                               dead_l.any(), ovf_e]).astype(jnp.int32)
            gflags = jax.lax.psum(flags, axis) > 0
            abort_pre = gflags.any()

            # ONE exchange moves fingerprints + states + trace meta
            a2a = lambda x: jax.lax.all_to_all(x, axis, 0, 0, tiled=False)
            i_fps = a2a(b_fps).reshape(n_dev * cap, 4)
            i_mask = a2a(b_mask).reshape(n_dev * cap)
            i_p = a2a(b_p).reshape(n_dev * cap)
            i_a = a2a(b_a).reshape(n_dev * cap)
            i_m = a2a(b_m).reshape(n_dev * cap)
            i_st = {k: a2a(v).reshape((n_dev * cap,) + v.shape[2:])
                    for k, v in b_st.items()}
            if pack_spec is not None:
                i_st = i_st["rows"]     # [D*cap, words] packed rows

            # receiver-side capacity vote (cross-sender dedup can only
            # shrink the count, so this bound is safe)
            perm2, cand2 = dedup_batch(i_fps, i_mask)
            n_inc = cand2.sum()
            room = (N - nn) >= n_inc
            abort_room = jax.lax.psum(
                (~room).astype(jnp.int32), axis) > 0
            commit = ~abort_pre & ~abort_room

            # insert into the CARRIED table (c["slots"]), not the
            # step argument: the argument is constant across the tile
            # while_loop, so using it dropped every prior tile's
            # inserts — tile t+1 re-admitted tile t's successors and
            # any level needing >1 tile/device flooded the next
            # frontier with duplicates (caught by the multihost
            # depth-14 artifact: 518,843 "distinct" in a 43,941-state
            # space; scripts/bucket_repro.py pins the level-8 onset)
            new_tab, fresh, probe_ovf = insert_core(
                {"slots": slots}, i_fps[perm2], cand2 & commit)
            slots2 = new_tab["slots"]
            dest = jnp.where(fresh, nn + jnp.cumsum(fresh) - 1, N
                             ).astype(jnp.int32)
            src = perm2
            if pack_spec is not None:
                nb = nb.at[dest].set(i_st[src], mode="drop")
            else:
                for k in nb:
                    nb[k] = nb[k].at[dest].set(i_st[k][src],
                                               mode="drop")
            nbp = nbp.at[dest].set(i_p[src], mode="drop")
            nba = nba.at[dest].set(i_a[src], mode="drop")
            nbprm = nbprm.at[dest].set(i_m[src], mode="drop")
            n_fresh = fresh.sum()

            # committed-but-unresolved probes pause the level for table
            # growth; resolved lanes landed atomically so re-entry of
            # the same tile only re-dedups them (nothing lost)
            g_povf = jax.lax.psum(
                (commit & probe_ovf).astype(jnp.int32), axis) > 0
            # failure-cause priority (ISSUE 10): violation > slot >
            # bag > expand-grow > bucket > deadlock > next; fpset
            # growth last.  Expand outranks bucket because a truncated
            # queue makes the bucket contents meaningless
            reason = jnp.where(
                gflags[0], R_VIOLATION,
                jnp.where(gflags[2], R_SLOT_ERR,
                          jnp.where(gflags[1], R_BAG_GROW,
                                    jnp.where(gflags[5], R_EXPAND_GROW,
                                    jnp.where(gflags[3], R_BUCKET_GROW,
                                              jnp.where(gflags[4],
                                                        R_DEADLOCK,
                                              jnp.where(abort_room,
                                                        R_NEXT_GROW,
                                                        RUNNING)))))))
            reason = jnp.where((reason == RUNNING) & g_povf,
                               R_FPSET_GROW, reason)
            return {
                "t": jnp.where(commit & ~g_povf, t + 1, t),
                "reason": jnp.where(c["reason"] == RUNNING, reason,
                                    c["reason"]),
                "viol": viol, "dead": dead_i, "need": need,
                "slots": slots2,
                "nb": nb, "nbp": nbp, "nba": nba, "nbprm": nbprm,
                "nn": nn + jnp.where(commit, n_fresh, 0),
                "gen": c["gen"] + jnp.where(commit & ~g_povf, n_en, 0),
                "act": c["act"] + jnp.where(commit & ~g_povf, act_seg,
                                            jnp.uint32(0)),
                # exchange-occupancy metric: useful bucket rows this
                # device shipped (the wire moves full static buckets)
                "sent": c["sent"] + jnp.where(
                    commit & ~g_povf, b_mask.sum().astype(jnp.int32), 0),
                # POR accounting (ISSUE 16): unreduced generated count
                # and shortcut-state tally; gfull == gen, amp == 0
                # when the filter is off/inert
                "gfull": c["gfull"] + jnp.where(commit & ~g_povf,
                                                n_full, 0),
                "amp": c["amp"] + jnp.where(commit & ~g_povf,
                                            amp_t, 0),
            }

        init = {
            "t": start_t[0],
            "reason": jnp.asarray(RUNNING, jnp.int32),
            "viol": jnp.full((3,), -1, jnp.int32),
            "dead": jnp.asarray(-1, jnp.int32),
            "need": jnp.zeros((n_act,), jnp.uint32),
            "slots": tables["slots"],
            "nb": nb, "nbp": nbp, "nba": nba, "nbprm": nbprm,
            "nn": nn0[0],
            "gen": jnp.asarray(0, jnp.int32),
            "act": jnp.zeros((n_act,), jnp.uint32),
            "sent": jnp.asarray(0, jnp.int32),
            "gfull": jnp.asarray(0, jnp.int32),
            "amp": jnp.asarray(0, jnp.int32),
        }
        out = jax.lax.while_loop(cond, body, init)
        one = lambda x: x[None]
        return ({"slots": out["slots"][None]},
                out["nb"], out["nbp"], out["nba"], out["nbprm"],
                one(out["nn"]), one(out["t"]), one(out["reason"]),
                out["viol"][None], one(out["gen"]), one(out["sent"]),
                one(out["dead"]), out["act"][None], out["need"][None],
                one(out["gfull"]), one(out["amp"]))

    sp = P(axis)
    # donate the FPSet shards + the next-frontier buffer set (args 0,
    # 4-7): the K-deep dispatch window chains each step on the previous
    # one's outputs, so donation means the window holds ONE generation
    # of the capacity-bound buffers instead of K (ISSUE 9 — the lever
    # that makes pipeline=2 the sharded default).  The frontier (1) and
    # base_gid (9) are re-read by every dispatch of the level's chain
    # and must NOT be donated.
    step = jax.jit(_shard_map(
        step_shard, mesh=mesh,
        in_specs=(sp,) * 10,
        out_specs=(sp,) * 16), donate_argnums=(0, 4, 5, 6, 7))
    return step


class ShardedBFS:
    """Host driver: run the sharded level kernel to fixpoint.

    The multi-chip counterpart of engine.device_bfs.DeviceBFS — same
    pause/grow/re-enter protocol, same host-side trace-pointer store and
    replay-based counterexample reconstruction; the frontier and the
    fingerprint set are hash-partitioned over the mesh axis and states
    migrate to their owner in the in-level all_to_all."""

    def __init__(self, spec, mesh: Mesh, axis: str = "d", max_msgs=None,
                 tile=32, bucket_cap=None, next_capacity=1 << 12,
                 fpset_capacity=1 << 14, check_deadlock=False,
                 model_factory=None, pipeline=2, exchange_retries=5,
                 exchange_backoff=0.05, exchange_backoff_cap=2.0,
                 sleep=time.sleep, pack="auto", commit="fused",
                 symmetry="auto", bounds="auto", por="off"):
        from ..core.values import TLAError
        if commit not in ("fused", "per-action"):
            raise TLAError(f"commit must be 'fused' or 'per-action' "
                           f"(got {commit!r})")
        self.spec = spec
        self.mesh = mesh
        self.axis = axis
        self.D = mesh.shape[axis]
        self.tile = tile
        # streamed edge emission (ISSUE 15) is a single-device paged
        # seam — the sharded engine journals the key as off
        self._edges_on = False
        # level-kernel commit mode (ISSUE 10): "fused" compacts each
        # tile's enabled lanes through the guard matrix before
        # expansion (occupancy-packed; exact-need cap growth);
        # "per-action" is the step_all full-lane expansion.  Results
        # are bit-identical between the two.
        self.commit = commit
        self.expand_caps = None       # fused per-action caps (lanes)
        self._need_seen = None
        # bounded exponential-backoff budget for transient exchange
        # failures (ISSUE 5): a dropped exchange re-issues the level
        # step (lossless — committed lanes just dedup) up to
        # `exchange_retries` CONSECUTIVE times before the run fails
        # loudly; `sleep` is injectable so tests don't wait
        self.exchange_retries = int(exchange_retries)
        self.exchange_backoff = float(exchange_backoff)
        self.exchange_backoff_cap = float(exchange_backoff_cap)
        self._sleep = sleep
        # set by an elastic resume that re-hash-partitioned an N-shard
        # snapshot onto this mesh (None: no reshard happened)
        self.resharded_from = None
        # dispatch-window depth (ISSUE 4; 1 = synchronous).  Default 2
        # like the device/paged engines (ISSUE 9): the step's jit now
        # DONATES the FPSet shards and next-frontier buffers, so a
        # K-deep window holds ONE generation of the capacity-bound
        # buffers instead of K — the HBM cost that made K>1 opt-in is
        # gone.  Semantics are identical at every K
        # (tests/test_pipeline.py).
        self.pipe_window = max(1, int(pipeline))
        # packed frontier encoding (ISSUE 9): "auto" packs whenever the
        # codec declares plane_bounds; False runs dense; True forces
        # the interchange format (ratio 1.0 without bounds).  Results
        # are bit-identical either way.
        self._pack_req = pack
        # symmetry canonicalization (ISSUE 11): "auto" = on iff the
        # cfg declares SYMMETRY; the CanonSpec runs inside the sharded
        # step, pre-bucketing (see make_sharded_level)
        self._symmetry_req = symmetry
        # model_factory(spec, max_msgs=..) -> (codec, kernel); default
        # is the hand-kernel registry (DeviceBFS parity — tests drive
        # the driver with stub kernels through this hook)
        self._model_factory = model_factory
        # bucket_cap=None: occupancy-calibrated — start minimal and let
        # R_BUCKET_GROW converge to the run's high-water mark (wire
        # volume is cap-bound; see module docstring)
        self.bucket_cap = bucket_cap if bucket_cap is not None \
            else max(64, tile)
        self.N = next_capacity          # per-device frontier capacity
        self.fp_cap = fpset_capacity    # per-device FPSet slots
        self.inv_names = list(spec.cfg.invariants)
        self._ckd = bool(check_deadlock)
        self._mat = {}
        # speclint bounds pre-pass (ISSUE 13): same consumption seam
        # as DeviceBFS — dead-action pruning, tightened packing, exact
        # fanout caps; see engine/bounds.resolve_bounds
        from ..engine.bounds import resolve_bounds
        self._facts = resolve_bounds(spec, bounds)
        self._pruned = []
        # ample-set partial-order reduction (ISSUE 16): same resolve
        # contract as DeviceBFS (constructor default "off", CLI
        # -por auto); the filter is rebuilt in _build with
        # sharded=True — the static monotone-witness C3 proviso the
        # owner-partitioned FPSet forces (see make_sharded_level)
        from ..engine.por import resolve_por
        self._por_facts = resolve_por(
            spec, por,
            temporal=bool(getattr(spec, "temporal_props", ())),
            edges=False, commit=self.commit)
        self._por = None
        self._por_kept = self._por_full = self._por_amp = 0
        self._build(max_msgs)

    def _build(self, max_msgs):
        from ..models import registry
        registry.ensure_compile_cache()
        registry.ensure_debug_flags()
        factory = self._model_factory or (
            lambda spec, max_msgs=None: registry.make_model(
                spec, max_msgs=max_msgs, fold_symmetry=False))
        self.codec, self.kern = factory(self.spec, max_msgs=max_msgs)
        # statically dead actions (bounds pass): prune the kernel lane
        # tables before the step builds its guard segments (ISSUE 13)
        if self._facts is not None and self._facts.dead_actions:
            from ..engine.bounds import prune_kernel
            dead = [n for n in self._facts.dead_actions
                    if n in self.kern.action_names]
            if dead and len(dead) < len(self.kern.action_names):
                self.kern = prune_kernel(self.kern, dead)
                self._pruned = dead
        self._inv = self.kern.invariant_fn(self.inv_names)
        self._mat = {}
        # symmetry canonicalization spec (rebuilt with the codec).
        # A factory-supplied FOLDED kernel already owns the reduction:
        # the canon seam stands down, and forcing -symmetry off is a
        # loud error (see DeviceBFS._build)
        from ..core.values import TLAError
        from ..engine.canon import build_canon_spec, kernel_fold_order
        self._sym_fold = kernel_fold_order(self.kern)
        if self.spec.symmetry_perms and self._sym_fold > 1:
            if self._symmetry_req is False:
                raise TLAError(
                    "symmetry=False requested but the model factory "
                    "built a kernel with a FOLDED perm table; rebuild "
                    "it with fold_symmetry=False "
                    "(registry.make_model) to make -symmetry off real")
            self._canon = None
        else:
            self._canon = build_canon_spec(self.spec, self.codec,
                                           self.kern,
                                           self._symmetry_req)
        # packed-frontier spec for THIS codec binding (rebuilt with the
        # codec on bag growth — MAX_MSGS changes the lane count)
        from ..engine.pack import build_pack_spec
        tighten = (self._facts.plane_tighten()
                   if self._facts is not None else {})
        if self._pack_req is False:
            self._pk = None
            self._pk_decl = None
        else:
            self._pk = build_pack_spec(self.codec, spec=self.spec,
                                       force=self._pack_req is True,
                                       tighten=tighten or None)
            self._pk_decl = (build_pack_spec(
                self.codec, spec=self.spec,
                force=self._pack_req is True) if tighten else self._pk)
        if self.commit == "fused":
            names = self.kern.action_names
            tl = [self.tile * self.kern._lane_count(n) for n in names]
            if self.expand_caps is None:
                self.expand_caps = [min(t, max(8, self.tile))
                                    for t in tl]
                # static fanout bounds seed the caps (ISSUE 13): zero
                # growth redraws on exact-bounds fixtures
                if self._facts is not None:
                    for a, n in enumerate(names):
                        fo = self._facts.fanout.get(n)
                        if fo:
                            self.expand_caps[a] = min(
                                tl[a], max(8, self.tile * fo))
            else:   # re-clamp after a MAX_MSGS rebuild (lanes grow)
                self.expand_caps = [min(t, max(8, int(c)))
                                    for t, c in zip(tl,
                                                    self.expand_caps)]
            if self._need_seen is None or \
                    len(self._need_seen) != len(names):
                self._need_seen = np.zeros(len(names), np.int64)
        self._por = None
        if self._por_facts is not None:
            from ..engine.por import PORFilter
            self._por = PORFilter(self._por_facts, self.kern,
                                  sharded=True)
        self._por_active = (self._por is not None
                            and self._por.any_eligible
                            and self.commit == "fused")
        self._step = make_sharded_level(self.kern, self._inv, self.mesh,
                                        self.axis, self.tile,
                                        self.bucket_cap,
                                        check_deadlock=self._ckd,
                                        pack_spec=self._pk,
                                        commit=self.commit,
                                        expand_caps=self.expand_caps,
                                        canon=self._canon,
                                        por=(self._por
                                             if self._por_active
                                             else None))
        self._fresh_jit = True   # first dispatch after a (re)jit is
        #                          charged to the "compile" phase
        self._sh = NamedSharding(self.mesh, P(self.axis))
        self._rep_sh = NamedSharding(self.mesh, P())
        # multi-process: host pulls of globally-sharded arrays must
        # reshard to replicated first (parallel/multihost.py)
        self._pull = make_replicator(self.mesh)

    # borrowed single-device helpers (same attribute contract)
    from ..engine.device_bfs import DeviceBFS as _DB
    _materialize_one = _DB._materialize_one
    _trace = _DB._trace
    _fetch_row = _DB._fetch_row
    _pack_manifest = _DB._pack_manifest
    _check_pack_manifest = _DB._check_pack_manifest
    _pack_gauges = _DB._pack_gauges
    _fp_batch = _DB._fp_batch
    _canon_manifest = _DB._canon_manifest
    _check_canon_manifest = _DB._check_canon_manifest
    _symmetry_on = _DB._symmetry_on
    _bounds_doc = _DB._bounds_doc
    _bounds_manifest = _DB._bounds_manifest
    _check_bounds_manifest = _DB._check_bounds_manifest
    _bounds_gauges = _DB._bounds_gauges
    _por_doc = _DB._por_doc
    _por_manifest = _DB._por_manifest
    _check_por_manifest = _DB._check_por_manifest
    _por_gauges = _DB._por_gauges

    def _flush_pointers(self):
        """No-op: the sharded driver's pointer pulls are synchronous
        (they ride the per-level collective gather already)."""
    del _DB

    def _put(self, arr):
        return put_sharded(arr, self._sh)

    def _rep(self, arr):
        """Host value (identical on all processes) -> replicated
        global array (a P() input of the sharded kernels)."""
        return put_sharded(arr, self._rep_sh)

    def _alloc_frontier(self, cap):
        D = self.D
        if self._pk is not None:
            # packed at-rest frontier (ISSUE 9): [D*cap, words] uint32
            # planes — the exchange and the next frontier move packed
            # rows, so this buffer IS the interchange format
            nb = self._put(np.zeros((D * cap, self._pk.words),
                                    np.uint32))
        else:
            zero = self.codec.zero_state()
            nb = {k: self._put(np.zeros((D * cap,) + np.shape(v),
                                        np.int32))
                  for k, v in zero.items()}
        z = lambda: self._put(np.zeros((D * cap,), np.int32))
        return nb, z(), z(), z()

    def _pull_rows(self, garr, counts):
        """Gather per-device live rows of a [D*cap, ...] global array."""
        cap = garr.shape[0] // self.D
        host = self._pull(garr)
        return np.concatenate(
            [host[d * cap:d * cap + int(counts[d])]
             for d in range(self.D)], axis=0)

    def _grow_global(self, garr, old_cap, new_cap):
        host = self._pull(garr)
        D = self.D
        host = host.reshape((D, old_cap) + host.shape[1:])
        pad = np.zeros((D, new_cap - old_cap) + host.shape[2:],
                       host.dtype)
        out = np.concatenate([host, pad], axis=1)
        return self._put(out.reshape((D * new_cap,) + host.shape[2:]))

    @closes_observer
    def run(self, max_depth=None, max_states=None, max_seconds=None,
            log=None, check_deadlock=None, checkpoint_path=None,
            checkpoint_every=None, resume_from=None,
            progress_every=10.0, obs=None) -> "CheckResult":
        import time as _time
        from ..analysis import preflight
        from ..core.values import TLAError
        from ..engine.bfs import CheckResult
        from ..engine.fpset import grow as fp_grow
        from ..obs import RunObserver
        preflight(self.spec, log=log)   # fail fast, before any dispatch
        obs = RunObserver.ensure(obs, "sharded", self.spec, log=log,
                                 progress_every=progress_every)
        obs.pipeline = self.pipe_window
        obs.pack = self._pk is not None
        obs.commit = self.commit
        obs.symmetry = self._symmetry_on()
        obs.bounds = self._bounds_doc()
        obs.edges = self._edges_on
        obs.por = self._por_doc()
        self._obs_active = obs          # closes_observer finalizes it
        self._act_counts = np.zeros(len(self.kern.action_names),
                                    np.int64)
        self._tiles_done = 0
        self._lanes_disp = 0
        self._por_kept = self._por_full = self._por_amp = 0
        # multi-process: every rank collects, only host 0 writes the
        # journal / metrics file / stats table (per-shard numbers are
        # reduced host-side before they reach the collector)
        if jax.process_index() != 0:
            obs.primary = False
            obs.journal.close()     # write() no-ops once closed
        spec, codec = self.spec, self.codec
        D = self.D
        res = CheckResult()
        t0 = _time.time()
        obs.start(t0, backend=jax.default_backend(),
                  resumed=resume_from is not None)
        emit = obs.log

        if check_deadlock is not None and bool(check_deadlock) != self._ckd:
            self._ckd = bool(check_deadlock)
            self._build(self.codec.shape.MAX_MSGS)
        sharded_ins = make_sharded_insert(self.mesh, self.axis)

        # exchange metrics: useful rows shipped vs static wire volume
        # (all_to_all always moves full D x bucket_cap buckets).  Bytes
        # are accumulated with the row size current at the time (the
        # codec — and so the state row — grows on R_BAG_GROW)
        def _row_bytes():
            # state bytes as the wire actually moves them: packed words
            # when the pack spec is bound (the exchange buckets carry
            # packed rows), dense planes otherwise
            if self._pk is not None:
                state_b = self._pk.packed_bytes
            else:
                zero = self.codec.zero_state()
                state_b = sum(int(np.prod(np.shape(v)) or 1) * 4
                              for v in zero.values())
            return state_b + 16 + 1 + 12      # + fps/mask/meta
        exch_rows_useful = 0
        exch_rows_wire = 0
        exch_bytes_useful = 0
        exch_bytes_wire = 0

        if resume_from is not None:
            # --- resume from a level-boundary snapshot ----------------
            from ..engine.checkpoint import load_checkpoint, spec_digest
            ck = load_checkpoint(resume_from,
                                 expect_digest=spec_digest(spec),
                                 log=emit)
            ex = ck["extra"] or {}
            if not ex.get("sharded"):
                raise TLAError("checkpoint was written by the "
                               "single-device engine; resume it there")
            # the per-shard counts drive the frontier re-scatter below:
            # verify them against the actual snapshot arrays so a
            # snapshot written under a different shard layout fails
            # here with a clear message instead of an index error
            _counts = [int(x) for x in ex["shard_counts"]]
            n_src = len(_counts)
            if min(_counts, default=0) < 0 or \
                    sum(_counts) != int(ck["n_front"]):
                raise TLAError(
                    f"checkpoint extra.shard_counts {_counts} (sum "
                    f"{sum(_counts)}) does not match the manifest "
                    f"frontier count {ck['n_front']}: snapshot was "
                    f"written under a different shard layout; "
                    f"refusing to resume")
            if len(ex.get("dev_distinct", [])) != n_src:
                raise TLAError(
                    f"checkpoint extra.dev_distinct has "
                    f"{len(ex.get('dev_distinct', []))} entries for "
                    f"{n_src} FPSet shards; refusing to resume")
            if ck["max_msgs"] != self.codec.shape.MAX_MSGS or \
                    ex["bucket_cap"] != self.bucket_cap:
                self.bucket_cap = int(ex["bucket_cap"])
                self._build(ck["max_msgs"])
            # AFTER the max_msgs rebuild: the pack-spec version digests
            # the lane count, so a snapshot from a grown-bag run only
            # matches the spec rebuilt at ITS MAX_MSGS (DeviceBFS
            # orders these the same way)
            self._check_bounds_manifest(ck, resume_from)
            self._check_pack_manifest(ck, resume_from)
            self._check_canon_manifest(ck, resume_from)
            # POR flip/digest policy (ISSUE 16): the explored state
            # sets of a reduced and an unreduced run are not
            # comparable (no level markers to rebuild here — the
            # sharded C3 proviso is fully static)
            if self._por_active or ck.get("por"):
                self._check_por_manifest(ck, resume_from)
            rows = ck["frontier"]
            h_parent = np.asarray(ck["h_parent"])
            h_action = np.asarray(ck["h_action"])
            h_param = np.asarray(ck["h_param"])
            if n_src != D:
                # --- elastic resume: re-hash-partition N -> D ---------
                # (ISSUE 5 tentpole).  Every fingerprint and frontier
                # state migrates to route(fp) % D — the same ownership
                # rule the live exchange uses — so the resumed run is
                # indistinguishable from one that ran on this mesh all
                # along (modulo within-shard frontier order, which the
                # stable partition keeps in saved global order).
                fps_pool = pool_shard_fingerprints(ck["slots"])
                if fps_pool.shape[0] != int(ck["fp_count"]):
                    raise TLAError(
                        f"checkpoint FPSet shards hold "
                        f"{fps_pool.shape[0]} fingerprints, manifest "
                        f"says fp_count={ck['fp_count']}: snapshot "
                        f"is inconsistent; refusing to resume")
                owner = (np.asarray(route(jnp.asarray(fps_pool)))
                         % np.uint32(D)).astype(np.int64)
                slots, dev_distinct = build_shard_tables(
                    fps_pool, owner, D,
                    int(np.asarray(ck["slots"]).shape[1]))
                # frontier rows migrate to their new owner; the LAST
                # level's trace-pointer block permutes with them so
                # gid -> (parent, action, param) stays aligned (the
                # frontier IS the last level_sizes entry, saved in the
                # same global order as the trace tail)
                # canonical fingerprints (when symmetry is on) so the
                # re-route matches the live exchange's ownership rule
                ffps = np.asarray(self._fp_batch(
                    {k: np.asarray(v) for k, v in rows.items()}))
                fowner = (np.asarray(route(jnp.asarray(ffps)))
                          % np.uint32(D)).astype(np.int64)
                perm = np.argsort(fowner, kind="stable")
                rows = {k: np.asarray(v)[perm] for k, v in rows.items()}
                counts0 = np.bincount(fowner, minlength=D
                                      ).astype(np.int64)
                nf = int(ck["n_front"])
                if nf:
                    h_parent = np.concatenate(
                        [h_parent[:-nf], h_parent[-nf:][perm]])
                    h_action = np.concatenate(
                        [h_action[:-nf], h_action[-nf:][perm]])
                    h_param = np.concatenate(
                        [h_param[:-nf], h_param[-nf:][perm]])
                self.resharded_from = n_src
                obs.reshard(n_src, D, int(ck["fp_count"]))
                emit(f"resharded snapshot: {n_src} shards -> {D} "
                     f"devices ({fps_pool.shape[0]} fingerprints, "
                     f"{nf} frontier rows re-hash-partitioned)")
            else:
                slots = np.asarray(ck["slots"])
                counts0 = np.asarray(_counts, np.int64)
                dev_distinct = np.asarray(ex["dev_distinct"], np.int64)
            self.fp_cap = int(slots.shape[1])
            tables = {"slots": self._put(slots)}
            self.N = max(self.N, int(counts0.max(initial=0)))
            codec = self.codec
            self._init_states = [codec.decode(d)
                                 for d in ck["init_dense"]]
            self._h_parent = [h_parent]
            self._h_action = [h_action]
            self._h_param = [h_param]
            self.level_sizes = list(ck["level_sizes"])
            depth0 = ck["depth"]
            fp_count = ck["fp_count"]
            res.states_generated = ck["states_generated"]
            t0 -= ck["elapsed"]
            obs.set_epoch(t0)
            self._dev_distinct = dev_distinct
            xc = ex.get("exchange") or {}
            exch_rows_useful = xc.get("useful_rows", 0)
            exch_rows_wire = xc.get("wire_rows", 0)
            exch_bytes_useful = xc.get("useful_bytes", 0)
            exch_bytes_wire = xc.get("wire_bytes", 0)
            F = self.N
            zero = self.codec.zero_state()
            host_front = {k: np.zeros((D * F,) + np.shape(v), np.int32)
                          for k, v in zero.items()}
            pos = 0
            for d in range(D):
                for j in range(int(counts0[d])):
                    for k in host_front:
                        host_front[k][d * F + j] = rows[k][pos]
                    pos += 1
            # snapshots store dense planes (the engine-agnostic
            # interchange format); pack the scatter when packing is on
            front = (self._put(self._pk.pack_np(host_front))
                     if self._pk is not None else
                     {k: self._put(v) for k, v in host_front.items()})
            n_front = self._put(counts0.astype(np.int32))
            base_dev = (sum(self.level_sizes[:-1])
                        + np.concatenate([[0], np.cumsum(counts0)[:-1]]))
            emit(f"resumed from {resume_from}: depth {depth0}, "
                 f"{fp_count} distinct, frontier {int(counts0.sum())}")
        else:
            tables = make_sharded_tables(self.mesh, self.axis,
                                         self.fp_cap)

            # --- init states: dedup, assign to owner devices ----------
            init_states = list(spec.init_states())
            dense = [codec.encode(st) for st in init_states]
            batch = {k: np.stack([d[k] for d in dense]) for k in dense[0]}
            fps = np.asarray(self._fp_batch(batch))
            keep, seen = [], set()
            for i in range(len(dense)):
                t = tuple(fps[i])
                if t not in seen:
                    seen.add(t)
                    keep.append(i)
            owners = (np.asarray(route(jnp.asarray(fps[keep])))
                      % np.uint32(D)).astype(int)
            order = np.argsort(owners, kind="stable")
            keep = [keep[i] for i in order]
            owners = owners[order]
            self._init_states = [init_states[i] for i in keep]
            n0 = len(keep)
            counts0 = np.bincount(owners, minlength=D)

            F = self.N
            self._dev_distinct = counts0.astype(np.int64).copy()
            # build the initial frontier host-side (zeros + init rows)
            # and scatter once: pulling a freshly-allocated GLOBAL
            # array is illegal in multi-process mode
            zero = self.codec.zero_state()
            host_front = {k: np.zeros((D * F,) + np.shape(v), np.int32)
                          for k, v in zero.items()}
            pos = 0
            for d in range(D):
                for j in range(int(counts0[d])):
                    row = dense[keep[pos]]
                    for k in host_front:
                        host_front[k][d * F + j] = row[k]
                    pos += 1
            front = (self._put(self._pk.pack_np(host_front))
                     if self._pk is not None else
                     {k: self._put(v) for k, v in host_front.items()})
            n_front = self._put(counts0.astype(np.int32))
            tables, _fr, ovf = sharded_ins(
                tables, self._rep(fps[keep]),
                self._rep(np.ones((n0,), bool)))
            assert not bool(self._pull(ovf).any())
            fp_count = n0

            self._h_parent = [np.full(n0, -1, np.int64)]
            self._h_action = [np.full(n0, -1, np.int32)]
            self._h_param = [np.zeros(n0, np.int32)]
            self.level_sizes = [n0]
            depth0 = 0
            base_dev = np.concatenate([[0], np.cumsum(counts0)[:-1]])
            for i, st in enumerate(self._init_states):
                bad = spec.check_invariants(st)
                if bad:
                    res.ok = False
                    res.violated_invariant = bad
                    res.trace = self._trace(i)
                    return self._finish(res, obs, fp_count)
            res.states_generated += len(dense)

        def _attach_exchange(r):
            r.exchange = {
                "row_bytes": _row_bytes(),
                "useful_rows": exch_rows_useful,
                "useful_bytes": exch_bytes_useful,
                "wire_rows": exch_rows_wire,
                "wire_bytes": exch_bytes_wire,
            }
            for k, v in r.exchange.items():
                obs.gauge(f"exchange_{k}", int(v))
            emit(f"exchange: {exch_rows_useful} useful rows "
                 f"({exch_bytes_useful / 1e6:.1f} MB) / "
                 f"{exch_rows_wire} wire rows "
                 f"({exch_bytes_wire / 1e6:.1f} MB)")

        depth = depth0
        last_checkpoint = _time.time()

        # multi-process SPMD discipline: any control decision based on
        # wall clocks must be rank-agreed, or ranks issue mismatched
        # collectives (rank 0 enters the checkpoint pull — a reshard
        # collective — while rank 1 proceeds to the next level's step).
        # Rank 0's verdict is broadcast; single-process it's a no-op.
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            def agree(flag):
                return bool(int(multihost_utils.broadcast_one_to_all(
                    np.int32(bool(flag)))))

            def agree_any(flag):
                # any-rank reduce (vs rank 0's verdict): an exchange
                # drop observed on ONE host must make EVERY host take
                # the retry branch, or the pack issues mismatched
                # collectives.  One int32 allgather per dispatch —
                # noise next to the step's own all_to_alls
                return bool(multihost_utils.process_allgather(
                    np.int32(bool(flag))).any())
        else:
            def agree(flag):
                return bool(flag)

            agree_any = bool
        # pipelined dispatch window (ISSUE 4): the sharded step is one
        # whole-level attempt, chained on its own outputs; the host
        # blocks only on the oldest in-flight step's reason.  Replays
        # behind a pause commit nothing (every sharded abort is a
        # pre-commit vote), so pipe.drain() discarding them keeps
        # counts/levels/traces identical to -pipeline 1.
        from ..engine.pipeline import DispatchPipeline
        pipe = DispatchPipeline(self.pipe_window, obs,
                                ready=lambda o: o[7])

        pack_scalars = jax.jit(
            lambda r, s, g, gf, am, a: jnp.concatenate(
                [r[:, None], s[:, None], g[:, None], gf[:, None],
                 am[:, None], a.astype(jnp.int32)], axis=1))

        def pull(o):
            # ONE replication pull for all per-dispatch control
            # scalars — separate _pull calls cost one collective (a
            # tunnel RTT on a remote TPU) EACH; pack [D] reason/sent/
            # gen/gfull/amp and the [D, A] act counters into a single
            # [D, 5+A] array first
            packed = np.asarray(self._pull(
                pack_scalars(o[7], o[10], o[9], o[14], o[15],
                             o[12])), np.int64)
            reason = int(packed[0, 0])
            sent = int(packed[:, 1].sum())
            gen = int(packed[:, 2].sum())
            gfull = int(packed[:, 3].sum())
            amp = int(packed[:, 4].sum())
            act = packed[:, 5:].sum(axis=0)
            return reason, sent, gen, gfull, amp, act

        # shard context for fault hooks: the HOST process in
        # multi-process runs; a single-process mesh drives every
        # shard, so any armed shard matches (shard=None)
        my_shard = (jax.process_index() if jax.process_count() > 1
                    else None)
        xretry = 0      # consecutive exchange-drop retries (bounded)

        while True:
            with obs.timer("host_sync"):
                front_total = int(self._pull(n_front).sum())
            if front_total <= 0:
                break
            if max_depth is not None and depth >= max_depth:
                res.error = f"depth limit {max_depth} reached"
                break
            depth += 1
            fault_point("level", depth=depth, shard=my_shard, obs=obs)
            nb, nbp, nba, nbprm = self._alloc_frontier(self.N)
            nn = self._put(np.zeros(D, np.int32))
            start_t = self._put(np.zeros(D, np.int32))
            base_gid = self._put(base_dev.astype(np.int32))
            while True:
                while pipe.has_room():
                    # transient exchange failure: bounded exponential-
                    # backoff retry loop (ISSUE 5; was a one-shot
                    # re-issue).  The pause/re-enter protocol makes
                    # every retry lossless — committed lanes just
                    # dedup — so the only budget is patience: after
                    # `exchange_retries` CONSECUTIVE drops the run
                    # fails loudly instead of spinning forever.  The
                    # retry branch is rank-agreed (any-rank reduce):
                    # a drop seen on one host process must send every
                    # process down the same branch
                    dropped = False
                    try:
                        fault_point("exchange", depth=depth,
                                    shard=my_shard, obs=obs)
                    except InjectedExchangeDrop:
                        dropped = True
                    if agree_any(dropped):
                        xretry += 1
                        if xretry > self.exchange_retries:
                            raise TLAError(
                                f"sharded exchange failed {xretry} "
                                f"consecutive times at level {depth} "
                                f"(retry budget "
                                f"{self.exchange_retries}); giving up")
                        from ..resilience.backoff import backoff_delay
                        backoff = backoff_delay(
                            xretry, self.exchange_backoff,
                            self.exchange_backoff_cap)
                        obs.retry(attempt=xretry, backoff_s=backoff,
                                  what="exchange")
                        emit(f"exchange drop at level {depth}: retry "
                             f"{xretry}/{self.exchange_retries} in "
                             f"{backoff:.2f}s")
                        if backoff > 0:
                            self._sleep(backoff)
                        continue
                    xretry = 0
                    out = pipe.launch(
                        self._step, tables, front, n_front, start_t,
                        nb, nbp, nba, nbprm, nn, base_gid,
                        fresh=self._fresh_jit,
                        label=f"level {depth} dispatch")
                    self._fresh_jit = False
                    (tables, nb, nbp, nba, nbprm, nn,
                     start_t) = out[:7]
                out, sc = pipe.collect(pull)
                reason, sent, gen_add, gfull_add, amp_add, act_add = sc
                exch_rows_useful += sent
                exch_bytes_useful += sent * _row_bytes()
                # generated is accumulated per dispatch attempt (a
                # paused attempt's committed tiles count once; its
                # replays in the window are discarded by drain())
                res.states_generated += gen_add
                self._act_counts += act_add
                if self._por_active:
                    self._por_kept += gen_add
                    self._por_full += gfull_add
                    self._por_amp += amp_add
                if reason == RUNNING:
                    pipe.drain()     # trailing tickets are no-ops
                    break
                pipe.drain()         # trailing tickets replay the pause
                if reason == R_VIOLATION:
                    viol_out = out[8]
                    vrows = self._pull(viol_out)
                    sel = vrows[vrows[:, 0] >= 0][0]
                    gid, va, vprm = (int(x) for x in sel)
                    res.ok = False
                    res.trace = self._trace(gid, extra=(va, vprm))
                    bad = spec.check_invariants(res.trace[-1].state)
                    if bad is None:
                        raise TLAError(
                            "device/interpreter divergence in sharded "
                            "BFS: interpreter accepts the replayed "
                            f"violation state (action "
                            f"{self.kern.action_names[va]})")
                    res.violated_invariant = bad
                    res.diameter = depth
                    _attach_exchange(res)
                    return self._finish(res, obs, fp_count)
                if reason == R_SLOT_ERR:
                    raise TLAError(
                        "dense-layout slot collision in sharded BFS "
                        "(see models/vsr.py docstring)")
                if reason == R_DEADLOCK:
                    dd = self._pull(out[11])
                    d = int(np.nonzero(dd >= 0)[0][0])
                    di = int(dd[d])
                    gid = int(base_dev[d]) + di
                    res.ok = False
                    res.error = "deadlock"
                    res.deadlock_state = self.codec.decode(
                        self._pk.unpack_row_np(
                            self._pull(front[d * F + di]))
                        if self._pk is not None else
                        {k: self._pull(v[d * F + di])
                         for k, v in front.items()})
                    res.trace = self._trace(gid)
                    res.diameter = depth
                    _attach_exchange(res)
                    return self._finish(res, obs, fp_count)
                if reason == R_BAG_GROW:
                    old = self.codec.shape.MAX_MSGS
                    old_pk = self._pk
                    self._build(old * 2)

                    def regrow_packed(garr):
                        # packed buffers round-trip through the OLD
                        # spec to dense, pad, re-pack under the rebuilt
                        # one (MAX_MSGS changes the lane count AND the
                        # spec version; see DeviceBFS._grow_msgs)
                        host = old_pk.unpack_np(self._pull(garr))
                        host = self.codec.pad_msgs(host, old)
                        return self._put(self._pk.pack_np(host))

                    # pad the message-table axis of every state array
                    def pad_msgs_global(g_dict, cap):
                        host = {k: self._pull(v).reshape(
                            (D, cap) + v.shape[1:])
                            for k, v in g_dict.items()}
                        out = {}
                        for k, v in host.items():
                            if k in self.codec.MSG_KEYS:
                                shape = list(v.shape)
                                shape[2] = (self.codec.shape.MAX_MSGS
                                            - old)
                                v = np.concatenate(
                                    [v, np.zeros(shape, v.dtype)],
                                    axis=2)
                            out[k] = self._put(v.reshape(
                                (D * cap,) + v.shape[2:]))
                        return out
                    if old_pk is not None:
                        front = regrow_packed(front)
                        nb = regrow_packed(nb)
                    else:
                        front = pad_msgs_global(front, F)
                        nb = pad_msgs_global(nb, self.N)
                    obs.grow("message_table", self.codec.shape.MAX_MSGS)
                    emit(f"message table grown to "
                         f"{self.codec.shape.MAX_MSGS} (recompiling)")
                elif reason == R_BUCKET_GROW:
                    self.bucket_cap *= 2
                    self._step = make_sharded_level(
                        self.kern, self._inv, self.mesh, self.axis,
                        self.tile, self.bucket_cap,
                        check_deadlock=self._ckd, pack_spec=self._pk,
                        commit=self.commit,
                        expand_caps=self.expand_caps,
                        canon=self._canon)
                    self._fresh_jit = True
                    obs.grow("exchange_bucket", self.bucket_cap)
                    emit(f"exchange bucket grown to {self.bucket_cap} "
                         f"(recompiling)")
                elif reason == R_EXPAND_GROW:
                    # fused commit: grow every cap to the exact
                    # rank-maxed observed need (ISSUE 10) — one
                    # recompile, no doubling guesses
                    need = np.asarray(self._pull(out[13]),
                                      np.int64).max(axis=0)
                    self._need_seen = np.maximum(self._need_seen, need)
                    grown = []
                    for a, name in enumerate(self.kern.action_names):
                        cap_a = self.expand_caps[a]
                        if int(self._need_seen[a]) > cap_a:
                            self.expand_caps[a] = min(
                                self.tile * self.kern._lane_count(name),
                                _align8(self._need_seen[a]))
                            grown.append((name, self.expand_caps[a]))
                    if not grown:   # defensive: strict growth anyway
                        a = int(np.argmax(need))
                        self.expand_caps[a] = min(
                            self.tile * self.kern._lane_count(
                                self.kern.action_names[a]),
                            self.expand_caps[a] * 2)
                        grown = [(self.kern.action_names[a],
                                  self.expand_caps[a])]
                    self._step = make_sharded_level(
                        self.kern, self._inv, self.mesh, self.axis,
                        self.tile, self.bucket_cap,
                        check_deadlock=self._ckd, pack_spec=self._pk,
                        commit=self.commit,
                        expand_caps=self.expand_caps,
                        canon=self._canon)
                    self._fresh_jit = True
                    for _n, cap in grown:
                        obs.grow("expand_buffer", cap)
                    emit("expand caps grown to exact need: "
                         + ", ".join(f"{n}={c}" for n, c in grown)
                         + " (recompiling)")
                elif reason == R_NEXT_GROW:
                    new_n = self.N * 2
                    nb = (self._grow_global(nb, self.N, new_n)
                          if self._pk is not None else
                          {k: self._grow_global(v, self.N, new_n)
                           for k, v in nb.items()})
                    nbp = self._grow_global(nbp, self.N, new_n)
                    nba = self._grow_global(nba, self.N, new_n)
                    nbprm = self._grow_global(nbprm, self.N, new_n)
                    self.N = new_n
                    self._fresh_jit = True   # shape change: retrace
                    obs.grow("next_buffer", new_n)
                    emit(f"next-frontier grown to {new_n}/device")
                elif reason == R_FPSET_GROW:
                    slots = self._pull(tables["slots"])
                    grown = [fp_grow({"slots": jnp.asarray(slots[d])}
                                     )["slots"] for d in range(D)]
                    self.fp_cap = int(grown[0].shape[0])
                    tables = {"slots": self._put(np.stack(
                        [np.asarray(g) for g in grown]))}
                    self._fresh_jit = True   # shape change: retrace
                    obs.grow("fpset", self.fp_cap)
                    emit(f"FPSet shards grown to {self.fp_cap}/device")
                else:
                    raise TLAError(f"unknown sharded reason {reason}")

            # committed tiles this level x full static bucket volume
            # (generated was already accumulated per dispatch attempt)
            with obs.timer("host_sync"):
                tiles_lvl = int(self._pull(start_t).max())
                wire = tiles_lvl * D * D * self.bucket_cap
                exch_rows_wire += wire
                exch_bytes_wire += wire * _row_bytes()
                nn_h = self._pull(nn)
            # occupancy accounting (ISSUE 10): expand lanes dispatched
            # this level, under the cap set in effect
            self._tiles_done += tiles_lvl * D
            self._lanes_disp += tiles_lvl * D * self._lanes_per_tile()
            n_next = int(nn_h.sum())
            fp_count += n_next
            obs.level_done(depth, frontier=front_total,
                           distinct=fp_count,
                           generated=res.states_generated)
            if n_next:
                with obs.timer("host_sync"):
                    self._h_parent.append(
                        self._pull_rows(nbp, nn_h).astype(np.int64))
                    self._h_action.append(self._pull_rows(nba, nn_h))
                    self._h_param.append(self._pull_rows(nbprm, nn_h))
                self.level_sizes.append(n_next)
                self._dev_distinct += nn_h
            # gid bases of the new frontier (device-order concatenation)
            base_dev = (sum(self.level_sizes[:-1])
                        + np.concatenate([[0], np.cumsum(nn_h)[:-1]]))
            front = nb
            F = self.N
            n_front = nn

            # pending preemption (supervisor's PreemptionGuard) forces
            # a rescue snapshot at this boundary; the decision is
            # rank-agreed like every wall-clock one (n_next is a global
            # sum, so the agree() call pattern matches across ranks)
            rescue = preempt_signal()
            want_rescue = bool(n_next) and agree(rescue is not None)
            if checkpoint_path and n_next and (want_rescue or agree(
                    checkpoint_every is None or
                    _time.time() - last_checkpoint >= checkpoint_every)):
                from ..engine.checkpoint import (save_checkpoint,
                                                 spec_digest)
                # the pulls are collectives in multi-process mode —
                # every process participates; only rank 0 writes
                ck_slots = self._pull(tables["slots"])
                # snapshots always store DENSE planes — the interchange
                # format any engine/pack configuration can resume
                ck_front = (self._pk.unpack_np(
                    self._pull_rows(front, nn_h))
                    if self._pk is not None else
                    {k: self._pull_rows(v, nn_h)
                     for k, v in front.items()})
                if jax.process_index() == 0:
                    save_checkpoint(
                        checkpoint_path,
                        slots=ck_slots,
                        frontier=ck_front,
                        n_front=n_next,
                        h_parent=np.concatenate(self._h_parent),
                        h_action=np.concatenate(self._h_action),
                        h_param=np.concatenate(self._h_param),
                        init_dense=[self.codec.encode(st)
                                    for st in self._init_states],
                        level_sizes=self.level_sizes, depth=depth,
                        fp_count=fp_count,
                        states_generated=res.states_generated,
                        max_msgs=self.codec.shape.MAX_MSGS,
                        expand_mults=[],
                        elapsed=_time.time() - t0,
                        digest=spec_digest(spec),
                        pack=self._pack_manifest(),
                        canon=self._canon_manifest(),
                        bounds=self._bounds_manifest(),
                        por=self._por_manifest(), obs=obs,
                        extra={"sharded": True,
                               "shard_counts": [int(x) for x in nn_h],
                               "bucket_cap": self.bucket_cap,
                               "fp_cap": self.fp_cap, "N": self.N,
                               "dev_distinct": [int(x) for x in
                                                self._dev_distinct],
                               "exchange": {
                                   "useful_rows": exch_rows_useful,
                                   "wire_rows": exch_rows_wire,
                                   "useful_bytes": exch_bytes_useful,
                                   "wire_bytes": exch_bytes_wire}})
                last_checkpoint = _time.time()
                obs.checkpoint(checkpoint_path, depth, fp_count)
                emit(f"checkpoint written to {checkpoint_path} "
                     f"(depth {depth}, {fp_count} distinct)")
            if want_rescue:
                sig = rescue or "SIGTERM"
                obs.rescue(checkpoint_path or "", depth, fp_count, sig)
                emit(f"preempted by {sig}: rescue snapshot at depth "
                     f"{depth} ({checkpoint_path}); exiting resumable")
                _attach_exchange(res)
                raise Preempted(checkpoint_path, depth, fp_count, sig)

            obs.progress(depth=depth, distinct=fp_count,
                         generated=res.states_generated)
            if max_seconds and agree(_time.time() - t0 > max_seconds):
                res.error = f"time budget {max_seconds}s reached"
                break
            if max_states and fp_count >= max_states:
                res.error = f"state limit {max_states} reached"
                break
            # proactive shard growth keeps in-level probe overflow rare
            if self._dev_distinct.max() > 0.4 * self.fp_cap:
                slots = self._pull(tables["slots"])
                grown = [fp_grow({"slots": jnp.asarray(slots[d])}
                                 )["slots"] for d in range(D)]
                self.fp_cap = int(grown[0].shape[0])
                tables = {"slots": self._put(np.stack(
                    [np.asarray(g) for g in grown]))}
                self._fresh_jit = True       # shape change: retrace
                obs.grow("fpset", self.fp_cap)
                emit(f"FPSet shards grown to {self.fp_cap}/device")

        res.diameter = depth
        _attach_exchange(res)
        return self._finish(res, obs, fp_count)

    def _finish(self, res, obs, fp_count):
        self._bounds_gauges(obs)
        self._por_gauges(obs)
        res.distinct_states = fp_count
        self._pack_gauges(obs)
        obs.gauge("symmetry_perms",
                  self._canon.perms if self._canon is not None
                  else self._sym_fold)
        if res.states_generated and fp_count:
            obs.gauge("orbit_ratio",
                      round(res.states_generated / fp_count, 4))
        cap_total = self.fp_cap * self.D
        obs.gauge("fpset_capacity", cap_total)
        obs.gauge("fpset_occupancy",
                  fp_count / cap_total if cap_total else 0.0)
        # mesh size of the run (compare_bench treats mesh mismatches
        # between docs as advisory — a 4-device run and an 8-device
        # run measure different regimes, not a regression)
        obs.gauge("mesh_devices", int(self.D))
        if hasattr(self, "_dev_distinct"):
            # per-shard distinct counts, reduced on host 0 (the only
            # rank that writes the metrics file / journal)
            obs.gauge("shard_distinct",
                      [int(x) for x in self._dev_distinct])
        acts = getattr(self, "_act_counts", None)
        if acts is not None:
            obs.gauge("action_expansions",
                      {n: int(c) for n, c in
                       zip(self.kern.action_names, acts)})
        # occupancy = real work items / expand lanes dispatched
        # (ISSUE 10); the sharded step always commits with ONE insert
        # batch per tile (the exchange receiver), in both commit modes
        lanes = getattr(self, "_lanes_disp", 0)
        if lanes and acts is not None:
            obs.gauge("occupancy",
                      round(float(acts.sum()) / lanes, 4))
        obs.gauge("inserts_per_tile", 1)
        obs.gauge("commit_mode", self.commit)
        return obs.finish(res,
                          levels=getattr(self, "level_sizes", None))

    def _lanes_per_tile(self):
        """Expand lanes one tile dispatches on one device: the fused
        caps, or the full T x L dense expansion in per-action mode."""
        if self.commit == "fused" and self.expand_caps is not None:
            return sum(
                min(self.tile * self.kern._lane_count(n),
                    max(8, int(c)))
                for n, c in zip(self.kern.action_names,
                                self.expand_caps))
        return self.tile * self.kern.n_lanes


def make_sharded_insert(mesh: Mesh, axis: str):
    """Insert a replicated fingerprint batch into the owning shards
    (used to register init states)."""
    n_dev = mesh.shape[axis]

    def ins(tables, fps, mask):
        tables = {k: v[0] for k, v in tables.items()}
        me = jax.lax.axis_index(axis)
        mine = mask & ((route(fps) % jnp.uint32(n_dev)).astype(jnp.int32)
                       == me)
        tables, fresh, ovf = insert_core(tables, fps, mine)
        return ({k: v[None] for k, v in tables.items()},
                jnp.asarray([fresh.sum()]), jnp.asarray([ovf]))

    return jax.jit(_shard_map(
        ins, mesh=mesh, in_specs=(P(axis), P(), P()),
        out_specs=(P(axis), P(axis), P(axis))))
