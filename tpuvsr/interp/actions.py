"""Next-state enumeration: the interpreter-side getNextStates.

Evaluates an action formula as a nondeterministic program over a mutable
trail of primed-variable bindings with backtracking:

  * conjunction  -> sequential composition (left-to-right, lazy)
  * disjunction  -> branch (fork the enumeration)
  * \\E           -> iterate the bound set in canonical order
  * x' = e       -> bind x's next value (or test, if already bound)
  * UNCHANGED t  -> bind every variable in the flattened tuple
  * operator call-> inline the definition when it (transitively) assigns
                    primes (Send/Broadcast/Discard wrappers, VSR.tla:247-270)

Each successful path yields once; the caller snapshots ``ctx.primes`` as
the successor state.  This reproduces TLC's action semantics including
the load-bearing laziness of SURVEY.md §2.7.1.
"""

from __future__ import annotations

from ..core.values import TLAError, tla_eq
from .evalr import Closure, EMPTY_ENV, Env, EvalCtx, Evaluator, _MISSING


class ActionEnumerator:
    def __init__(self, ev: Evaluator):
        self.ev = ev

    # ------------------------------------------------------------------
    def successors(self, expr, state):
        """Yield successor states (dict) for one action expr from state."""
        ctx = EvalCtx(state)
        for _ in self._enum(expr, EMPTY_ENV, ctx):
            primes = ctx.primes
            missing = self.ev.varnames - primes.keys()
            if missing:
                raise TLAError(
                    f"action left variables unassigned: {sorted(missing)}")
            yield dict(primes)

    def init_states(self, expr):
        """Enumerate initial states from an Init predicate."""
        ctx = EvalCtx({})
        for _ in self._enum_init(expr, EMPTY_ENV, ctx):
            missing = self.ev.varnames - ctx.state.keys()
            if missing:
                raise TLAError(f"Init left variables unassigned: {sorted(missing)}")
            yield dict(ctx.state)

    # ------------------------------------------------------------------
    def _enum(self, e, env: Env, ctx: EvalCtx):
        ev = self.ev
        tag = e[0]
        if tag == "and":
            yield from self._enum_seq(e[1], 0, env, ctx)
            return
        if tag == "or":
            saved = dict(ctx.primes)
            for item in e[1]:
                ctx.primes.clear()
                ctx.primes.update(saved)
                yield from self._enum(item, env, ctx)
            ctx.primes.clear()
            ctx.primes.update(saved)
            return
        if tag == "exists":
            saved = dict(ctx.primes)
            for binding in ev._group_bindings(e[1], env, ctx):
                ctx.primes.clear()
                ctx.primes.update(saved)
                yield from self._enum(e[2], env.extend(binding), ctx)
            ctx.primes.clear()
            ctx.primes.update(saved)
            return
        if tag == "binop" and e[1] == "eq" and e[2][0] == "prime" \
                and e[2][1][0] == "id":
            var = e[2][1][1]
            val = ev.eval(e[3], env, ctx)
            if var in ctx.primes:
                if tla_eq(ctx.primes[var], val):
                    yield
                return
            ctx.primes[var] = val
            yield
            ctx.primes.pop(var, None)
            return
        if tag == "unchanged":
            names = ev.collect_state_vars(e[1], env)
            added = []
            ok = True
            for name in names:
                cur = ctx.state[name]
                if name in ctx.primes:
                    if not tla_eq(ctx.primes[name], cur):
                        ok = False
                        break
                else:
                    ctx.primes[name] = cur
                    added.append(name)
            if ok:
                yield
            for name in added:
                ctx.primes.pop(name, None)
            return
        if tag == "call":
            name = e[1]
            if ev.touches_primes(name):
                d = ev.defs.get(name)
                args = [ev._arg_value(a, env, ctx) for a in e[2]]
                new_env = EMPTY_ENV.extend(dict(zip(d.params, args)))
                yield from self._enum(d.body, new_env, ctx)
                return
        if tag == "id":
            name = e[1]
            if ev.touches_primes(name):
                d = ev.defs.get(name)
                yield from self._enum(d.body, EMPTY_ENV, ctx)
                return
            v = env.lookup(name)
            if isinstance(v, tuple):
                # LET-bound action fragment
                yield from self._enum(v, env, ctx)
                return
        if tag == "if":
            c = ev.eval(e[1], env, ctx)
            yield from self._enum(e[2] if c is True else e[3], env, ctx)
            return
        if tag == "case":
            for guard, val in e[1]:
                if ev.eval(guard, env, ctx) is True:
                    yield from self._enum(val, env, ctx)
                    return
            if e[2] is not None:
                yield from self._enum(e[2], env, ctx)
                return
            raise TLAError("CASE: no arm matched in action")
        if tag == "let":
            new_env = ev._force_let(ev._let_env(e[1], env), ctx)
            yield from self._enum(e[2], new_env, ctx)
            return
        if tag == "not":
            # guard; cannot contain prime assignments
            if ev.eval(e, env, ctx) is True:
                yield
            return
        # default: pure guard
        v = ev.eval(e, env, ctx)
        if v is True:
            yield
        elif v is not False:
            raise TLAError(f"non-boolean conjunct in action: {e!r}")

    def _enum_seq(self, items, i, env, ctx):
        if i == len(items):
            yield
            return
        for _ in self._enum(items[i], env, ctx):
            yield from self._enum_seq(items, i + 1, env, ctx)

    # ------------------------------------------------------------------
    def _enum_init(self, e, env, ctx):
        ev = self.ev
        tag = e[0]
        if tag == "and":
            yield from self._enum_init_seq(e[1], 0, env, ctx)
            return
        if tag == "or":
            saved = dict(ctx.state)
            for item in e[1]:
                ctx.state.clear()
                ctx.state.update(saved)
                yield from self._enum_init(item, env, ctx)
            ctx.state.clear()
            ctx.state.update(saved)
            return
        if tag == "exists":
            saved = dict(ctx.state)
            for binding in ev._group_bindings(e[1], env, ctx):
                ctx.state.clear()
                ctx.state.update(saved)
                yield from self._enum_init(e[2], env.extend(binding), ctx)
            ctx.state.clear()
            ctx.state.update(saved)
            return
        if tag == "binop" and e[1] == "eq" and e[2][0] == "id" \
                and e[2][1] in ev.varnames:
            var = e[2][1]
            val = ev.eval(e[3], env, ctx)
            if var in ctx.state:
                if tla_eq(ctx.state[var], val):
                    yield
                return
            ctx.state[var] = val
            yield
            ctx.state.pop(var, None)
            return
        if tag == "binop" and e[1] == "in" and e[2][0] == "id" \
                and e[2][1] in ev.varnames and e[2][1] not in ctx.state:
            var = e[2][1]
            s = ev.eval(e[3], env, ctx)
            from .evalr import _sorted_set
            for x in _sorted_set(s):
                ctx.state[var] = x
                yield
            ctx.state.pop(var, None)
            return
        if tag == "let":
            new_env = ev._force_let(ev._let_env(e[1], env), ctx)
            yield from self._enum_init(e[2], new_env, ctx)
            return
        if tag == "call":
            d = ev.defs.get(e[1])
            if d is not None:
                args = [ev._arg_value(a, env, ctx) for a in e[2]]
                yield from self._enum_init(d.body, EMPTY_ENV.extend(dict(zip(d.params, args))), ctx)
                return
        if tag == "id":
            d = ev.defs.get(e[1])
            if d is not None and not d.params:
                yield from self._enum_init(d.body, EMPTY_ENV, ctx)
                return
        if tag == "if":
            c = ev.eval(e[1], env, ctx)
            yield from self._enum_init(e[2] if c is True else e[3], env, ctx)
            return
        v = ev.eval(e, env, ctx)
        if v is True:
            yield

    def _enum_init_seq(self, items, i, env, ctx):
        if i == len(items):
            yield
            return
        for _ in self._enum_init(items[i], env, ctx):
            yield from self._enum_init_seq(items, i + 1, env, ctx)
