"""TLA+ expression evaluator and next-state enumerator (the oracle engine).

This is the semantic core replacing TLC's ``Tool``/``Worker`` expression
machinery (SURVEY.md §1.2): lazy left-to-right conjunct evaluation (the
reference depends on it — the dead ``m.commit`` field access at
VSR.tla:421 must never be evaluated eagerly, SURVEY.md §2.7.1),
existential enumeration, primed-variable binding during action
evaluation, UNCHANGED frame expansion through tuple-valued definitions
(``vars``/``rep_state_vars`` at VSR.tla:140-147), and deterministic
CHOOSE (SURVEY.md §2.7.5 — we pick the least satisfying element under
``value_key``'s canonical total order).

The enumerator yields one successor binding per nondeterministic branch:
disjunctions fork, ``\\E`` iterates its (sorted) domain, and ``x' = e``
binds x's next-state value.  This mirrors TLC's getNextStates and is the
behavior the JAX transition kernel is differentially tested against.
"""

from __future__ import annotations

import itertools

from ..core.values import (FnVal, ModelValue, TLAError, fmt, mk_seq,
                           tla_eq, value_key)
from ..frontend.tla_ast import Def, Module


class SymbolicSet:
    """Nat / Int / record-set / function-set: membership without enumeration."""

    def __init__(self, name, contains):
        self.name = name
        self.contains = contains

    def __repr__(self):
        return self.name


NAT = SymbolicSet("Nat", lambda v: isinstance(v, int) and not isinstance(v, bool) and v >= 0)
INT = SymbolicSet("Int", lambda v: isinstance(v, int) and not isinstance(v, bool))


class Closure:
    __slots__ = ("params", "body", "env", "evaluator", "name")

    def __init__(self, params, body, env, name="LAMBDA"):
        self.params = params
        self.body = body
        self.env = env
        self.name = name


class Env:
    """Immutable chained environment for bound variables and LET defs."""
    __slots__ = ("mapping", "parent")

    def __init__(self, mapping=None, parent=None):
        self.mapping = mapping if mapping is not None else {}
        self.parent = parent

    def lookup(self, name):
        env = self
        while env is not None:
            v = env.mapping.get(name, _MISSING)
            if v is not _MISSING:
                return v
            env = env.parent
        return _MISSING

    def extend(self, mapping):
        return Env(mapping, self)

    def is_empty(self):
        return not self.mapping and (
            self.parent is None or self.parent.is_empty())


_MISSING = object()
EMPTY_ENV = Env()


class EvalCtx:
    """Per-evaluation mutable context: current state and primed bindings."""
    __slots__ = ("state", "primes")

    def __init__(self, state, primes=None):
        self.state = state
        self.primes = primes if primes is not None else {}


def _sorted_set(s):
    if isinstance(s, frozenset):
        return sorted(s, key=value_key)
    raise TLAError(f"cannot enumerate non-finite set {s!r}")


class Evaluator:
    def __init__(self, module: Module, constants: dict):
        self.module = module
        self.constants = dict(constants)
        self.varnames = set(module.variables)
        self.defs = module.defs
        self._prime_touch = {}
        self.cur_ctx = None
        self._builtins = _make_builtins(self)

    # ------------------------------------------------------------------
    # static analysis: does a definition (transitively) assign primes?
    # ------------------------------------------------------------------
    def touches_primes(self, name: str) -> bool:
        cached = self._prime_touch.get(name)
        if cached is not None:
            return cached
        d = self.defs.get(name)
        if d is None:
            self._prime_touch[name] = False
            return False
        self._prime_touch[name] = False  # cycle guard (RECURSIVE defs)
        res = self._expr_touches(d.body)
        self._prime_touch[name] = res
        return res

    def _expr_touches(self, e) -> bool:
        if isinstance(e, Def):
            return self._expr_touches(e.body)
        if isinstance(e, list):
            return any(self._expr_touches(x) for x in e)
        if not isinstance(e, tuple):
            return False
        if not e or not isinstance(e[0], str):
            return any(self._expr_touches(x) for x in e)
        tag = e[0]
        if tag in ("prime", "unchanged"):
            return True
        if tag == "call":
            return self.touches_primes(e[1]) or \
                any(self._expr_touches(a) for a in e[2])
        if tag == "id":
            return self.touches_primes(e[1])
        return any(self._expr_touches(x) for x in e[1:])

    # ------------------------------------------------------------------
    # plain (state-level) evaluation
    # ------------------------------------------------------------------
    def eval(self, e, env: Env, ctx: EvalCtx):
        self.cur_ctx = ctx
        tag = e[0]
        m = getattr(self, "_eval_" + tag, None)
        if m is None:
            raise TLAError(f"cannot evaluate {tag} expression: {e!r}")
        return m(e, env, ctx)

    def _eval_num(self, e, env, ctx):
        return e[1]

    def _eval_str(self, e, env, ctx):
        return e[1]

    def _eval_bool(self, e, env, ctx):
        return e[1]

    def _eval_at(self, e, env, ctx):
        v = env.lookup("@")
        if v is _MISSING:
            raise TLAError("@ used outside EXCEPT")
        return v

    def resolve_id(self, name, env, ctx):
        v = env.lookup(name)
        if v is not _MISSING:
            if isinstance(v, _LazyThunk):
                return v.force()
            return v
        d = self.defs.get(name)
        if d is not None:
            if d.params:
                return Closure(d.params, d.body, EMPTY_ENV, name)
            return self.eval(d.body, EMPTY_ENV, ctx)
        if name in self.constants:
            return self.constants[name]
        if name in self.varnames:
            if name not in ctx.state:
                raise TLAError(f"variable {name} unbound")
            return ctx.state[name]
        b = self._builtins.get(name)
        if b is not None:
            return b
        raise TLAError(f"unknown identifier {name}")

    def _eval_id(self, e, env, ctx):
        return self.resolve_id(e[1], env, ctx)

    def _eval_prime(self, e, env, ctx):
        inner = e[1]
        if inner[0] != "id":
            raise TLAError("prime applied to non-variable")
        name = inner[1]
        if name in ctx.primes:
            return ctx.primes[name]
        raise TLAError(f"primed variable {name}' read before assignment")

    def apply_op(self, fn, args, env, ctx):
        if isinstance(fn, Closure):
            if len(fn.params) != len(args):
                raise TLAError(f"arity mismatch calling {fn.name}")
            return self.eval(fn.body, fn.env.extend(dict(zip(fn.params, args))), ctx)
        if callable(fn):
            return fn(*args)
        raise TLAError(f"not an operator: {fn!r}")

    def _arg_value(self, a, env, ctx):
        """Evaluate a call argument; operator-valued args become closures."""
        if a[0] == "lambda":
            return Closure(a[1], a[2], env)
        if a[0] == "id":
            # identifier naming an operator with params -> closure
            name = a[1]
            if env.lookup(name) is _MISSING and name not in self.constants \
                    and name not in self.varnames:
                d = self.defs.get(name)
                if d is not None and d.params:
                    return Closure(d.params, d.body, EMPTY_ENV, name)
                b = self._builtins.get(name)
                if b is not None and name not in ("Nat", "Int"):
                    return b
        return self.eval(a, env, ctx)

    def _eval_call(self, e, env, ctx):
        name = e[1]
        args = [self._arg_value(a, env, ctx) for a in e[2]]
        fn = env.lookup(name)
        if fn is _MISSING:
            d = self.defs.get(name)
            if d is not None:
                fn = Closure(d.params, d.body, EMPTY_ENV, name)
            else:
                fn = self._builtins.get(name)
                if fn is None:
                    raise TLAError(f"unknown operator {name}")
        return self.apply_op(fn, args, env, ctx)

    def _eval_lambda(self, e, env, ctx):
        return Closure(e[1], e[2], env)

    def _eval_and(self, e, env, ctx):
        for item in e[1]:
            v = self.eval(item, env, ctx)
            if v is not True:
                if v is False:
                    return False
                raise TLAError(f"non-boolean in conjunction: {fmt(v)}")
        return True

    def _eval_or(self, e, env, ctx):
        for item in e[1]:
            v = self.eval(item, env, ctx)
            if v is not False:
                if v is True:
                    return True
                raise TLAError(f"non-boolean in disjunction: {fmt(v)}")
        return False

    def _eval_not(self, e, env, ctx):
        v = self.eval(e[1], env, ctx)
        if not isinstance(v, bool):
            raise TLAError("~ applied to non-boolean")
        return not v

    def _eval_neg(self, e, env, ctx):
        return -self.eval(e[1], env, ctx)

    def _eval_binop(self, e, env, ctx):
        op = e[1]
        if op == "implies":
            a = self.eval(e[2], env, ctx)
            if a is False:
                return True
            return self.eval(e[3], env, ctx) is True
        a = self.eval(e[2], env, ctx)
        b = self.eval(e[3], env, ctx)
        if op == "eq":
            return tla_eq(a, b)
        if op == "ne":
            return not tla_eq(a, b)
        if op == "in":
            return _member(a, b)
        if op == "notin":
            return not _member(a, b)
        if op == "lt":
            return a < b
        if op == "le":
            return a <= b
        if op == "gt":
            return a > b
        if op == "ge":
            return a >= b
        if op == "plus":
            return a + b
        if op == "minus":
            return a - b
        if op == "times":
            return a * b
        if op == "div":
            return a // b
        if op == "mod":
            return a % b
        if op == "range":
            return frozenset(range(a, b + 1))
        if op == "union":
            return a | b
        if op == "intersect":
            return a & b
        if op == "setdiff":
            return a - b
        if op == "subseteq":
            return a <= b
        if op == "merge":
            return a.merge_left(b)
        if op == "mapsto":
            return FnVal([(a, b)])
        if op == "equiv":
            return a == b
        if op == "concat":
            return mk_seq(a.seq_elems() + b.seq_elems())
        raise TLAError(f"unknown binop {op}")

    def _eval_tuple(self, e, env, ctx):
        return mk_seq(self.eval(x, env, ctx) for x in e[1])

    def _eval_setenum(self, e, env, ctx):
        return frozenset(self.eval(x, env, ctx) for x in e[1])

    def _eval_setfilter(self, e, env, ctx):
        _, var, sexpr, pred = e
        s = self.eval(sexpr, env, ctx)
        out = []
        for x in _sorted_set(s):
            if self.eval(pred, env.extend({var: x}), ctx) is True:
                out.append(x)
        return frozenset(out)

    def _eval_setmap(self, e, env, ctx):
        _, elem, groups = e
        out = []
        for binding in self._group_bindings(groups, env, ctx):
            out.append(self.eval(elem, env.extend(binding), ctx))
        return frozenset(out)

    def _group_bindings(self, groups, env, ctx):
        """Iterate bindings for [(names, set_expr)...] quantifier groups."""
        evaluated = []
        for names, sexpr in groups:
            s = self.eval(sexpr, env, ctx)
            elems = _sorted_set(s)
            for n in names:
                evaluated.append((n, elems))
        names = [n for n, _ in evaluated]
        for combo in itertools.product(*[el for _, el in evaluated]):
            yield dict(zip(names, combo))

    def _eval_fnctor(self, e, env, ctx):
        _, groups, body = e
        if len(groups) == 1 and len(groups[0][0]) == 1:
            var = groups[0][0][0]
            s = self.eval(groups[0][1], env, ctx)
            return FnVal((x, self.eval(body, env.extend({var: x}), ctx))
                         for x in _sorted_set(s))
        # multi-binder functions map tuples -> value
        pairs = []
        for binding in self._group_bindings(groups, env, ctx):
            key = mk_seq(binding.values())
            pairs.append((key, self.eval(body, env.extend(binding), ctx)))
        return FnVal(pairs)

    def _eval_record(self, e, env, ctx):
        return FnVal((name, self.eval(v, env, ctx)) for name, v in e[1])

    def _eval_recordset(self, e, env, ctx):
        fields = [(n, self.eval(v, env, ctx)) for n, v in e[1]]

        def contains(v):
            if not isinstance(v, FnVal):
                return False
            if v.domain() != frozenset(n for n, _ in fields):
                return False
            return all(_member(v.apply(n), s) for n, s in fields)
        return SymbolicSet("[record set]", contains)

    def _eval_fnset(self, e, env, ctx):
        dom = self.eval(e[1], env, ctx)
        rng = self.eval(e[2], env, ctx)

        def contains(v):
            if not isinstance(v, FnVal):
                return False
            if isinstance(dom, frozenset) and v.domain() != dom:
                return False
            return all(_member(x, rng) for _, x in v.items)
        return SymbolicSet("[fn set]", contains)

    def _eval_except(self, e, env, ctx):
        f = self.eval(e[1], env, ctx)
        for path, valexpr in e[2]:
            keys = []
            for kind, x in path:
                keys.append(x if kind == "fld" else self.eval(x, env, ctx))
            f = self._except_update(f, keys, valexpr, env, ctx)
        return f

    def _except_update(self, f, keys, valexpr, env, ctx):
        if not isinstance(f, FnVal):
            raise TLAError("EXCEPT applied to non-function")
        k = keys[0]
        old = f.apply(k)
        if len(keys) == 1:
            new = self.eval(valexpr, env.extend({"@": old}), ctx)
        else:
            new = self._except_update(old, keys[1:], valexpr, env, ctx)
        return f.updated(k, new)

    def _eval_apply(self, e, env, ctx):
        f = self.eval(e[1], env, ctx)
        k = self.eval(e[2], env, ctx)
        if isinstance(f, FnVal):
            return f.apply(k)
        raise TLAError(f"applying non-function {fmt(f)}")

    def _eval_dot(self, e, env, ctx):
        f = self.eval(e[1], env, ctx)
        if isinstance(f, FnVal):
            return f.apply(e[2])
        raise TLAError(f"field access on non-record {fmt(f)}.{e[2]}")

    def _eval_domain(self, e, env, ctx):
        f = self.eval(e[1], env, ctx)
        if isinstance(f, FnVal):
            return f.domain()
        raise TLAError("DOMAIN of non-function")

    def _eval_powerset(self, e, env, ctx):
        s = self.eval(e[1], env, ctx)
        elems = _sorted_set(s)
        out = []
        for r in range(len(elems) + 1):
            for combo in itertools.combinations(elems, r):
                out.append(frozenset(combo))
        return frozenset(out)

    def _eval_bigunion(self, e, env, ctx):
        s = self.eval(e[1], env, ctx)
        out = frozenset()
        for x in s:
            out |= x
        return out

    def _eval_if(self, e, env, ctx):
        c = self.eval(e[1], env, ctx)
        if c is True:
            return self.eval(e[2], env, ctx)
        if c is False:
            return self.eval(e[3], env, ctx)
        raise TLAError("IF condition not boolean")

    def _eval_case(self, e, env, ctx):
        for guard, val in e[1]:
            if self.eval(guard, env, ctx) is True:
                return self.eval(val, env, ctx)
        if e[2] is not None:
            return self.eval(e[2], env, ctx)
        raise TLAError("CASE: no arm matched and no OTHER")

    def _let_env(self, defs, env):
        mapping = {}
        new_env = env.extend(mapping)
        for d in defs:
            if d.params:
                mapping[d.name] = Closure(d.params, d.body, new_env, d.name)
            else:
                mapping[d.name] = _LazyLet(d, new_env)
        return new_env

    def _eval_let(self, e, env, ctx):
        return self.eval(e[2], self._force_let(self._let_env(e[1], env), ctx), ctx)

    def _force_let(self, env, ctx):
        # resolve 0-ary LET defs lazily on first lookup
        for k, v in list(env.mapping.items()):
            if isinstance(v, _LazyLet):
                env.mapping[k] = _LazyThunk(self, v, ctx)
        return env

    def _eval_exists(self, e, env, ctx):
        for binding in self._group_bindings(e[1], env, ctx):
            if self.eval(e[2], env.extend(binding), ctx) is True:
                return True
        return False

    def _eval_forall(self, e, env, ctx):
        for binding in self._group_bindings(e[1], env, ctx):
            if self.eval(e[2], env.extend(binding), ctx) is not True:
                return False
        return True

    def _eval_choose(self, e, env, ctx):
        _, var, sexpr, body = e
        s = self.eval(sexpr, env, ctx)
        for x in _sorted_set(s):
            if self.eval(body, env.extend({var: x}), ctx) is True:
                return x
        raise TLAError("CHOOSE: no element satisfies predicate")

    def _eval_unchanged(self, e, env, ctx):
        # boolean context (e.g. evaluating [Next]_vars stutter check)
        for name in self.collect_state_vars(e[1], env):
            if name not in ctx.primes or not tla_eq(ctx.primes[name], ctx.state[name]):
                return False
        return True

    # ------------------------------------------------------------------
    # UNCHANGED frame expansion
    # ------------------------------------------------------------------
    def collect_state_vars(self, e, env):
        """Flatten a tuple/def/var expression into state-variable names
        (handles the nested tuples-of-vars idiom at VSR.tla:140-147)."""
        out = []
        self._collect_vars(e, env, out)
        return out

    def _collect_vars(self, e, env, out):
        tag = e[0]
        if tag == "tuple":
            for x in e[1]:
                self._collect_vars(x, env, out)
            return
        if tag == "id":
            name = e[1]
            if name in self.varnames:
                out.append(name)
                return
            d = self.defs.get(name)
            if d is not None and not d.params:
                self._collect_vars(d.body, env, out)
                return
            v = env.lookup(name)
            if isinstance(v, tuple):
                self._collect_vars(v, env, out)
                return
            raise TLAError(f"UNCHANGED operand {name} is not a variable tuple")
        raise TLAError(f"cannot flatten UNCHANGED operand {e!r}")


class _LazyLet:
    __slots__ = ("d", "env")

    def __init__(self, d, env):
        self.d = d
        self.env = env


class _LazyThunk:
    """Memoized 0-ary LET binding (evaluated on first use, per TLC)."""
    __slots__ = ("ev", "lazy", "ctx", "_val", "_done")

    def __init__(self, ev, lazy, ctx):
        self.ev = ev
        self.lazy = lazy
        self.ctx = ctx
        self._done = False
        self._val = None

    def force(self):
        if not self._done:
            self._val = self.ev.eval(self.lazy.d.body, self.lazy.env, self.ctx)
            self._done = True
        return self._val


def _member(a, b):
    if isinstance(b, frozenset):
        return a in b
    if isinstance(b, SymbolicSet):
        return b.contains(a)
    raise TLAError(f"\\in applied to non-set {b!r}")


# ----------------------------------------------------------------------
# Builtin operator library (the EXTENDS closure: Naturals, FiniteSets,
# FiniteSetsExt, Sequences, SequencesExt, TLC, TLCExt — VSR.tla:89)
# ----------------------------------------------------------------------
def _make_builtins(ev: Evaluator):
    def _len(s):
        if not isinstance(s, FnVal):
            raise TLAError("Len of non-sequence")
        return len(s)

    def _append(s, x):
        return s.seq_append(x)

    def _head(s):
        return s.apply(1)

    def _tail(s):
        return mk_seq(s.seq_elems()[1:])

    def _subseq(s, a, b):
        return mk_seq(s.seq_elems()[a - 1:b])

    def _card(s):
        if isinstance(s, frozenset):
            return len(s)
        raise TLAError("Cardinality of non-finite set")

    def _quantify(s, pred):
        n = 0
        ctx = ev.cur_ctx
        for x in _sorted_set(s):
            if ev.apply_op(pred, [x], EMPTY_ENV, ctx) is True:
                n += 1
        return n

    def _max(s):
        return max(s)

    def _min(s):
        return min(s)

    def _permutations(s):
        elems = _sorted_set(s)
        perms = []
        for p in itertools.permutations(elems):
            perms.append(FnVal(zip(elems, p)))
        return frozenset(perms)

    def _assert(cond, msg):
        if cond is not True:
            raise TLAError(f"Assert failed: {msg}")
        return True

    def _print(val, out=True):
        print(fmt(val))
        return out

    def _tostring(v):
        return fmt(v)

    def _isfiniteset(s):
        return isinstance(s, frozenset)

    def _range(f):
        return frozenset(v for _, v in f.items)

    def _settoseq(s):
        return mk_seq(_sorted_set(s))

    def _fold_set(op, base, s):
        acc = base
        for x in _sorted_set(s):
            acc = ev.apply_op(op, [x, acc], EMPTY_ENV, ev.cur_ctx)
        return acc

    return {
        "Nat": NAT, "Int": INT,
        "Len": _len, "Append": _append, "Head": _head, "Tail": _tail,
        "SubSeq": _subseq, "Seq": lambda s: SymbolicSet("Seq", lambda v: isinstance(v, FnVal) and v.is_sequence()),
        "Cardinality": _card, "IsFiniteSet": _isfiniteset,
        "Quantify": _quantify, "Max": _max, "Min": _min,
        "FoldSet": _fold_set, "Range": _range, "SetToSeq": _settoseq,
        "Permutations": _permutations,
        "Assert": _assert, "Print": _print, "PrintT": lambda v: _print(v, True),
        "ToString": _tostring,
    }
