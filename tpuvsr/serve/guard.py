"""Edge hardening + overload protection for the serving tier
(ISSUE 18 tentpole).

The HTTP front (``serve/http.py``) trusted every byte it received:
no auth, no rate limits, no request bounds, and a crash-looping job
could burn the pool's restart budget while occupying devices.  This
module is the one place those policies live; the front and the worker
only ask questions and map :class:`GuardDenied` onto wire codes:

* **Bearer-token auth** — per-tenant tokens in a spool-local
  ``tokens.json`` (``{"tenant": "secret", ...}``), verified with a
  constant-time compare over EVERY entry (no early exit for a wrong
  tenant, no timing oracle for token length prefixes).  A missing
  ``tokens.json`` means open mode — exactly the pre-ISSUE-18 trust
  model, so single-user spools keep working unchanged.  Missing or
  wrong credentials are 401; a VALID token acting on another tenant's
  behalf (cross-tenant submit or cancel) is 403.  Both are journaled
  as ``auth_denied``.

* **Per-tenant token-bucket rate limits** — ``rate`` requests/second
  refill, ``burst`` capacity.  The bucket state is a **pure fold over
  journal timestamps**, not wall-clock mutation: accepted submissions
  are replayed off ``jobs.jsonl`` (the submit records the queue
  already fsyncs) and denials off ``guard.jsonl``, merged in ts
  order — so a fresh Guard over the same spool reconverges to the
  same bucket state (the telemetry-fold discipline, ISSUE 17), and
  the 429 ``Retry-After`` is computed from the deficit's refill time,
  not guessed.

* **Queue-depth backpressure** — a spool backlog past ``high_water``
  means the fleet is saturated: new submissions get 503 with the
  depth in the body (journaled ``backpressure``) instead of silently
  growing an unbounded queue.

* **Circuit breaker per (tenant, spec-digest)** — K engine failures
  inside a rolling window trip the breaker OPEN (journaled
  ``breaker_open``): further submissions of that same spec fail fast
  with reason ``"breaker-open"`` before touching a device.  After a
  cooldown (the shared bounded-exponential curve,
  ``resilience/backoff.py`` — doubled on every re-trip) the breaker
  HALF-OPENs: one probe runs; success closes it (journaled
  ``breaker_close``), failure re-opens with a longer cooldown.

Every rejection is journaled to ``<spool>/guard.jsonl`` (schema
events ``auth_denied`` / ``rate_limited`` / ``backpressure`` /
``breaker_open`` / ``breaker_close``), which the telemetry aggregator
tails — so the abuse counters on ``/v1/metrics`` are journal-derived
and restart-convergent like every other fold in the system.

jax-free and engine-free: the front stays milliseconds.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import math
import os
import threading

from ..obs.journal import Journal

#: default request body cap (bytes) — a submit body is a small JSON
#: object; anything near this size is abuse or a bug
MAX_BODY = 1 << 20

#: default header/read timeout (seconds) for one HTTP request — the
#: slow-loris reap: a connection that dribbles bytes slower than this
#: is closed, not indulged
REQUEST_TIMEOUT = 10.0


class GuardDenied(Exception):
    """A guarded request was rejected.  ``code`` is the HTTP status
    the front maps it to (401/403/413/429/503), ``reason`` the
    journaled/wire explanation, ``retry_after`` the 429 refill hint
    (seconds, None = no header)."""

    def __init__(self, code, reason, *, tenant=None, retry_after=None,
                 depth=None):
        super().__init__(reason)
        self.code = int(code)
        self.reason = reason
        self.tenant = tenant
        self.retry_after = retry_after
        self.depth = depth


def spec_digest(spec, cfg=None):
    """The breaker's spec identity: one digest over (spec, cfg), so a
    crash-looping submission trips its OWN breaker and never a
    sibling spec's."""
    h = hashlib.sha1()
    h.update(str(spec).encode())
    h.update(b"\x00")
    h.update(str(cfg or "").encode())
    return h.hexdigest()[:16]


class TokenBucket:
    """A token bucket advanced by EXPLICIT timestamps (never the wall
    clock): ``advance(ts)`` refills ``rate`` tokens/second up to
    ``burst``; ``take(ts)`` consumes one.  Folding the same (ts,
    take/deny) sequence always lands in the same state — the
    determinism the restart-convergence battery holds."""

    __slots__ = ("rate", "burst", "tokens", "last_ts")

    def __init__(self, rate, burst):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self.last_ts = None

    def advance(self, ts):
        ts = float(ts)
        if self.last_ts is None:
            self.last_ts = ts
            return
        dt = ts - self.last_ts
        if dt > 0:
            self.tokens = min(self.burst, self.tokens + dt * self.rate)
            self.last_ts = ts

    def take(self, ts):
        """Advance to ``ts`` and consume one token (flooring at zero —
        the replay of an accepted submission must never go negative)."""
        self.advance(ts)
        self.tokens = max(0.0, self.tokens - 1.0)

    def ok(self, ts):
        self.advance(ts)
        return self.tokens >= 1.0

    def retry_after(self):
        """Seconds until one full token exists — the 429 Retry-After
        (integer-ceiled on the wire; at least 1)."""
        if self.rate <= 0:
            return None
        need = max(0.0, 1.0 - self.tokens)
        return need / self.rate


class CircuitBreaker:
    """closed -> open -> half-open -> closed, driven by explicit
    timestamps.  ``k`` failures inside ``window`` seconds trip it;
    after a cooldown (bounded-exponential, doubled per re-trip) ONE
    probe is allowed; probe success closes, probe failure re-opens."""

    __slots__ = ("k", "window", "schedule", "failures", "state",
                 "opened_ts", "cooldown", "trips", "probing")

    def __init__(self, k=3, window=60.0, cooldown_base=2.0,
                 cooldown_cap=300.0):
        from ..resilience.backoff import BackoffSchedule
        self.k = max(1, int(k))
        self.window = float(window)
        self.schedule = BackoffSchedule(cooldown_base, cooldown_cap)
        self.failures = []       # recent failure timestamps
        self.state = "closed"
        self.opened_ts = None
        self.cooldown = 0.0
        self.trips = 0
        self.probing = False

    def allow(self, ts):
        """May a run of this key proceed at ``ts``?  Half-open grants
        exactly one in-flight probe per cooldown expiry."""
        ts = float(ts)
        if self.state == "closed":
            return True
        if self.state == "open" and ts - self.opened_ts >= self.cooldown:
            self.state = "half-open"
            self.probing = False
        if self.state == "half-open" and not self.probing:
            self.probing = True
            return True
        return False

    def record(self, ok, ts):
        """Fold one run outcome.  Returns ``"open"`` / ``"close"``
        when this outcome transitioned the breaker (the caller
        journals it), None otherwise."""
        ts = float(ts)
        if ok:
            if self.state in ("half-open", "open"):
                # a successful probe (or an out-of-band success)
                # closes the breaker and resets the cooldown curve
                self.state = "closed"
                self.probing = False
                self.failures = []
                self.schedule.reset()
                return "close"
            self.failures = []
            return None
        if self.state == "half-open":
            # the probe failed: re-open with a LONGER cooldown
            self.state = "open"
            self.probing = False
            self.opened_ts = ts
            self.cooldown = self.schedule.next()
            self.trips += 1
            return "open"
        if self.state == "open":
            return None
        self.failures = [t for t in self.failures
                         if ts - t <= self.window]
        self.failures.append(ts)
        if len(self.failures) >= self.k:
            self.state = "open"
            self.opened_ts = ts
            self.cooldown = self.schedule.next()
            self.trips += 1
            self.failures = []
            return "open"
        return None


class Guard:
    """The serving tier's admission guard over one spool (see module
    doc).  Thread-safe: the HTTP front's handler threads and the
    worker share one instance."""

    def __init__(self, spool, *, tokens_path=None, rate=None,
                 burst=None, max_inflight=None, high_water=None,
                 max_body=MAX_BODY, breaker_k=3, breaker_window=60.0,
                 breaker_cooldown=2.0, breaker_cooldown_cap=300.0,
                 log=None):
        self.spool = os.path.abspath(spool)
        self.tokens_path = (tokens_path if tokens_path is not None
                            else os.path.join(self.spool,
                                              "tokens.json"))
        self.journal_path = os.path.join(self.spool, "guard.jsonl")
        self.jobs_log = os.path.join(self.spool, "jobs.jsonl")
        self._drv = None             # lazy spool driver (ISSUE 20)
        self._jobs_cursor = None     # driver cursor over "jobs"
        self.rate = None if rate is None else float(rate)
        self.burst = (float(burst) if burst is not None
                      else (self.rate if self.rate else 1.0))
        self.max_inflight = (None if max_inflight is None
                             else max(1, int(max_inflight)))
        self.high_water = (None if high_water is None
                           else max(1, int(high_water)))
        self.max_body = int(max_body)
        self.breaker_k = int(breaker_k)
        self.breaker_window = float(breaker_window)
        self.breaker_cooldown = float(breaker_cooldown)
        self.breaker_cooldown_cap = float(breaker_cooldown_cap)
        self.log = log
        self._lock = threading.RLock()
        self._tokens = None          # tenant -> secret
        self._tokens_mtime = None
        self._buckets = {}           # tenant -> TokenBucket
        self._offsets = {}           # path -> consumed byte offset
        self._breakers = {}          # (tenant, digest) -> CircuitBreaker

    # -- journaling ----------------------------------------------------
    def _journal(self, event, ts, **fields):
        """Append one guard event at the DECISION's timestamp (the
        explicit ``ts`` kwarg overrides the Journal's wall-clock
        stamp), so the journaled fold replays the exact state the
        live decision saw."""
        j = Journal(self.journal_path, run_id="guard",
                    trace_id="", span_id="", parent_span="")
        try:
            j.write(event, ts=round(float(ts), 3), **fields)
        finally:
            j.close()
        # our own append is already folded into the live buckets:
        # skip it when guard.jsonl is next tailed
        try:
            self._offsets[self.journal_path] = \
                os.path.getsize(self.journal_path)
        except OSError:
            pass
        if self.log:
            self.log("guard: " + event + " "
                     + " ".join(f"{k}={v}" for k, v in fields.items()))

    # -- bearer-token auth ---------------------------------------------
    @property
    def auth_enabled(self):
        return bool(self._load_tokens())

    def _load_tokens(self):
        """``tokens.json`` with an mtime cache — operators rotate
        tokens by rewriting the file, no restart needed.  Absent or
        unreadable means open mode."""
        try:
            mtime = os.path.getmtime(self.tokens_path)
        except OSError:
            self._tokens, self._tokens_mtime = None, None
            return None
        with self._lock:
            if mtime != self._tokens_mtime:
                try:
                    with open(self.tokens_path) as f:
                        doc = json.load(f)
                    self._tokens = {str(k): str(v)
                                    for k, v in dict(doc).items()}
                except (OSError, ValueError, TypeError, AttributeError):
                    self._tokens = None
                self._tokens_mtime = mtime
            return self._tokens

    def authenticate(self, auth_header, *, ts, path=None):
        """Authorization header -> the token's tenant.  Open mode
        (no ``tokens.json``) returns None — no tenant is imposed.
        Every failure journals ``auth_denied`` and raises 401."""
        tokens = self._load_tokens()
        if not tokens:
            return None

        def deny(reason):
            self._journal("auth_denied", ts, reason=reason,
                          path=path)
            raise GuardDenied(401, reason)

        if not auth_header:
            deny("missing-authorization")
        parts = str(auth_header).split(None, 1)
        if len(parts) != 2 or parts[0].lower() != "bearer":
            deny("not-a-bearer-token")
        presented = parts[1].strip()
        # constant-time over EVERY entry: compare_digest for each
        # tenant, never an early exit on the first mismatch (a timing
        # oracle would leak which tenant names exist)
        matched = None
        for tenant, secret in tokens.items():
            if hmac.compare_digest(presented.encode(),
                                   str(secret).encode()):
                matched = tenant
        if matched is None:
            deny("unknown-token")
        return matched

    def authorize_tenant(self, auth_tenant, claimed, *, ts,
                         path=None, action="submit"):
        """The effective tenant of an authenticated request.  Open
        mode (``auth_tenant`` None) passes ``claimed`` through; with
        auth on, acting as ANOTHER tenant is a journaled 403 and an
        unclaimed tenant defaults to the token's own."""
        if auth_tenant is None:
            return claimed
        if claimed is not None and str(claimed) != str(auth_tenant):
            reason = f"cross-tenant-{action}"
            self._journal("auth_denied", ts, reason=reason,
                          tenant=auth_tenant, claimed=str(claimed),
                          path=path)
            raise GuardDenied(403, reason, tenant=auth_tenant)
        return auth_tenant

    # -- the deterministic rate fold -----------------------------------
    def _bucket(self, tenant):
        key = tenant or "-"
        b = self._buckets.get(key)
        if b is None:
            b = self._buckets[key] = TokenBucket(self.rate, self.burst)
        return b

    def _tail(self, path):
        """Complete new lines of one journal since the last poll
        (torn tails held back — the spool fold discipline)."""
        pos = self._offsets.get(path, 0)
        try:
            if os.path.getsize(path) <= pos:
                return
        except OSError:
            return
        try:
            with open(path) as f:
                f.seek(pos)
                while True:
                    line = f.readline()
                    if not line or not line.endswith("\n"):
                        break
                    self._offsets[path] = f.tell()
                    if line.strip():
                        yield line
        except OSError:
            return

    def refresh(self):
        """Fold journal lines appended since the last look into the
        bucket state: accepted submissions off ``jobs.jsonl``, denials
        off ``guard.jsonl``, merged in ts order — so the buckets are a
        pure function of the journals and a fresh Guard reconverges
        (incremental == fresh == restarted)."""
        if self.rate is None:
            return
        with self._lock:
            events = []              # (ts, taken?, tenant)
            # accepted submissions come off the spool DRIVER's jobs
            # stream (ISSUE 20) — auto-detected from the spool's
            # persisted config, so the same fold works whether the
            # records live in jobs.jsonl or the quorum replicas
            if self._drv is None:
                from ..service.spooldrv import open_driver
                self._drv = open_driver(self.spool)
            recs, self._jobs_cursor = self._drv.read(
                "jobs", self._jobs_cursor)
            for rec in recs:
                if rec.get("op") != "submit":
                    continue
                job = rec.get("job") or {}
                ts = rec.get("ts", job.get("submitted_ts"))
                if ts is None:
                    continue
                events.append((float(ts), True, job.get("tenant")))
            for line in self._tail(self.journal_path):
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("event") != "rate_limited":
                    continue
                try:
                    events.append((float(ev["ts"]), False,
                                   ev.get("tenant")))
                except (KeyError, TypeError, ValueError):
                    continue
            events.sort(key=lambda e: e[0])
            for ts, taken, tenant in events:
                b = self._bucket(tenant)
                if taken:
                    b.take(ts)
                else:
                    b.advance(ts)

    def admit_submission(self, tenant, *, ts, inflight=None):
        """May ``tenant`` submit at ``ts``?  Token-bucket rate first,
        then the in-flight quota.  A denial journals ``rate_limited``
        (advancing the folded clock exactly as a fresh fold would)
        and raises 429 with the refill-derived Retry-After."""
        # decisions run on ROUNDED ts — the same precision the journal
        # records — so a fresh fold replays exactly this bucket state
        ts = round(float(ts), 3)
        with self._lock:
            self.refresh()
            if self.rate is not None:
                b = self._bucket(tenant)
                if not b.ok(ts):
                    retry = b.retry_after()
                    retry_s = (None if retry is None
                               else max(1, int(math.ceil(retry))))
                    self._journal(
                        "rate_limited", ts,
                        tenant=str(tenant or "-"),
                        retry_after_s=round(retry or 0.0, 3),
                        reason="rate")
                    raise GuardDenied(
                        429, f"rate limit: tenant {tenant or '-'} "
                             f"over {self.rate:g} submits/s "
                             f"(burst {self.burst:g})",
                        tenant=tenant, retry_after=retry_s)
            if self.max_inflight is not None and inflight is not None \
                    and inflight >= self.max_inflight:
                self._journal(
                    "rate_limited", ts, tenant=str(tenant or "-"),
                    retry_after_s=0.0, reason="inflight-quota",
                    inflight=int(inflight))
                raise GuardDenied(
                    429, f"in-flight quota: tenant {tenant or '-'} "
                         f"has {inflight} unfinished job(s) "
                         f"(max {self.max_inflight})",
                    tenant=tenant, retry_after=1)
        # the accepted submission's jobs.jsonl record folds the token
        # consumption on the next refresh — the bucket state stays
        # journal-derived even on the accept path

    # -- backpressure --------------------------------------------------
    def admit_depth(self, depth, *, ts):
        """503 when the queue backlog is past the high-water mark —
        the spool must not become an unbounded buffer for a flood."""
        if self.high_water is None or depth < self.high_water:
            return
        self._journal("backpressure", ts, depth=int(depth),
                      high_water=int(self.high_water))
        raise GuardDenied(
            503, f"queue depth {depth} past high water "
                 f"{self.high_water}", depth=int(depth))

    # -- request bounds ------------------------------------------------
    def check_body_size(self, length):
        """413 on an oversized request body (checked off
        Content-Length BEFORE the body is read — an abusive client
        never makes the front buffer its payload)."""
        if length is not None and int(length) > self.max_body:
            raise GuardDenied(
                413, f"body of {int(length)} bytes exceeds the "
                     f"{self.max_body}-byte cap")

    # -- the circuit breaker -------------------------------------------
    def _breaker(self, tenant, digest):
        key = (tenant or "-", digest)
        br = self._breakers.get(key)
        if br is None:
            br = self._breakers[key] = CircuitBreaker(
                self.breaker_k, self.breaker_window,
                self.breaker_cooldown, self.breaker_cooldown_cap)
        return br

    def breaker_allow(self, tenant, digest, *, ts):
        """May a run of (tenant, spec-digest) proceed?  False means
        the breaker is open — the worker fails the job fast with
        reason ``"breaker-open"`` before any device time."""
        with self._lock:
            return self._breaker(tenant, digest).allow(ts)

    def breaker_record(self, tenant, digest, ok, *, ts):
        """Fold one run outcome into the breaker; transitions are
        journaled (``breaker_open`` / ``breaker_close``) so the
        telemetry fold counts them restart-convergently."""
        with self._lock:
            br = self._breaker(tenant, digest)
            moved = br.record(ok, ts)
            if moved == "open":
                self._journal(
                    "breaker_open", ts, tenant=str(tenant or "-"),
                    digest=digest, failures=int(self.breaker_k),
                    cooldown_s=round(br.cooldown, 3),
                    trips=br.trips)
            elif moved == "close":
                self._journal(
                    "breaker_close", ts, tenant=str(tenant or "-"),
                    digest=digest)
            return moved

    def breaker_state(self, tenant, digest):
        br = self._breakers.get((tenant or "-", digest))
        return br.state if br is not None else "closed"
