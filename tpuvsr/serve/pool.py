"""N-worker process pool over one spool (ISSUE 14 tentpole a).

The queue's atomic-claim machinery was multi-process-safe from PR 6 —
this module finally USES it: ``serve --workers N`` (or a
:class:`WorkerPool` in library code) launches N ``serve`` worker
processes over the same spool, each owning a device group, and the
spool arbitrates — every claim file has exactly one creator, so every
job runs exactly once no matter how many workers race
(``tests/test_service.py`` drills 3+ processes over one spool).

Topology: the parent stays a thin supervisor (recover-stale sweeps +
the optional HTTP front); the children do all the work.  A child that
dies mid-job leaves a dead claim whose job any survivor recovers WITH
its rescue checkpoint (``recover_stale``, hardened in this PR with
worker-id + heartbeat mtimes so a live worker on another host is
never mistaken for dead) — the kill-one-of-N drill in
``scripts/fault_matrix.py`` proves the survivor finishes the dead
worker's job bit-identically.

The parent also RESPAWNS dead workers (ISSUE 15 satellite, the
ROADMAP item 2 residual): a child that exits nonzero (SIGKILL, OOM,
crash) is relaunched under the same worker id after an exponential
backoff, up to ``max_restarts`` times per worker slot — journaled as
``worker_respawn`` into ``<spool>/pool.jsonl`` — so a transiently
killed fleet heals itself instead of merely having its stale claims
swept onto the survivors.  Clean exits (rc 0, a finished --drain) are
never respawned, and a slot that keeps dying stays down once its
budget is spent.

Device groups are PINNED, not just sized (ISSUE 18 — closes the PR 14
residual): :meth:`WorkerPool.device_group` carves the device budget
into DISJOINT ``(lo, count)`` slices, one per worker slot, and
``_spawn`` exports the slice to the child as
``TPUVSR_DEVICE_GROUP="lo:count"`` plus ``TPU_VISIBLE_CHIPS`` (the
TPU-VM runtime's own visibility list, ``JAX_VISIBLE_DEVICES``-style)
— so a dying job can only ever poison its own slot's chips, never a
sibling's mesh.  A respawned slot inherits the same slice: pinning
survives the crash it exists to contain.

Workers that only ever claim light jobs (shell / interp-validate /
lint-only) never import jax — a shell-only fleet starts in well under
a second per worker.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time


def child_env(extra=None):
    """Environment for tpuvsr child processes: the repo that spawned
    us leads PYTHONPATH so ``-m tpuvsr`` resolves to the same code
    even though children run with cwd=spool.  The ONE copy of this
    logic — ``tpuvsr.testing.subprocess_env`` layers the test-only
    CPU-backend forcing on top of it."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    pp = env.get("PYTHONPATH")
    env["PYTHONPATH"] = repo + (os.pathsep + pp if pp else "")
    env.update(extra or {})
    return env


class WorkerPool:
    """Spawn and supervise N ``python -m tpuvsr serve`` worker
    processes over `spool`.  Worker stdout/stderr land in
    ``<spool>/workers/w<i>.log`` so a dead worker's last words are
    always on disk."""

    def __init__(self, spool, workers=2, *, devices=None, drain=True,
                 max_seconds=None, max_jobs=None, extra_args=(),
                 env=None, python=None, log=None, max_restarts=3,
                 restart_backoff=1.0):
        self.spool = os.path.abspath(spool)
        self.workers = max(1, int(workers))
        self.devices = devices
        self.drain = drain
        self.max_seconds = max_seconds
        self.max_jobs = max_jobs
        self.extra_args = list(extra_args)
        self.env = env
        self.python = python or sys.executable
        self.log = log
        self.procs = []
        self.log_dir = os.path.join(self.spool, "workers")
        # dead-worker respawn budget (ISSUE 15 satellite): per worker
        # SLOT, with exponential backoff between restarts; journaled
        # to <spool>/pool.jsonl as worker_respawn events
        self.max_restarts = max(0, int(max_restarts))
        self.restart_backoff = float(restart_backoff)
        self._restarts = {}        # slot index -> restart count
        self._next_try = {}        # slot index -> earliest retry time
        self.respawned = 0         # total respawns this pool lifetime
        self._journal = None

    def device_group(self, i):
        """Worker slot ``i``'s pinned device slice as ``(lo, count)``
        — DISJOINT across slots (remainder devices go to the lowest
        slots), so two workers can never share a chip.  None when the
        pool is un-sized (``devices=None``) or the slot has no device
        left (more workers than devices: the extras run unpinned
        light work)."""
        if self.devices is None or i >= self.workers:
            return None
        total = int(self.devices)
        if total < 1:
            return None
        base, rem = divmod(total, self.workers)
        count = base + (1 if i < rem else 0)
        if count < 1:
            return None
        lo = i * base + min(i, rem)
        return (lo, count)

    def _cmd(self, i):
        cmd = [self.python, "-m", "tpuvsr", "serve",
               "--spool", self.spool, "--worker-id", f"w{i}"]
        if self.drain:
            cmd.append("--drain")
        if self.devices is not None:
            group = self.device_group(i)
            per = group[1] if group else 1
            cmd += ["--devices", str(per)]
        if self.max_seconds is not None:
            cmd += ["--max-seconds", str(self.max_seconds)]
        if self.max_jobs is not None:
            cmd += ["--max-jobs", str(self.max_jobs)]
        return cmd + self.extra_args

    def _env(self, i=None):
        env = dict(self.env) if self.env is not None else child_env()
        group = None if i is None else self.device_group(i)
        if group is not None:
            # the pinning contract (ISSUE 18): the child's DevicePool
            # budget is the slice SIZE, and the runtime-visible chip
            # list is the slice MEMBERS — disjoint per slot, so a
            # crashing job cannot poison a sibling worker's mesh
            lo, count = group
            chips = ",".join(str(d) for d in range(lo, lo + count))
            env["TPUVSR_DEVICE_GROUP"] = f"{lo}:{count}"
            env["TPU_VISIBLE_CHIPS"] = chips
        return env

    def _spawn(self, i):
        log_path = os.path.join(self.log_dir, f"w{i}.log")
        fh = open(log_path, "ab")
        p = subprocess.Popen(
            self._cmd(i), stdout=fh, stderr=subprocess.STDOUT,
            env=self._env(i), cwd=self.spool)
        fh.close()                        # the child holds its own fd
        p._tpuvsr_log = log_path
        return p

    def start(self):
        os.makedirs(self.log_dir, exist_ok=True)
        for i in range(self.workers):
            p = self._spawn(i)
            self.procs.append(p)
            if self.log:
                self.log(f"pool: worker w{i} pid {p.pid}")
        return self

    def alive(self):
        return sum(1 for p in self.procs if p.poll() is None)

    def pending_respawn(self):
        """True when some slot is dead-nonzero with restart budget
        left (possibly waiting out its backoff) — the supervision
        loop must NOT drain the pool while this holds, or backoff
        windows would silently eat the remaining budget."""
        return any(
            p.poll() is not None and p.poll() != 0
            and self._restarts.get(i, 0) < self.max_restarts
            for i, p in enumerate(self.procs))

    def _pool_journal(self):
        if self._journal is None:
            from ..obs.journal import Journal
            self._journal = Journal(
                os.path.join(self.spool, "pool.jsonl"))
        return self._journal

    def respawn_dead(self):
        """Relaunch worker slots whose child exited NONZERO (killed /
        crashed), bounded to ``max_restarts`` per slot with
        exponential backoff between attempts; journaled as
        ``worker_respawn``.  Clean exits (rc 0 — a finished --drain)
        stay down.  Returns the slot indices respawned this call.
        Idempotent and cheap: the supervision loop calls it every
        sweep tick."""
        out = []
        now = time.time()
        for i, p in enumerate(self.procs):
            rc = p.poll()
            if rc is None or rc == 0:
                continue
            n = self._restarts.get(i, 0)
            if n >= self.max_restarts:
                continue
            if now < self._next_try.get(i, 0.0):
                continue
            from ..resilience.backoff import backoff_delay
            self._restarts[i] = n + 1
            self._next_try[i] = now + backoff_delay(
                n + 1, self.restart_backoff)
            self.procs[i] = self._spawn(i)
            self.respawned += 1
            out.append(i)
            self._pool_journal().write(
                "worker_respawn", worker=f"w{i}",
                attempt=self._restarts[i], rc=int(rc),
                pid=self.procs[i].pid)
            if self.log:
                self.log(f"pool: worker w{i} died rc={rc}; respawned "
                         f"as pid {self.procs[i].pid} (attempt "
                         f"{self._restarts[i]}/{self.max_restarts})")
        return out

    def kill_one(self, i, sig=signal.SIGKILL):
        """Hard-kill worker `i` (fault drills: the dead-worker half of
        the kill-one-of-N scenario)."""
        p = self.procs[i]
        if p.poll() is None:
            os.kill(p.pid, sig)
        p.wait(30)
        return p.returncode

    def wait(self, timeout=None):
        """Block until every worker exits; returns their exit codes.
        On timeout the stragglers are SIGTERMed (rescue + requeue is
        their normal response) and the codes reflect that."""
        deadline = None if timeout is None else time.time() + timeout
        for p in self.procs:
            left = (None if deadline is None
                    else max(0.1, deadline - time.time()))
            try:
                p.wait(left)
            except subprocess.TimeoutExpired:
                p.terminate()
                try:
                    p.wait(15)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(15)
        if self._journal is not None:
            self._journal.close()
            self._journal = None
        return [p.returncode for p in self.procs]

    def stop(self, sig=signal.SIGTERM):
        for p in self.procs:
            if p.poll() is None:
                os.kill(p.pid, sig)

    def tail(self, i, lines=8):
        try:
            with open(os.path.join(self.log_dir, f"w{i}.log")) as f:
                return "".join(f.readlines()[-lines:])
        except OSError:
            return ""
