"""HTTP front for the serving tier (ISSUE 14 tentpole c).

``python -m tpuvsr serve --http PORT`` exposes the dispatch service
over the wire — stdlib ``http.server`` only, no new dependencies —
so the CLI verbs become one client among many.  The endpoints mirror
the verbs exactly (both sides share ``tpuvsr.service.api.job_doc``,
so a ``status --json`` and a ``GET /v1/jobs/<id>`` are the SAME
document):

    POST /v1/jobs                submit  (JSON body: spec, cfg,
                                 engine, kind, flags, priority,
                                 devices[, _min, _max], tenant)
    GET  /v1/jobs                list    (status verb's queue view +
                                 per-tenant ledger)
    GET  /v1/jobs/<id>           status  (per-job doc, ?tail=N)
    GET  /v1/jobs/<id>/events    the job's journal as NDJSON;
                                 ?follow=1 streams it chunked —
                                 lines appear as the worker appends
                                 them, and the stream closes when the
                                 job reaches a terminal state (the
                                 journal IS the query surface; this
                                 endpoint just tails it over the wire)
    POST /v1/jobs/<id>/cancel    cancel
    GET  /v1/tenants             tenant accounting fold
    GET  /v1/metrics             fleet telemetry in Prometheus text
                                 exposition format 0.0.4 (ISSUE 17;
                                 the shared TelemetryAggregator folds
                                 the spool's journals live per scrape)
    GET  /v1/telemetry           the same fold as tpuvsr-telemetry/1
                                 JSON
    GET  /healthz                queue stats

Exit-code mapping: every job doc carries ``exit_code`` — the unified
table's code for its state (``tpuvsr/exitcodes.py``: done 0,
violated 12, failed/cancelled 70, preempted-requeued 75, running
``null``) — so an HTTP client polling ``status`` and a CLI client
waiting on ``serve`` exit with the same verdict.  Transport errors
use HTTP's own vocabulary: unknown job 404 (the CLI's usage error 2),
illegal transition 409, malformed body 400.

The server is a ``ThreadingHTTPServer`` running beside the worker's
drain loop; every request folds the spool through one shared
RLock-guarded :class:`JobQueue`, so the front needs no coordination
with workers beyond the spool itself — kill the front, jobs keep
running; kill the workers, submissions keep landing.

Hardening (ISSUE 18): every request passes the spool's
:class:`~tpuvsr.serve.guard.Guard` first — bearer-token auth when
``tokens.json`` exists (401 missing/unknown token, 403 cross-tenant
submit/cancel), body-size cap off Content-Length (413, before the
body is buffered), per-tenant token-bucket + in-flight quota (429
with a refill-derived ``Retry-After``), and queue-depth backpressure
(503 with the depth in the body).  ``/healthz`` stays open so load
balancers can probe.  TLS is one ``ssl.SSLContext`` wrap of the
listening socket (``--tls-cert/--tls-key``), and a per-connection
read timeout reaps slow-loris clients — a connection that dribbles
bytes slower than ``request_timeout`` is closed, not indulged.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..service.queue import TERMINAL, JobQueue, QueueError
from .guard import REQUEST_TIMEOUT, Guard, GuardDenied

#: job fields a POST /v1/jobs body may set (everything else is 400 —
#: a typo'd field must not silently vanish)
SUBMIT_FIELDS = frozenset((
    "spec", "cfg", "engine", "kind", "flags", "priority", "devices",
    "devices_min", "devices_max", "tenant", "job_id"))

KINDS = ("check", "sim", "validate", "shell")


class ServiceHTTP:
    """The HTTP front over one spool.  ``port=0`` binds an ephemeral
    port (tests); ``start`` serves from a daemon thread and ``stop``
    shuts the listener down (in-flight streams close on their next
    poll tick)."""

    def __init__(self, spool, *, host="127.0.0.1", port=0, poll=0.15,
                 max_stream_s=3600.0, log=None, slo=None, guard=None,
                 tls_cert=None, tls_key=None,
                 request_timeout=REQUEST_TIMEOUT):
        self.spool = os.path.abspath(spool)
        self.queue = JobQueue(self.spool)
        self.poll = poll
        self.max_stream_s = max_stream_s
        self.log = log
        self._thread = None
        self._closing = False
        # the fleet telemetry fold (ISSUE 17), built on first scrape —
        # one shared aggregator, its own lock, tailed incrementally
        # per request so /v1/metrics serves live folds while jobs run
        self._telemetry = None
        self._telemetry_lock = threading.Lock()
        self._slo = slo
        # the admission guard (ISSUE 18): a default Guard still caps
        # body size and honours a spool-local tokens.json — the
        # un-configured front is hardened, just not rate-limited
        self.guard = guard if guard is not None else Guard(self.spool)
        svc = self

        class Handler(_Handler):
            service = svc
            # per-connection read timeout: socketserver applies it to
            # the socket, and BaseHTTPRequestHandler turns a timeout
            # mid-request into close_connection — the slow-loris reap
            timeout = request_timeout

        self.server = ThreadingHTTPServer((host, int(port)), Handler)
        self.server.daemon_threads = True
        self.tls = bool(tls_cert)
        if tls_cert:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key or None)
            self.server.socket = ctx.wrap_socket(
                self.server.socket, server_side=True)

    @property
    def port(self):
        return self.server.server_address[1]

    @property
    def address(self):
        host, port = self.server.server_address[:2]
        scheme = "https" if self.tls else "http"
        return f"{scheme}://{host}:{port}"

    def start(self):
        self._thread = threading.Thread(
            target=self.server.serve_forever, name="tpuvsr-http",
            daemon=True)
        self._thread.start()
        if self.log:
            self.log(f"http front listening on {self.address}")
        return self

    def telemetry(self):
        """The shared spool aggregator, polled: every call folds any
        journal lines appended since the last scrape."""
        with self._telemetry_lock:
            if self._telemetry is None:
                from ..obs.telemetry import TelemetryAggregator
                self._telemetry = TelemetryAggregator(
                    self.spool, slo=self._slo)
        self._telemetry.poll()
        return self._telemetry

    def stop(self):
        self._closing = True
        self.server.shutdown()
        self.server.server_close()
        if self._thread is not None:
            self._thread.join(10)
            self._thread = None


class _Handler(BaseHTTPRequestHandler):
    service: ServiceHTTP = None
    protocol_version = "HTTP/1.1"

    # -- plumbing ------------------------------------------------------
    def log_message(self, fmt, *args):  # noqa: A003 — stdlib hook
        if self.service.log:
            self.service.log(f"http: {fmt % args}")

    def _json(self, code, obj, headers=None):
        body = (json.dumps(obj, default=str) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _text(self, code, body, content_type):
        body = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _error(self, code, message):
        self._json(code, {"error": message})

    def _deny(self, e):
        """One :class:`GuardDenied` onto the wire: the status it
        names, a JSON body with the reason, and the 429 refill hint
        as a real ``Retry-After`` header."""
        headers = {}
        if e.retry_after is not None:
            headers["Retry-After"] = str(int(e.retry_after))
        doc = {"error": e.reason, "code": e.code}
        if e.depth is not None:
            doc["depth"] = e.depth
        self._json(e.code, doc, headers=headers)

    def _auth(self):
        """The request's authenticated tenant (None in open mode).
        Raises :class:`GuardDenied` 401 — already journaled — on a
        missing or unknown bearer token."""
        return self.service.guard.authenticate(
            self.headers.get("Authorization"),
            ts=round(time.time(), 3), path=self.path)

    def _body(self):
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b""
        if not raw:
            return {}
        return json.loads(raw)

    # -- routes --------------------------------------------------------
    def do_GET(self):  # noqa: N802 — stdlib hook
        from ..service.api import job_doc
        from .fairshare import TenantLedger
        url = urlparse(self.path)
        qs = parse_qs(url.query)
        parts = [p for p in url.path.split("/") if p]
        q = self.service.queue
        try:
            # /healthz stays unauthenticated (load-balancer probes);
            # everything else needs a valid bearer token when auth is
            # on (a 401 here is journaled `auth_denied` by the guard)
            if parts != ["healthz"]:
                self._auth()
            # telemetry routes fold journals, not the queue — they
            # take the aggregator's own lock, never the queue's
            if parts == ["v1", "metrics"]:
                from ..obs.telemetry import prometheus_text
                snap = self.service.telemetry().snapshot()
                return self._text(
                    200, prometheus_text(snap),
                    "text/plain; version=0.0.4; charset=utf-8")
            if parts == ["v1", "telemetry"]:
                return self._json(
                    200, self.service.telemetry().snapshot())
            with q.lock():
                q.refresh()
                if parts == ["healthz"]:
                    return self._json(200, {"ok": True,
                                            "stats": q.stats()})
                if parts == ["v1", "tenants"]:
                    return self._json(
                        200, {"tenants": TenantLedger.fold(q.jobs())})
                if parts == ["v1", "jobs"]:
                    # lightweight rows (the CLI list uses to_dict too):
                    # per-job docs fold whole journals — O(journal
                    # bytes) per sim/validate job is for the single-job
                    # route, not a dashboard poll holding the lock
                    from ..exitcodes import state_exit
                    rows = [dict(j.to_dict(),
                                 exit_code=state_exit(j.state))
                            for j in q.jobs()]
                    return self._json(200, {
                        "stats": q.stats(), "jobs": rows,
                        "tenants": TenantLedger.fold(q.jobs())})
                if len(parts) == 3 and parts[:2] == ["v1", "jobs"]:
                    job = q.get(parts[2])
                    tail = int((qs.get("tail") or ["0"])[0])
                    return self._json(200, job_doc(q, job, tail=tail))
            if len(parts) == 4 and parts[:2] == ["v1", "jobs"] \
                    and parts[3] == "events":
                follow = (qs.get("follow") or ["0"])[0] not in \
                    ("0", "", "false")
                tail = int((qs.get("tail") or ["0"])[0])
                return self._stream_events(parts[2], follow, tail)
        except GuardDenied as e:
            return self._deny(e)
        except QueueError as e:
            return self._error(404, str(e))
        except (ValueError, TypeError) as e:
            return self._error(400, str(e))
        return self._error(404, f"no route {url.path!r}")

    def do_POST(self):  # noqa: N802 — stdlib hook
        from ..service.api import job_doc
        from .fairshare import TenantLedger
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        q = self.service.queue
        guard = self.service.guard
        now = round(time.time(), 3)
        try:
            auth_tenant = self._auth()
            # the body cap is enforced off Content-Length BEFORE the
            # body is read — an oversized payload is never buffered
            guard.check_body_size(
                self.headers.get("Content-Length") or 0)
        except GuardDenied as e:
            return self._deny(e)
        try:
            body = self._body()
        except (ValueError, TypeError) as e:
            return self._error(400, f"bad JSON body: {e}")
        try:
            if parts == ["v1", "jobs"]:
                unknown = set(body) - SUBMIT_FIELDS
                if unknown:
                    return self._error(
                        400, f"unknown submit fields {sorted(unknown)}")
                if not body.get("spec"):
                    return self._error(400, "submit needs a spec")
                if body.get("kind", "check") not in KINDS:
                    return self._error(
                        400, f"unknown kind {body.get('kind')!r} "
                             f"(one of {list(KINDS)})")
                # cross-tenant submit is 403; with auth on, an
                # unclaimed tenant defaults to the token's own
                tenant = guard.authorize_tenant(
                    auth_tenant, body.get("tenant"), ts=now,
                    path=self.path, action="submit")
                with q.lock():
                    q.refresh()
                    # overload checks, cheapest-signal first:
                    # 503 on backlog past high water, then the
                    # tenant's token bucket / in-flight quota (429)
                    guard.admit_depth(q.backlog(), ts=now)
                    guard.admit_submission(
                        tenant, ts=now,
                        inflight=TenantLedger.in_flight(
                            q.jobs(), tenant))
                    job = q.submit(
                        body["spec"], cfg=body.get("cfg"),
                        engine=body.get("engine", "auto"),
                        kind=body.get("kind", "check"),
                        flags=body.get("flags"),
                        priority=body.get("priority", 0),
                        devices=body.get("devices", 1),
                        devices_min=body.get("devices_min"),
                        devices_max=body.get("devices_max"),
                        tenant=tenant,
                        job_id=body.get("job_id"))
                    return self._json(200, job_doc(q, job))
            if len(parts) == 4 and parts[:2] == ["v1", "jobs"] \
                    and parts[3] == "cancel":
                with q.lock():
                    q.refresh()
                    job = q.get(parts[2])       # 404 before 409
                    # cancelling another tenant's job is 403
                    guard.authorize_tenant(
                        auth_tenant, job.tenant, ts=now,
                        path=self.path, action="cancel")
                    try:
                        job = q.cancel(parts[2])
                    except QueueError as e:
                        return self._error(409, str(e))
                    return self._json(200, job_doc(q, job))
        except GuardDenied as e:
            return self._deny(e)
        except QueueError as e:
            return self._error(404, str(e))
        except (ValueError, TypeError) as e:
            return self._error(400, str(e))
        return self._error(404, f"no route {url.path!r}")

    # -- streaming status ---------------------------------------------
    def _chunk(self, data):
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")

    def _stream_events(self, job_id, follow, tail):
        """NDJSON journal tail over chunked transfer: replay the
        existing journal (last `tail` lines when set), then — with
        ``follow`` — poll for appended COMPLETE lines until the job is
        terminal and fully drained (or the stream budget/client
        disconnect ends it).  Torn tails are held back exactly like
        the spool fold holds back a torn jobs.jsonl line."""
        svc = self.service
        q = svc.queue
        with q.lock():
            q.refresh()
            job = q.get(job_id)                  # QueueError -> 404
            path = q.journal_path(job.job_id)
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        t0 = time.time()
        pos = 0
        pending = []
        grace = False
        try:
            while True:
                emitted = False
                try:
                    with open(path, "rb") as f:
                        f.seek(pos)
                        while True:
                            line = f.readline()
                            if not line or not line.endswith(b"\n"):
                                break            # torn tail: re-read
                            pos = f.tell()
                            pending.append(line)
                except OSError:
                    pass                          # journal not born yet
                if tail and pending:
                    pending = pending[-tail:]
                    tail = 0
                for line in pending:
                    self._chunk(line)
                    emitted = True
                if emitted:
                    grace = False
                pending = []
                with q.lock():
                    q.refresh()
                    terminal = q.get(job_id).state in TERMINAL
                if not follow:
                    break
                if terminal and not emitted:
                    # terminal and this pass surfaced nothing new: the
                    # journal is drained (a torn final line of a dead
                    # worker never completes — do NOT spin on it).
                    # One grace poll first: the worker writes the
                    # spool transition a beat before the job_done line
                    if grace:
                        break
                    grace = True
                    time.sleep(svc.poll)
                    continue
                if svc._closing or \
                        time.time() - t0 > svc.max_stream_s:
                    break
                if not emitted:
                    time.sleep(svc.poll)
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass
