"""Fair-share scheduling for the serving tier (ISSUE 14 tentpole b).

The queue's original pop order — highest priority, then submission
order — is exactly the policy that starves: one tenant streaming
high-priority submissions pushes everyone else's jobs to the back of
the line forever (ROADMAP item 2).  This module replaces it with the
two classic anti-starvation mechanisms, composed:

* **Priority aging** — a job's *effective* priority grows by one per
  ``age_every`` seconds waited, so any job eventually outranks any
  fixed priority class.  ``max_wait_bound`` prices the guarantee: a
  priority-``p`` job outranks every FRESH priority-``q`` submission
  after at most ``age_every * (q - p + 1)`` seconds — the bound the
  saturation drill (``scripts/serve_demo.py``) asserts against.
* **Deficit round robin over tenants** — every job carries a
  ``tenant`` and every tenant a weight (default 1.0).  Pop order is
  computed by DRR: tenants are visited in a fixed round-robin cycle,
  each visit accrues ``quantum * weight`` of credit, and a tenant
  emits its best jobs (aged priority, then seq) while its credit
  covers their cost (requested devices).  A tenant submitting 1000
  jobs therefore gets the same share of pops as a tenant submitting
  3 — weighted, not first-come-drain-everything.

The policy object is PER-WORKER in-memory state (deficits persist
across claims via :meth:`charge`), which makes multi-worker fairness
approximate by construction — each worker is independently fair, and
the aging term is global (it reads ``submitted_ts`` off the durable
job record), so the no-starvation bound holds fleet-wide.  Every pop
is journaled as a ``sched_decision`` event on the popped job's own
journal (SCHEMA.md), so "why did MY job wait?" is answerable after
the fact.

:class:`TenantLedger` is the accounting fold behind ``status``/HTTP
``/v1/tenants``: per-tenant job counts by state plus consumed
service-seconds, read straight off the spool fold — no extra state
to keep durable.

Deliberately jax-free and service-free: the queue hands ``order`` its
claimable jobs and the worker calls ``charge``/``explain``; nothing
here imports engines, so ``submit``-side tooling can price the policy
in milliseconds.
"""

from __future__ import annotations

import time

#: floor for tenant weights — a zero/negative weight must throttle a
#: tenant, never freeze it (DRR credit of 0 would starve it outright,
#: the exact bug this module exists to kill)
MIN_WEIGHT = 0.01


class FairSharePolicy:
    """Deficit-round-robin pop order with priority aging (see module
    doc).  One instance per worker; ``order`` is handed to
    ``JobQueue.claim_next`` and ``charge`` is called on every
    successful claim so the deficits track real service."""

    def __init__(self, weights=None, *, quantum=1.0, age_every=60.0,
                 age_cap=1_000_000, deficit_cap=None, clock=time.time):
        #: tenant -> weight (share of pops per DRR round); unknown
        #: tenants get 1.0
        self.weights = dict(weights or {})
        self.quantum = float(quantum)
        #: seconds of waiting per +1 effective priority (None/0 = off)
        self.age_every = age_every
        self.age_cap = age_cap
        #: credit an idle-then-bursting tenant may bank (bounds how
        #: long it can monopolize pops when it returns)
        self.deficit_cap = (deficit_cap if deficit_cap is not None
                            else 8.0 * self.quantum)
        self.clock = clock
        # the persistent DRR ring state: banked credit per tenant,
        # the tenant whose visit comes next, and whether that visit is
        # already in progress (mid-visit = no fresh quantum on resume).
        # This is what makes claim-by-claim pops fair: without the
        # advancing pointer every claim would restart the ring at the
        # first tenant and hand it every pop.
        self._deficit = {}
        self._next = None
        self._carry = False

    # -- the two mechanisms -------------------------------------------
    def weight(self, tenant):
        try:
            w = float(self.weights.get(tenant, 1.0))
        except (TypeError, ValueError):
            w = 1.0
        return max(MIN_WEIGHT, w)

    def effective_priority(self, job, now=None):
        """Base priority plus the aging boost earned by waiting."""
        if not self.age_every:
            return int(job.priority or 0)
        now = self.clock() if now is None else now
        waited = max(0.0, now - self._since(job, now))
        return (int(job.priority or 0)
                + min(self.age_cap, int(waited // self.age_every)))

    @staticmethod
    def _since(job, now):
        # 0.0 is a legal epoch (fixtures, fakes) — only None is "no
        # timestamp recorded"
        return job.submitted_ts if job.submitted_ts is not None else now

    def max_wait_bound(self, base_priority, top_priority):
        """Seconds after which a waiting ``base_priority`` job outranks
        every FRESH ``top_priority`` submission — the aging policy's
        starvation bound (infinite when aging is off)."""
        if not self.age_every:
            return float("inf")
        return self.age_every * max(0, int(top_priority)
                                    - int(base_priority) + 1)

    def cost(self, job):
        """DRR cost of one pop: the devices the job will occupy."""
        return max(1, int(job.devices or 1))

    # -- the DRR ring --------------------------------------------------
    def _backlogs(self, jobs, now=None):
        """tenant -> that tenant's claimable jobs in aged-priority
        order (the within-tenant pop order)."""
        now = self.clock() if now is None else now
        per = {}
        for j in jobs:
            per.setdefault(j.tenant, []).append(j)
        for backlog in per.values():
            backlog.sort(
                key=lambda j: (-self.effective_priority(j, now),
                               j.seq))
        return per

    @staticmethod
    def _ring_key(tenant):
        return (tenant is not None, str(tenant))

    def _drr(self, backlogs, state):
        """The one DRR loop, shared by the full-order preview and the
        real-claim bookkeeping: visit tenants round-robin from
        ``state['next']`` (stable ring, None first), accrue
        ``quantum * weight`` per visit (none on a mid-visit resume),
        pop while the credit covers the head job's cost, bank the
        remainder (capped) when it does not, and reset an emptied
        tenant's credit (classic DRR: no hoarding while idle).
        Mutates `state` in place as it yields — the caller decides
        whether that state is a scratch copy (``order``) or the
        persistent one (``charge``)."""
        ring = sorted(backlogs, key=self._ring_key)
        if not ring:
            return
        start = 0
        if state.get("next") is not None or None in backlogs:
            nk = self._ring_key(state.get("next"))
            for i, t in enumerate(ring):
                if self._ring_key(t) >= nk:
                    start = i
                    break
        i, first = start, True
        deficit = state["deficit"]
        while any(backlogs[t] for t in ring):
            t = ring[i % len(ring)]
            if backlogs[t]:
                if first and state.get("carry") \
                        and t == state.get("next"):
                    cred = deficit.get(t, 0.0)   # resume mid-visit
                else:
                    cred = (deficit.get(t, 0.0)
                            + self.quantum * self.weight(t))
                while backlogs[t] and cred >= self.cost(backlogs[t][0]):
                    job = backlogs[t].pop(0)
                    cred -= self.cost(job)
                    deficit[t] = cred
                    if backlogs[t]:
                        state["next"], state["carry"] = t, True
                    else:
                        deficit[t] = 0.0
                        state["next"] = ring[(i + 1) % len(ring)]
                        state["carry"] = False
                    yield job
                if backlogs[t]:
                    # head too costly for the remaining credit: bank
                    # it and move on — next visit tops it up.  The cap
                    # bounds idle hoarding but never sits below the
                    # head's cost (a fat job must stay reachable)
                    cap = max(self.deficit_cap,
                              self.cost(backlogs[t][0]))
                    deficit[t] = min(cap, cred)
                    state["next"] = ring[(i + 1) % len(ring)]
                    state["carry"] = False
            first = False
            i += 1

    # -- pop order -----------------------------------------------------
    def order(self, jobs, now=None):
        """Claimable jobs -> pop order.  Within a tenant: effective
        (aged) priority desc, then submission order.  Across tenants:
        the DRR ring, resumed from the persistent state — so a tenant
        under-served by past claims is visited first.  Pure preview:
        the persistent state is NOT advanced (``charge`` does that on
        the real claim)."""
        state = {"deficit": dict(self._deficit), "next": self._next,
                 "carry": self._carry}
        return list(self._drr(self._backlogs(jobs, now), state))

    # -- bookkeeping on a real claim ----------------------------------
    def charge(self, job, waiting=()):
        """Record an actual claim: replay the DRR ring on the
        PERSISTENT state until it pops `job` (normally the first pop —
        ``claim_next`` claims the head of ``order``), advancing the
        pointer/credits exactly as the preview predicted.  `waiting`
        is the still-claimable job list at claim time; a lost-race
        mismatch just replays a little further, which only costs
        fairness approximation, never correctness."""
        rest = [j for j in waiting if j.job_id != job.job_id]
        backlogs = self._backlogs([job] + rest)
        state = {"deficit": self._deficit, "next": self._next,
                 "carry": self._carry}
        for n, popped in enumerate(self._drr(backlogs, state)):
            if popped.job_id == job.job_id or n > len(rest):
                break
        self._next, self._carry = state["next"], state["carry"]

    def explain(self, job, now=None):
        """The ``sched_decision`` journal payload for a claimed job."""
        now = self.clock() if now is None else now
        return {
            "policy": "drr",
            "tenant": job.tenant,
            "weight": round(self.weight(job.tenant), 3),
            "deficit": round(self._deficit.get(job.tenant, 0.0), 3),
            "priority": int(job.priority or 0),
            "aged_priority": self.effective_priority(job, now),
            "waited_s": round(max(0.0, now - self._since(job, now)), 3),
        }


class TenantLedger:
    """Per-tenant accounting folded from the durable job records —
    the query surface behind ``status``'s tenant table and the HTTP
    front's ``/v1/tenants`` (nothing extra is persisted; the spool IS
    the ledger)."""

    @staticmethod
    def fold(jobs):
        out = {}
        for j in jobs:
            row = out.setdefault(j.tenant or "-", {
                "jobs": 0, "queued": 0, "active": 0, "done": 0,
                "violated": 0, "failed": 0, "cancelled": 0,
                "service_s": 0.0})
            row["jobs"] += 1
            if j.state in ("queued", "admitted", "preempted-requeued"):
                row["queued"] += 1
            elif j.state == "running":
                row["active"] += 1
            elif j.state in row:
                row[j.state] += 1
            elapsed = (j.result or {}).get("elapsed_s")
            if elapsed:
                try:
                    row["service_s"] = round(
                        row["service_s"] + float(elapsed), 3)
                except (TypeError, ValueError):
                    pass
        return out

    @staticmethod
    def in_flight(jobs, tenant):
        """One tenant's unfinished job count (everything not yet
        terminal) — the number the guard's in-flight quota caps
        (ISSUE 18), layered ON TOP of the DRR fair share: DRR decides
        who runs next, the quota decides who may even enqueue more."""
        key = tenant or "-"
        return sum(1 for j in jobs
                   if (j.tenant or "-") == key
                   and j.state in ("queued", "admitted",
                                   "preempted-requeued", "running"))
