"""Thread-safe in-process multi-runner for CPU-advised jobs
(ISSUE 14 tentpole a).

One worker process owns one device group, and before this module it
ran ONE job at a time — so a 2-second speclint report or a shell job
sleeping on a subprocess occupied a whole mesh while device-hungry
checks waited.  The multi-runner is the side lane: a small thread
pool inside the worker that runs **light** jobs concurrently with the
mesh job, where light means provably device-free:

* ``kind="shell"`` — the job IS a subprocess; the worker thread only
  polls it;
* ``kind="validate"`` with ``flags.interp`` — the interpreter
  reference validator (``tpuvsr/validate/host.py``), pure Python over
  the spec interpreter, no jax;
* ``kind="check"`` with ``flags.lint_only`` — a speclint report job:
  the admission gate already ran the analyzer, the "run" just
  publishes the report (``tpuvsr/analysis``, jax-free).

Light jobs allocate **zero** devices from the pool (the worker's
``job_started`` journal event says ``devices: 0``), so the scheduler
never counts them against the mesh, and the drain loop keeps claiming
device jobs while they run.  Everything they touch — the queue (now
RLock-guarded), per-job journals (append-per-write), the processed
list — is safe under concurrent threads.

Dispatch decisions are pure (:func:`is_light` reads only durable job
fields), so every worker in a fleet classifies a job identically.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor


def is_light(job):
    """True when `job` provably needs no accelerator devices and may
    run on the worker's thread-pool side lane (see module doc)."""
    flags = job.flags or {}
    if job.kind == "shell":
        return True
    if job.kind == "validate" and flags.get("interp"):
        return True
    if job.kind == "check" and flags.get("lint_only"):
        return True
    return False


class MultiRunner:
    """The worker's side lane: ``submit`` schedules one light job on
    the thread pool and returns immediately; ``drain`` blocks until
    every in-flight light job settled (the worker calls it before its
    drain loop exits, so a worker never abandons a running claim)."""

    def __init__(self, worker, threads=2):
        self.worker = worker
        self.threads = max(1, int(threads))
        self._ex = None
        self._futures = []

    def submit(self, job):
        if self._ex is None:
            self._ex = ThreadPoolExecutor(
                max_workers=self.threads,
                thread_name_prefix="tpuvsr-light")
        self._futures.append(
            self._ex.submit(self.worker.run_one_light, job))

    def inflight(self):
        """Count of light jobs not yet settled (prunes done ones)."""
        self._futures = [f for f in self._futures if not f.done()]
        return len(self._futures)

    def drain(self):
        """Wait for every in-flight light job; surface the first
        unexpected error (``run_one_light`` maps job errors onto the
        queue itself, so an exception here is a worker bug)."""
        futures, self._futures = self._futures, []
        for f in futures:
            f.result()

    def close(self):
        self.drain()
        if self._ex is not None:
            self._ex.shutdown(wait=True)
            self._ex = None
