"""tpuvsr.serve — the multi-worker fair-share serving tier
(ISSUE 14 tentpole, ROADMAP item 2).

``tpuvsr/service`` made verification durable; this package makes it
CONCURRENT and FAIR, after the many-tenants-one-queue posture of
federated dispatch (arxiv 2606.02019) and streaming trace validation
(arxiv 2404.16075):

* **pool.py** — N worker processes over one spool (the PR 6 atomic
  claims finally exercised multi-process), each owning a device
  group; dead workers' jobs recovered by survivors via the hardened
  worker-id + heartbeat claim files;
* **multirunner.py** — a thread-pool side lane inside every worker so
  light jobs (shell, interp validates, speclint reports) run beside
  the mesh job with a zero-device allocation;
* **fairshare.py** — deficit-round-robin pop order over per-tenant
  weighted quotas plus priority aging (``sched_decision`` journaled
  per pop; ``TenantLedger`` folds the accounting off the spool);
* **http.py** — the wire API: ``serve --http PORT`` exposes
  submit/status/cancel/list plus chunked streaming of per-job
  journals, stdlib ``http.server`` only;
* **guard.py** — the hardened front door (ISSUE 18): bearer-token
  auth, TLS, request bounds, per-tenant token-bucket rate limits,
  queue-depth backpressure, and the per-(tenant, spec) circuit
  breaker — every rejection journaled and folded into telemetry.

Imports are lazy (PEP 562) so the jax-free pieces (queue tooling,
claim racers, shell-only workers) stay milliseconds to import.
"""

from __future__ import annotations

_EXPORTS = {
    "FairSharePolicy": ("fairshare", "FairSharePolicy"),
    "TenantLedger": ("fairshare", "TenantLedger"),
    "MIN_WEIGHT": ("fairshare", "MIN_WEIGHT"),
    "MultiRunner": ("multirunner", "MultiRunner"),
    "is_light": ("multirunner", "is_light"),
    "ServiceHTTP": ("http", "ServiceHTTP"),
    "WorkerPool": ("pool", "WorkerPool"),
    "Guard": ("guard", "Guard"),
    "GuardDenied": ("guard", "GuardDenied"),
    "TokenBucket": ("guard", "TokenBucket"),
    "CircuitBreaker": ("guard", "CircuitBreaker"),
    "spec_digest": ("guard", "spec_digest"),
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    try:
        mod, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}") from None
    import importlib
    return getattr(importlib.import_module(f".{mod}", __name__), attr)
