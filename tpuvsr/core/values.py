"""TLA+ value domain for the TPU-native model checker.

Implements the value universe exercised by the reference corpus
(/root/reference/vsr-revisited): booleans, naturals/integers, strings,
model values (cfg-bound CONSTANTS such as Nil/Normal/v1), finite sets,
and functions.  Records, sequences, and the message bag are all TLA+
functions (records = functions over string domains, sequences = functions
over 1..n), so a single immutable ``FnVal`` covers them — this mirrors TLC
value semantics (e.g. ``<<>> = [x \\in {} |-> x]`` and the non-1-based log
slices built at VSR.tla:535).

Determinism requirements (SURVEY.md §2.7.5): every ``CHOOSE`` must return
the same element for the same set across evaluations, and symmetry
canonicalization needs a total order on values.  ``value_key`` provides a
canonical total order over the whole universe.
"""

from __future__ import annotations

from typing import Any, Iterable, Tuple


class ModelValue:
    """An uninterpreted model value bound in a .cfg (e.g. ``Nil``, ``v1``).

    Interned: identity comparison is value comparison.  TLC semantics: a
    model value is equal only to itself and unequal to every other value.
    """

    _interned: dict = {}
    __slots__ = ("name",)

    def __new__(cls, name: str) -> "ModelValue":
        mv = cls._interned.get(name)
        if mv is None:
            mv = object.__new__(cls)
            mv.name = name
            cls._interned[name] = mv
        return mv

    def __repr__(self) -> str:
        return self.name

    def __hash__(self) -> int:
        return hash(("MV", self.name))

    # Equality is identity (interned); default object eq suffices.


class FnVal:
    """An immutable TLA+ function: finite mapping from values to values.

    Stored as a tuple of (key, value) pairs sorted by ``value_key`` of the
    key, giving canonical equality/hash regardless of construction order.
    Covers records ([a |-> 1]), sequences (<<a, b>> with domain 1..n),
    logs with arbitrary integer domains, and the message bag
    (message-record -> pending-delivery count, VSR.tla:228-245).
    """

    __slots__ = ("items", "_map", "_hash", "_key")

    def __init__(self, pairs: Iterable[Tuple[Any, Any]]):
        m = dict(pairs)
        self._map = m
        self.items = tuple(sorted(m.items(), key=lambda kv: value_key(kv[0])))
        self._hash = None
        self._key = None

    @staticmethod
    def empty() -> "FnVal":
        return _EMPTY_FN

    def __len__(self) -> int:
        return len(self.items)

    def __hash__(self) -> int:
        h = self._hash
        if h is None:
            h = self._hash = hash(self.items)
        return h

    def __eq__(self, other: Any) -> bool:
        if self is other:
            return True
        if not isinstance(other, FnVal):
            return False
        return self.items == other.items

    def __ne__(self, other: Any) -> bool:
        return not self.__eq__(other)

    # --- TLA+ operations -------------------------------------------------

    def domain(self) -> frozenset:
        return frozenset(self._map)

    def has_key(self, k: Any) -> bool:
        return k in self._map

    def apply(self, k: Any) -> Any:
        try:
            return self._map[k]
        except KeyError:
            raise TLAError(f"function applied outside domain: {fmt(self)}[{fmt(k)}]")

    def get(self, k: Any, default: Any = None) -> Any:
        return self._map.get(k, default)

    def updated(self, k: Any, v: Any) -> "FnVal":
        m = dict(self._map)
        m[k] = v
        return FnVal(m.items())

    def merge_left(self, other: "FnVal") -> "FnVal":
        """``self @@ other`` — left-biased merge (TLC module semantics)."""
        m = dict(other._map)
        m.update(self._map)
        return FnVal(m.items())

    # --- sequence view ---------------------------------------------------

    def is_sequence(self) -> bool:
        n = len(self._map)
        if n == 0:
            return True
        return all(isinstance(k, int) for k in self._map) and \
            frozenset(self._map) == frozenset(range(1, n + 1))

    def seq_len(self) -> int:
        # Len() in TLC requires a sequence; corpus only calls it on 1..n logs.
        return len(self._map)

    def seq_elems(self) -> list:
        return [self._map[i] for i in range(1, len(self._map) + 1)]

    def seq_append(self, v: Any) -> "FnVal":
        n = len(self._map)
        m = dict(self._map)
        m[n + 1] = v
        return FnVal(m.items())

    def __repr__(self) -> str:
        return fmt(self)


_EMPTY_FN = FnVal(())


class TLAError(Exception):
    """Evaluation error (e.g. applying a function outside its domain).

    The reference relies on lazy evaluation to keep some of these latent
    (SURVEY.md §2.7.1: the dead ``m.commit`` at VSR.tla:421); an eager
    engine must only raise when the faulty expression is actually reached.
    """


def mk_seq(elems: Iterable[Any]) -> FnVal:
    return FnVal((i + 1, v) for i, v in enumerate(elems))


def mk_record(**fields: Any) -> FnVal:
    return FnVal(fields.items())


_TYPE_RANK = {bool: 0, int: 1, str: 2, ModelValue: 3, frozenset: 4, FnVal: 5}


def value_key(v: Any):
    """Canonical total-order key across the whole value universe.

    Used for: deterministic CHOOSE (min element satisfying the predicate is
    NOT what TLC does — TLC picks the first in its internal normalized
    order; we define our own stable order, which is all the determinism the
    semantics require), FnVal canonical item order, set ordering, and
    symmetry canonicalization (min over permutations).
    """
    t = type(v)
    if t is bool:
        return (0, v)
    if t is int:
        return (1, v)
    if t is str:
        return (2, v)
    if t is ModelValue:
        return (3, v.name)
    if t is frozenset:
        ks = sorted(value_key(x) for x in v)
        return (4, tuple(ks))
    if t is FnVal:
        k = v._key
        if k is None:
            k = v._key = (5, tuple((value_key(a), value_key(b)) for a, b in v.items))
        return k
    raise TLAError(f"unorderable value type: {t!r}")


def tla_eq(a: Any, b: Any) -> bool:
    """TLA+ equality.  Cross-type comparisons are FALSE (TLC is permissive
    for model values vs anything; we extend that to all type mismatches,
    which is sound for this corpus, e.g. ``m.log # Nil`` at VSR.tla:882)."""
    ta, tb = type(a), type(b)
    if ta is bool or tb is bool:
        return (ta is bool and tb is bool) and a == b
    if ta is int and tb is int:
        return a == b
    if ta is not tb:
        return False
    return a == b


def fmt(v: Any) -> str:
    """Pretty-print a value in TLC trace style (TRACE:8-24 format)."""
    t = type(v)
    if t is bool:
        return "TRUE" if v else "FALSE"
    if t is int:
        return str(v)
    if t is str:
        return f'"{v}"'
    if t is ModelValue:
        return v.name
    if t is frozenset:
        elems = sorted(v, key=value_key)
        return "{" + ", ".join(fmt(e) for e in elems) + "}"
    if t is FnVal:
        if len(v) == 0:
            return "<<>>"
        if v.is_sequence():
            return "<<" + ", ".join(fmt(e) for e in v.seq_elems()) + ">>"
        if all(isinstance(k, str) for k in v.domain()):
            return "[" + ", ".join(f"{k} |-> {fmt(x)}" for k, x in v.items) + "]"
        return "(" + " @@ ".join(f"{fmt(k)} :> {fmt(x)}" for k, x in v.items) + ")"
    return repr(v)


def permute_value(v: Any, mapping: dict) -> Any:
    """Apply a model-value permutation (symmetry reduction, VSR.tla:151)
    recursively through sets, function domains, and function values."""
    t = type(v)
    if t is ModelValue:
        return mapping.get(v, v)
    if t is frozenset:
        return frozenset(permute_value(e, mapping) for e in v)
    if t is FnVal:
        return FnVal((permute_value(k, mapping), permute_value(x, mapping))
                     for k, x in v.items)
    return v
