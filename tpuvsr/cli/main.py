"""TLC-flag-compatible command line (SURVEY.md §5 config/flag system).

    python -m tpuvsr SPEC.tla [-config FILE.cfg] [options]

The reference corpus's specs and cfgs run unchanged; flags mirror the
TLC CLI that the reference's README drives (workers/simulation/depth):

  -config FILE     model file (default: SPEC base name + .cfg)
  -workers N|auto  accepted for TLC compatibility (the device engine
                   parallelizes across lanes/devices instead of threads)
  -simulate        simulation mode (random walks) instead of BFS —
                   runs on the sharded walker fleet (tpuvsr/sim) for
                   specs with a device kernel, the interpreter
                   otherwise
  -validate FILE   trace-validation mode (tpuvsr/validate, ISSUE 8):
                   check every recorded implementation trace in FILE
                   (TRACE.jsonl — one JSON object per line, see the
                   README "Trace validation" section) against the
                   spec's next-state relation, partial observations
                   tracked as candidate-state sets (arxiv 2404.16075).
                   Runs batched on the device mesh for specs with a
                   compiled kernel (traces vmapped + shard_mapped, the
                   fleet idiom), through the interpreter otherwise (or
                   under -engine interp/-fpset host).  Reports the
                   first divergence per trace: event index, recorded
                   event, the spec-side enabled action set at that
                   point, and invariant metadata.  Divergence reports
                   are bit-identical across mesh sizes, batch sizes
                   and rescue/resume seams.  Exit 0 all accepted, 12
                   divergences found, 75 preempted (rescue snapshot
                   written to -checkpointdir; rerun with -recover)
  -batch N         -validate: traces checked per round (default 1024;
                   the OOM-degrade ladder halves it)
  -depth N         walk depth in simulation mode (default 100)
  -num N           number of walks (default 10000; TLC runs forever)
  -seed N          simulation RNG seed.  Fleet walks are a pure
                   function of (seed, walk id): a violation replays
                   bit-identically at any -walkers count, any mesh
                   shape, and across a rescue/resume seam
  -walkers N       fleet size (default 1024; 10^5+ is the intended
                   scale — walkers are vmapped and shard_mapped
                   across every visible device)
  -split           importance splitting: fingerprint-novelty
                   kill/clone at chunk boundaries (deep-defect hunts;
                   trades walker-count replay-independence for hit
                   rate)
  -hunt            continuous defect hunt: collect every violation
                   (deduped fleet-wide, each replayed to a TRACE
                   counterexample) instead of stopping at the first
  -engine E        auto | device | interp | sharded (default auto:
                   the jit+vmap device engine for specs with a
                   compiled kernel, the interpreter otherwise;
                   sharded = the multi-chip engine over every visible
                   device — frontier and fingerprint set
                   hash-partitioned over a 1-D mesh)
  -fpset NAME      fingerprint-set implementation, mirroring TLC's
                   pluggable-FPSet class flag: auto (default) | hbm
                   (the HBM-resident device table — forces the device
                   engine) | paged (HBM fingerprints + host-RAM-paged
                   frontier — the spill tier for defect-scale runs,
                   TLC's disk-backed queue analog) | host (the
                   interpreter's in-memory set — forces the
                   interpreter engine)
  -maxstates N     stop BFS after N distinct states
  -deadlock        enable deadlock reporting (note: TLC's flag of the
                   same name *disables* its default-on check; the
                   reference corpus only runs deadlock-off)
  -checkpoint N    write an engine snapshot every N minutes (device
                   BFS; TLC's -checkpoint)
  -checkpointdir P snapshot directory (default: <spec>.ckpt)
  -recover PATH    resume a BFS run from a snapshot (TLC's -recover)
  -fused           device BFS: whole fixpoint in O(1) dispatches (no
                   per-level host syncs — the remote-TPU mode; not
                   combinable with -checkpoint/-recover or temporal
                   properties, EXCEPT under -supervise, where each
                   fused dispatch is bounded to a rescue quantum so
                   level-boundary snapshots and SIGTERM rescues work;
                   a supervised resume continues through the chunked
                   engine)
  -chained         device BFS: cross-level chained window
                   (run_chained) — the dispatch window survives level
                   boundaries; checkpointable via its level-boundary
                   rescue seam (-checkpoint; snapshots resume through
                   the chunked engine, so -recover needs -supervise,
                   which journals the mode degrade)
  -commit MODE     fused | per-action (default fused): level-kernel
                   commit mode.  fused runs the occupancy-packed
                   three-stage tile pass (chunk-wide guard matrix ->
                   work-queue compaction -> single-commit tiles: ONE
                   FPSet insert batch + ONE scatter per frontier tile
                   instead of n_actions of each, expansion caps sized
                   by exact enabled counts).  per-action is the
                   historical serial-phase body.  Results are
                   bit-identical either way (README "The level
                   kernel")
  -pipeline K      device/paged/sharded BFS dispatch window: keep up
                   to K level-kernel dispatches in flight, blocking
                   only on the oldest, so host-side work (journal,
                   metrics, spill compaction, checkpoint staging)
                   overlaps device compute (default 2 on every device
                   engine — the sharded step donates its buffers since
                   ISSUE 9, so the old K-generations-in-HBM cost of a
                   sharded window is gone; 1 = the synchronous
                   pre-pipeline behavior).  Counts, level sizes and
                   violation traces are bit-identical for every K
                   (README "Pipelining")
  -symmetry MODE   on | off (default: on when the cfg declares
                   SYMMETRY, off otherwise — TLC's semantics, where
                   declaring Permutations IS enabling the reduction):
                   device-native symmetry reduction (engine/canon.py).
                   With on, every successor is canonicalized to the
                   least element of its symmetry orbit PRE-FINGERPRINT
                   inside the jitted level kernel, so the FPSet and
                   frontier hold ONE entry per orbit (up to |Values|!
                   fewer distinct states); verdicts are identical to
                   off (traces may differ by orbit representative).
                   Snapshots record the canonicalization spec —
                   resuming with a flipped -symmetry or changed group
                   is a policy error.  Liveness checking keeps its
                   existing SYMMETRY-off requirement, and trace
                   validation tracks concrete states (-symmetry on
                   conflicts with PROPERTY cfgs and -validate)
  -spill DIR       paged engine: NVMe/disk spill tier for the host
                   frontier pages (ISSUE 11, CAPACITY.md mitigation
                   2).  Pages beyond the RAM budget flush to
                   append-only level files under DIR and re-read
                   sequentially; the 189 M host-RAM packed-state
                   ceiling becomes a disk-priced 10^9-state one.
                   Implies -fpset paged; conflicts with -engine
                   device/interp/sharded, -fpset host/hbm,
                   -simulate/-validate/-supervise and temporal
                   properties (retain_levels needs resident levels)
  -edges MODE      on | off (default: on for PROPERTY cfgs, meaning-
                   less otherwise): behavior-graph edge stream
                   (ISSUE 15).  With on, the level kernel's fused
                   commit resolves every enabled lane's successor
                   fingerprint to a graph node id on device and
                   appends (src, action, dst) edges to a device
                   buffer drained into an incremental host CSR
                   builder — liveness graph construction becomes a
                   near-free rider on the safety BFS instead of a
                   second full re-expansion pass (the two-pass path,
                   kept under -edges off as the bit-identity oracle).
                   Snapshots carry the stream (gid column + edge rows
                   + retained levels), so a preempted temporal run
                   resumes to a bit-identical CSR and verdict.
                   Conflicts: -simulate/-validate/-symmetry on/
                   -engine interp/-fpset host; -edges on needs a
                   PROPERTY cfg (checked after the cfg loads)
  -pack MODE       on | off (default on): packed bit-planed frontier
                   encoding (engine/pack.py) — the at-rest frontier,
                   host spill pages and the sharded exchange move
                   ceil(total_bits/32) uint32 words per state instead
                   of one word per field, with the per-field bit
                   budgets taken from the speclint widths pass.
                   Results are bit-identical on/off (README "Packed
                   frontier").  Device engines only: explicit -pack on
                   with -engine interp/-fpset host is an error
  -lint            run the speclint static analyzer (tpuvsr/analysis)
                   over the bound spec and exit: 0 clean/warnings,
                   1 errors.  With -json the report is one JSON object.
                   -lint=off disables the engines' fail-fast pre-flight
                   gate (equivalent to TPUVSR_LINT=off).
  -json            emit a one-line JSON result summary (includes a
                   "metrics" object: phase timers, counters, gauges
                   from the obs collector)
  -metrics FILE    dump the full tpuvsr-metrics/1 document (phase
                   timers, counters, gauges, per-level trajectory) to
                   FILE as JSON, and render a final stats table on
                   stderr (schema: tpuvsr/obs/SCHEMA.md)
  -journal FILE    append a JSONL run journal (run_start/level_done/
                   checkpoint/spill/grow/violation/run_end plus the
                   resilience events fault/retry/degrade/
                   rescue_checkpoint) to FILE; a -recover resume
                   pointed at the same FILE continues the same journal
                   with cumulative elapsed
  -supervise       run the BFS under the resilience supervisor
                   (tpuvsr/resilience): RESOURCE_EXHAUSTED degrades
                   (tile halving, then hbm -> paged fallback) with
                   bounded exponential-backoff retries resuming from
                   the latest snapshot, and SIGTERM/SIGINT checkpoint
                   at the next level boundary and exit with the
                   resumable code 75 (rerun with -recover, or drive
                   the loop with scripts/supervise.py).  With
                   -engine sharded the ladder is mesh-aware: per-shard
                   tile halving, then mesh shrink to the largest
                   usable power-of-two device count (the resume
                   re-hash-partitions the snapshot onto the smaller
                   mesh), then single-device paged fallback.
                   Device/paged/sharded BFS only; implies
                   level-boundary checkpointing to -checkpointdir
                   when -checkpoint is not given.
  -inject SPEC     arm the deterministic fault-injection plan
                   (tpuvsr/resilience/faults.py grammar, e.g.
                   "oom@level=3,corrupt-ckpt:frontier.npz",
                   "oom@shard=0", "exchange-drop:3@shard=0"); the
                   TPUVSR_FAULT env var arms the same plan

Environment: TPUVSR_PROFILE=DIR wraps the engine fixpoint loop in
jax.profiler.trace(DIR) with per-level/per-phase TraceAnnotation
spans (view with TensorBoard / Perfetto).  TPUVSR_FAULT=SPEC arms
fault injection (same grammar as -inject).

Mutually exclusive flags (argparse errors, exit code 2, before any
spec is loaded): -fused with -checkpoint/-recover (unless -supervise,
whose rescue quantum makes fused snapshots possible); -fpset host with
-engine device; -fpset hbm/paged with -engine interp; -supervise with
-simulate/-engine interp/-fpset host; -engine sharded with
-simulate/-fused (the sharded engine has no fused fixpoint) or any
non-auto -fpset (its fingerprint set is always the mesh-sharded HBM
table); -walkers/-split/-hunt without -simulate, or with
-engine interp/-fpset host (the fleet is a device backend);
explicit -pack on with -engine interp/-fpset host (the packed
frontier is a device-engine format; the interpreter has no dense
frontier to pack); -chained with -fused/-engine sharded/-engine
interp/-fpset host/-simulate/-validate, or with -recover unless
-supervise (the chained window has no resume path of its own);
explicit -commit with -engine interp/-fpset host/-simulate/-validate
(it configures the BFS level kernel); explicit -symmetry with
-engine interp/-fpset host (the interpreter always applies the
declared SYMMETRY itself) and -symmetry on with -validate (trace
validation tracks concrete states) or a PROPERTY cfg (liveness keeps
SYMMETRY off — checked after the cfg loads); -spill with
-engine device/interp/sharded, -fpset host/hbm,
-simulate/-validate/-supervise (the spill tier is the paged engine's
host-page store); -bounds on with -lint=off (tightened facts from an
unverified spec cannot be trusted), with -engine interp/-fpset host,
or with -simulate/-validate (the fleet and the validator consume no
bounds facts — a forced flag must not be silently inert);
-por on with -lint=off/-engine interp/-fpset host/-simulate/
-validate/-edges on/-commit per-action, or a PROPERTY cfg (the
ample-set reduction preserves invariant/deadlock verdicts, not the
behavior graph — the cfg conflict is checked after it loads);
-validate with -simulate/-hunt/-fused/-supervise/-deadlock/
-maxstates/-checkpoint/-engine sharded/-fpset hbm|paged (validation
is its own engine mode: rescue checkpoints are preemption-driven, the
batch validator owns its mesh, and traces have no deadlock notion);
-batch without -validate.

Exit codes (the unified contract in tpuvsr/exitcodes.py): 0 ok;
1 speclint errors (-lint); 2 bad flags; 12 safety/temporal violation
(TLC's code); 75 preempted-but-resumable (a -supervise run caught
SIGTERM/SIGINT and wrote a rescue snapshot — rerun with -recover to
continue).  The dispatch service maps these to job terminal states
from the same table.

Service verbs (ISSUE 6 + the ISSUE 14 serving tier; tpuvsr/service +
tpuvsr/serve — README "Service"):

    python -m tpuvsr submit SPEC.tla [-config F] [--engine E]
                     [--priority N] [--devices N] [--tenant T] ...
    python -m tpuvsr serve  [--spool DIR] [--drain] [--workers N]
                     [--http PORT] [--tenant-weight T=W]
                     [--tls-cert PEM] [--rate N] [--high-water N]
                     [--breaker-threshold K]
                     [--spool-driver fs|objstore|quorum] ...
    python -m tpuvsr status [JOB] [--spool DIR] [--json] [--tail N]
    python -m tpuvsr cancel JOB [--spool DIR]

turn the checker into a long-running verification dispatcher: a
durable job queue with speclint admission, a mesh scheduler with
elastic shrink/grow of live sharded runs, and per-job journals +
metrics docs as the query surface.  The front door is hardened
(ISSUE 18, tpuvsr/serve/guard.py — README "Hardening the front
door"): bearer-token auth off a spool-local tokens.json, optional
TLS, per-tenant token-bucket rate limits (429 + Retry-After),
queue-depth backpressure (503), and a per-(tenant, spec) circuit
breaker that fail-fasts crash-looping submissions before they touch
a device.  The control plane itself is durable across machines
(ISSUE 20, tpuvsr/service/spooldrv.py — README "Multi-host data
plane"): pluggable spool drivers (fs / objstore / quorum) with
claim-epoch fencing, a quorum-replicated control log that survives
a lost replica, and host-lease failover that sweeps a dead host's
claims in one pass.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from ..exitcodes import EX_LINT, EX_OK, EX_VIOLATION


def build_parser():
    p = argparse.ArgumentParser(
        prog="tpuvsr", add_help=True, prefix_chars="-",
        description="TPU-native TLA+ model checker for the VSR corpus")
    p.add_argument("spec", help="path to the .tla module")
    p.add_argument("-config", help=".cfg model file")
    p.add_argument("-workers", default="auto")
    p.add_argument("-simulate", action="store_true")
    p.add_argument("-validate", default=None, metavar="TRACES.jsonl",
                   help="validate recorded implementation traces "
                        "(one JSON object per line) against the spec "
                        "instead of checking/simulating: per step the "
                        "next-state relation is constrained to "
                        "transitions consistent with the recorded "
                        "event; partial observations are tracked as "
                        "candidate-state sets (tpuvsr/validate).  "
                        "Exit 0 accepted / 12 diverged / 75 preempted")
    p.add_argument("-batch", type=int, default=None, metavar="N",
                   help="-validate: traces per round (default 1024)")
    p.add_argument("-depth", type=int, default=100)
    p.add_argument("-num", type=int, default=10000)
    p.add_argument("-seed", type=int, default=0)
    p.add_argument("-engine",
                   choices=["auto", "device", "interp", "sharded"],
                   default="auto")
    p.add_argument("-fpset", choices=["auto", "hbm", "paged", "host"],
                   default="auto")
    p.add_argument("-walkers", type=int, default=None, metavar="N",
                   help="simulation: walker-fleet size (default 1024; "
                        "the fleet replays any violation identically "
                        "for a fixed -seed at ANY walker count/mesh "
                        "shape — tpuvsr/sim)")
    p.add_argument("-split", action="store_true",
                   help="simulation: importance splitting — walkers "
                        "carry a fingerprint-novelty score; low-"
                        "novelty walkers are killed and respawned as "
                        "clones of high-novelty ones at chunk "
                        "boundaries (deep-defect hunts)")
    p.add_argument("-hunt", action="store_true",
                   help="simulation: continuous defect hunt — collect "
                        "EVERY violation (deduped fleet-wide, each "
                        "replayed to a TRACE counterexample) instead "
                        "of stopping at the first; bounded by "
                        "-num/-maxseconds")
    p.add_argument("-maxstates", type=int, default=None)
    p.add_argument("-deadlock", action="store_true")
    p.add_argument("-checkpoint", type=float, default=None,
                   metavar="MINUTES")
    p.add_argument("-checkpointdir", default=None)
    p.add_argument("-recover", default=None, metavar="PATH")
    p.add_argument("-json", action="store_true")
    p.add_argument("-maxseconds", type=float, default=None)
    p.add_argument("-fused", action="store_true",
                   help="device engine: run the whole fixpoint in O(1)"
                        " dispatches (no per-level host syncs; remote-"
                        "TPU mode; excludes -checkpoint/-recover "
                        "unless -supervise)")
    p.add_argument("-chained", action="store_true",
                   help="device engine: cross-level chained window "
                        "(run_chained) — the dispatch window survives "
                        "level boundaries; now checkpointable via its "
                        "level-boundary rescue seam (-checkpoint; a "
                        "snapshot resumes through the chunked engine, "
                        "so -recover needs -supervise)")
    p.add_argument("-commit", choices=["fused", "per-action"],
                   default=None, metavar="MODE",
                   help="level-kernel commit mode (default fused): "
                        "'fused' runs the occupancy-packed three-stage "
                        "tile pass — chunk-wide guard matrix, "
                        "work-queue compaction, ONE FPSet insert batch "
                        "+ ONE scatter per tile; 'per-action' runs the "
                        "historical n_actions serial phases.  Results "
                        "are bit-identical either way")
    p.add_argument("-pipeline", type=int, default=None, metavar="K",
                   help="device/paged/sharded BFS dispatch window: "
                        "keep K level-kernel dispatches in flight, "
                        "blocking only on the oldest (default 2 on "
                        "every device engine — the sharded step "
                        "donates its buffers; 1 = synchronous).  "
                        "Results are bit-identical for every K")
    p.add_argument("-symmetry", choices=["on", "off"], default=None,
                   metavar="MODE",
                   help="device-native symmetry reduction (default: "
                        "on iff the cfg declares SYMMETRY): states "
                        "are canonicalized to orbit representatives "
                        "pre-fingerprint inside the level kernel, so "
                        "the FPSet/frontier hold one entry per orbit "
                        "(engine/canon.py).  Verdicts are identical "
                        "on/off; traces may differ by orbit "
                        "representative")
    p.add_argument("-spill", default=None, metavar="DIR",
                   help="paged engine: disk spill tier for host "
                        "frontier pages — pages beyond the RAM "
                        "budget flush to append-only level files "
                        "under DIR (implies -fpset paged)")
    p.add_argument("-edges", choices=["on", "off"], default=None,
                   metavar="MODE",
                   help="behavior-graph edge stream for temporal "
                        "properties (default: on for PROPERTY cfgs): "
                        "the level kernel emits (src, action, dst) "
                        "edges during the safety BFS itself — "
                        "liveness graph construction becomes a "
                        "near-free rider on the run instead of a "
                        "second full re-expansion pass.  -edges off "
                        "falls back to the two-pass path (the "
                        "bit-identity oracle).  -edges on requires a "
                        "PROPERTY cfg and conflicts with -simulate/"
                        "-validate/-symmetry on/-engine interp/"
                        "-fpset host")
    p.add_argument("-pack", choices=["on", "off"], default=None,
                   metavar="MODE",
                   help="packed bit-planed frontier encoding "
                        "(default on for the device engines): the "
                        "at-rest frontier / spill pages / sharded "
                        "exchange move packed uint32 word planes "
                        "sized by the speclint widths pass.  Results "
                        "are bit-identical on/off")
    p.add_argument("-lower", action="store_true",
                   help="compile the device kernel's guards/actions/"
                        "invariants from the spec AST (tpuvsr/lower) "
                        "instead of the hand-written kernel; falls "
                        "back to the hand kernel for modules beyond "
                        "the lowerer's surface")
    p.add_argument("-bounds", choices=["on", "off"], default=None,
                   metavar="MODE",
                   help="speclint bounds pre-pass consumption (default "
                        "on while the lint gate is live): the symbolic "
                        "interval analysis (pass 6) tightens the "
                        "packed-frontier bit budgets to REACHABLE "
                        "ranges, prunes statically dead actions from "
                        "the kernel lane tables, and seeds the fused "
                        "commit's expansion caps from static fanout "
                        "bounds.  off runs declared-widths packing and "
                        "full action lists.  Results are bit-identical "
                        "on/off; snapshots record the facts digest "
                        "(resuming under a flipped -bounds is a policy "
                        "error)")
    p.add_argument("-por", choices=["on", "off"], default=None,
                   metavar="MODE",
                   help="ample-set partial-order reduction in the "
                        "fused commit (default on while the lint gate "
                        "is live and no blocker applies): the speclint "
                        "independence pass (pass 7) proves pairwise "
                        "action commutativity; at states where one "
                        "independent invisible action suffices, the "
                        "level kernel expands only that action.  "
                        "Invariant and deadlock verdicts are "
                        "bit-identical on/off; state/transition COUNTS "
                        "may shrink.  Refused (forced on errors; auto "
                        "stays off) under temporal properties, "
                        "-edges on, -commit per-action, -simulate/"
                        "-validate, or -lint=off.  Snapshots record "
                        "the facts digest (resuming under a flipped "
                        "-por is a policy error)")
    p.add_argument("-lint", nargs="?", const="full", default=None,
                   choices=["full", "off"], metavar="MODE",
                   help="run the speclint static analyzer and exit "
                        "(plain -lint), or -lint=off to disable the "
                        "engine pre-flight gate")
    p.add_argument("-metrics", default=None, metavar="FILE",
                   help="dump the tpuvsr-metrics/1 JSON document "
                        "(phase timers, counters, per-level rows) to "
                        "FILE and print a stats table on stderr")
    p.add_argument("-journal", default=None, metavar="FILE",
                   help="append the JSONL run journal to FILE "
                        "(continues across -recover)")
    p.add_argument("-supervise", action="store_true",
                   help="run the BFS under the resilience supervisor: "
                        "OOM degrades (tile halving -> paged fallback) "
                        "with backoff retries; SIGTERM/SIGINT "
                        "checkpoints at the next level boundary and "
                        "exits with the resumable code 75")
    p.add_argument("-inject", default=None, metavar="SPEC",
                   help="arm deterministic fault injection (grammar: "
                        "oom@level=N, oom@shard=S, kill@level=N, "
                        "corrupt-ckpt:FILE[@level=N], "
                        "exchange-drop[:K]@shard=S; comma-separated; "
                        ":K = K consecutive drops)")
    return p


def validate_args(parser, args):
    """Flag-conflict validation at parse time: documented mutual
    exclusions fail with argparse's usage error (exit code 2) instead
    of a late engine failure."""
    if args.fused and not args.supervise and (
            args.checkpoint is not None or args.recover):
        parser.error("-fused cannot be combined with "
                     "-checkpoint/-recover without -supervise (only "
                     "the supervised fused run bounds its dispatch to "
                     "a rescue quantum; a fused resume continues "
                     "through the chunked engine)")
    if args.pipeline is not None and args.pipeline < 1:
        parser.error(f"-pipeline must be >= 1 (got {args.pipeline})")
    if args.chained:
        if args.fused:
            parser.error("-chained and -fused are different device "
                         "dispatch modes; pick one")
        if args.engine == "sharded":
            parser.error("-chained is the device engine's cross-level "
                         "window; the sharded engine's per-level "
                         "exchange needs the host in the loop")
        if args.engine == "interp" or args.fpset == "host":
            parser.error("-chained needs the device engine")
        if args.simulate or args.validate is not None:
            parser.error("-chained configures the BFS dispatch "
                         "window; it cannot be combined with "
                         "-simulate/-validate")
        if args.recover and not args.supervise:
            parser.error("-chained has no resume path (its snapshots "
                         "resume through the chunked engine): combine "
                         "-recover with -supervise, which journals "
                         "the mode degrade, or drop -chained")
    if args.commit is not None:
        if args.engine == "interp" or args.fpset == "host":
            parser.error("-commit configures the device level kernel; "
                         "it cannot be combined with -engine interp/"
                         "-fpset host")
        if args.simulate or args.validate is not None:
            parser.error("-commit configures the BFS level kernel; it "
                         "cannot be combined with -simulate/-validate "
                         "(the fleet and the validator have their own "
                         "dispatch packing)")
    if args.fpset == "host" and args.engine == "device":
        parser.error("-fpset host requires -engine interp (the host "
                     "fingerprint set only exists in the interpreter)")
    if args.fpset in ("hbm", "paged") and args.engine == "interp":
        parser.error(f"-fpset {args.fpset} requires the device engine")
    if args.engine == "sharded":
        if args.simulate:
            parser.error("-engine sharded checks by BFS; simulation "
                         "runs on the device/interp engines")
        if args.fused:
            parser.error("-engine sharded cannot be combined with "
                         "-fused (the sharded engine has no fused "
                         "fixpoint; its per-level exchange needs the "
                         "host in the loop)")
        if args.fpset != "auto":
            parser.error(f"-engine sharded always uses the "
                         f"mesh-sharded HBM fingerprint set; it "
                         f"cannot be combined with -fpset "
                         f"{args.fpset}")
    if args.supervise and args.simulate:
        parser.error("-supervise supervises BFS runs, not simulation")
    for flag, given in (("-walkers", args.walkers is not None),
                        ("-split", args.split),
                        ("-hunt", args.hunt)):
        if given and not args.simulate:
            parser.error(f"{flag} needs -simulate (it configures the "
                         f"walker fleet)")
        if given and (args.engine == "interp"
                      or args.fpset == "host"):
            parser.error(f"{flag} needs the device fleet backend; it "
                         f"cannot be combined with -engine interp/"
                         f"-fpset host")
    if args.walkers is not None and args.walkers < 1:
        parser.error(f"-walkers must be >= 1 (got {args.walkers})")
    if args.hunt and args.deadlock:
        parser.error("-hunt collects invariant violations only (it "
                     "has no deadlock counterexample path); use plain "
                     "-simulate -deadlock")
    if args.supervise and (args.engine == "interp"
                           or args.fpset == "host"):
        parser.error("-supervise needs the device/paged/sharded "
                     "engine (the interpreter has no "
                     "checkpoint/degrade ladder)")
    if args.symmetry is not None and (args.engine == "interp"
                                      or args.fpset == "host"):
        parser.error("-symmetry configures the device "
                     "canonicalization kernel; the interpreter "
                     "always applies the declared SYMMETRY itself "
                     "(drop the flag or the -engine interp/-fpset "
                     "host selection)")
    if args.symmetry == "on" and args.validate is not None:
        parser.error("-symmetry on cannot be combined with -validate: "
                     "trace validation tracks CONCRETE states (an "
                     "observation may pin any variable to a specific "
                     "value), so orbit-equivalent candidates are not "
                     "interchangeable")
    if args.spill is not None:
        if args.engine in ("device", "interp", "sharded"):
            parser.error(f"-spill is the paged engine's host-page "
                         f"disk tier; it cannot be combined with "
                         f"-engine {args.engine} (device is HBM-only, "
                         f"sharded shards over HBM, the interpreter "
                         f"has no paged frontier)")
        if args.fpset in ("host", "hbm"):
            parser.error(f"-spill needs -fpset paged (or auto); "
                         f"-fpset {args.fpset} selects an engine "
                         f"without host frontier pages")
        if args.simulate or args.validate is not None:
            parser.error("-spill tiers the BFS frontier; it cannot "
                         "be combined with -simulate/-validate")
        if args.supervise:
            parser.error("-spill cannot be combined with -supervise "
                         "(the supervisor's degrade ladder manages "
                         "its own hbm -> paged fallback; run -fpset "
                         "paged -spill directly)")
    if args.edges == "on":
        if args.simulate or args.validate is not None:
            parser.error("-edges on streams the BFS behavior graph; "
                         "it cannot be combined with -simulate/"
                         "-validate (neither builds one)")
        if args.symmetry == "on":
            parser.error("-edges on cannot be combined with "
                         "-symmetry on: the behavior graph's nodes "
                         "are concrete states (liveness keeps its "
                         "SYMMETRY-off requirement)")
        if args.engine == "interp" or args.fpset == "host":
            parser.error("-edges on needs the paged device engine "
                         "(the edge stream rides the level kernel); "
                         "it cannot be combined with -engine interp/"
                         "-fpset host — the interpreter builds its "
                         "own graph")
    if args.pack == "on" and (args.engine == "interp"
                              or args.fpset == "host"):
        parser.error("-pack on needs a device engine (the packed "
                     "frontier is the device engines' interchange "
                     "format; the interpreter has no dense frontier "
                     "to pack)")
    if args.bounds == "on":
        if args.lint == "off":
            parser.error("-bounds on cannot be combined with "
                         "-lint=off: the tightened packing and pruned "
                         "action lists consume the speclint bounds "
                         "pass — an unverified spec's bounds cannot "
                         "be trusted (drop -lint=off or run "
                         "-bounds off)")
        if args.engine == "interp" or args.fpset == "host":
            parser.error("-bounds on configures the device engines' "
                         "static pre-pass consumption (tightened "
                         "packing, pruned lane tables); it cannot be "
                         "combined with -engine interp/-fpset host")
        if args.simulate or args.validate is not None:
            parser.error("-bounds on configures the BFS engines; the "
                         "fleet and the validator consume no bounds "
                         "facts (a forced flag must not be silently "
                         "inert) — drop -bounds on or run BFS mode")
    if args.por == "on":
        # ample-set POR (ISSUE 16): verdict-sound only inside the
        # fused BFS commit with the speclint gate live — every other
        # mode must refuse a forced flag rather than run it inert
        if args.lint == "off":
            parser.error("-por on cannot be combined with -lint=off: "
                         "the ample-set filter consumes the speclint "
                         "independence pass — commutativity facts "
                         "from an unverified spec cannot be trusted "
                         "(drop -lint=off or run -por off)")
        if args.engine == "interp" or args.fpset == "host":
            parser.error("-por on configures the device engines' "
                         "fused commit (the ample-set filter lives in "
                         "the level kernel); it cannot be combined "
                         "with -engine interp/-fpset host")
        if args.simulate or args.validate is not None:
            parser.error("-por on configures the BFS engines; the "
                         "fleet and the validator consume no "
                         "independence facts (a forced flag must not "
                         "be silently inert) — drop -por on or run "
                         "BFS mode")
        if args.edges == "on":
            parser.error("-por on cannot be combined with -edges on: "
                         "the reduced run omits transitions by "
                         "design, so the streamed behavior graph "
                         "would be incomplete (and the two share the "
                         "FPSet gids column)")
        if args.commit == "per-action":
            parser.error("-por on needs the fused commit (the "
                         "ample-set filter is a stage of the fused "
                         "level kernel); it cannot be combined with "
                         "-commit per-action")
    if args.validate is not None:
        # trace validation is its own engine mode (ISSUE 8): the
        # check/simulate mode switches and their engine shapes don't
        # compose with it — say so at parse time, not mid-run
        if args.simulate:
            parser.error("-validate checks recorded traces; it cannot "
                         "be combined with -simulate (the two are "
                         "different engine modes)")
        if args.hunt or args.split or args.walkers is not None:
            parser.error("-walkers/-split/-hunt configure the "
                         "simulation fleet; they cannot be combined "
                         "with -validate")
        if args.fused:
            parser.error("-validate has no fused fixpoint (its chunk "
                         "loop needs the host to commit divergences); "
                         "it cannot be combined with -fused")
        if args.supervise:
            parser.error("-validate runs its own rescue/resume and "
                         "OOM batch-halving ladder; it cannot be "
                         "combined with -supervise (use the dispatch "
                         "service for requeue loops)")
        if args.deadlock:
            parser.error("-validate has no deadlock notion (a trace "
                         "ending early is simply shorter); it cannot "
                         "be combined with -deadlock")
        if args.maxstates is not None:
            parser.error("-maxstates bounds BFS; -validate is bounded "
                         "by the trace file and -maxseconds")
        if args.checkpoint is not None:
            parser.error("-validate checkpoints are preemption-driven "
                         "rescues (SIGTERM -> snapshot -> exit 75), "
                         "not periodic; -checkpoint cannot be "
                         "combined with it (-checkpointdir sets the "
                         "rescue directory, -recover resumes)")
        if args.engine == "sharded":
            parser.error("-validate shards its trace batch over the "
                         "mesh itself; it cannot be combined with "
                         "-engine sharded (the BFS mesh engine)")
        if args.fpset in ("hbm", "paged"):
            parser.error(f"-fpset {args.fpset} configures the BFS "
                         f"fingerprint set; -validate keeps its "
                         f"candidate sets per trace (use -fpset host/"
                         f"-engine interp for the interpreter "
                         f"validator)")
    if args.batch is not None:
        if args.validate is None:
            parser.error("-batch sizes the -validate round; it needs "
                         "-validate")
        if args.batch < 1:
            parser.error(f"-batch must be >= 1 (got {args.batch})")
    if args.inject:
        from ..resilience.faults import FaultPlan
        try:
            FaultPlan.parse(args.inject)
        except ValueError as e:
            parser.error(f"-inject: {e}")


def _pick_engine(requested, fpset, spec):
    # -fpset mirrors TLC's pluggable FPSet class selection: the HBM
    # table only exists in the device engine, the host set only in the
    # interpreter (BASELINE.json north_star gating).  Conflicting
    # fpset/engine combinations are rejected at parse time by
    # validate_args (exit code 2), so only consistent ones reach here.
    if fpset == "hbm":
        return "device"
    if fpset == "paged":
        return "paged"
    if fpset == "host":
        return "interp"
    if requested != "auto":
        return requested
    # modules with a compiled device kernel (models/registry.py) run on
    # the device engine; everything else on the interpreter
    from ..models.registry import has_device_model
    return "device" if has_device_model(spec) else "interp"


def _format_divergence(rec):
    """Render one divergence record the way violation traces render:
    the recorded event that no spec transition matches, plus the
    spec-side enabled set at that point."""
    lines = [f"Error: trace {rec['trace']} diverges at event "
             f"{rec['step']}."]
    ev = rec.get("event") or {}
    if ev.get("action"):
        lines.append(f"  recorded action: {ev['action']}")
    if ev.get("vars"):
        lines.append("  recorded observation: "
                     + ", ".join(f"{k} = {v}"
                                 for k, v in sorted(ev["vars"].items())))
    if rec.get("reason") == "no-init-state":
        lines.append("  no spec init state matches the trace's init "
                     "observation")
    lines.append(f"  candidate states at the divergence: "
                 f"{rec.get('candidates', 0)}")
    enabled = rec.get("enabled") or []
    if enabled:
        lines.append("  spec-side enabled actions there:")
        for e in enabled:
            loc = f"  ({e['location']})" if e.get("location") else ""
            par = (f"[{e['param']}]" if e.get("param") is not None
                   else "")
            lines.append(f"    {e['action']}{par}{loc}")
    else:
        lines.append("  no spec action is enabled there (the spec "
                     "deadlocks where the implementation continued)")
    if rec.get("invariant"):
        lines.append(f"  note: every candidate state violated "
                     f"invariant {rec['invariant']} from event "
                     f"{rec['invariant_step']} on")
    return "\n".join(lines)


def _run_validate(args, spec, engine, obs, log, summary_metrics):
    """The -validate execution branch (ISSUE 8): load TRACE.jsonl,
    run the batched device validator (interpreter fallback), report
    the first divergence, and map the ending onto the unified
    exit-code table (0 accepted / 12 diverged / 75 preempted)."""
    from ..core.values import TLAError
    from ..exitcodes import EX_RESUMABLE
    from ..validate import host_validate_batch, load_traces
    try:
        traces = load_traces(args.validate, spec)
    except (OSError, TLAError) as e:
        print(f"[tpuvsr] -validate: {e}", file=sys.stderr)
        return 2
    log(f"validating {len(traces)} trace(s) from {args.validate}")
    if engine == "interp":
        if args.recover:
            log(f"-recover {args.recover} ignored: the interpreter "
                f"validator keeps no rescue snapshots (it restarts "
                f"from trace 0)")
        res = host_validate_batch(spec, traces, obs=obs, log=log,
                                  max_seconds=args.maxseconds)
    else:
        from ..resilience.supervisor import (Preempted,
                                             PreemptionGuard)
        from ..validate import ObservationUnsupported
        from ..validate.batch import BatchValidator
        ckpt_dir = args.checkpointdir or (
            os.path.splitext(args.spec)[0] + ".ckpt")
        try:
            # encodability is pre-flighted BEFORE the journal-backed
            # observer is handed over, so a fallback run still writes
            # the user's -journal/-metrics through the same observer
            bv = BatchValidator(spec, batch=args.batch or 1024,
                                pipeline=args.pipeline, log=log)
            bv.check_observations(traces)
        except ObservationUnsupported as e:
            # the codec cannot express some observation as encoded-
            # leaf comparisons — the interpreter validator is fully
            # general, so fall back instead of failing the run
            log(f"{e}; falling back to the interpreter validator")
            if args.recover:
                log(f"-recover {args.recover} ignored: the "
                    f"interpreter validator keeps no rescue "
                    f"snapshots (it restarts from trace 0)")
            res = host_validate_batch(spec, traces, obs=obs, log=log,
                                      max_seconds=args.maxseconds)
            bv = None
        try:
            if bv is not None:
                with PreemptionGuard(log=log):
                    res = bv.run(traces, checkpoint_path=ckpt_dir,
                                 resume_from=args.recover, obs=obs,
                                 log=log, max_seconds=args.maxseconds)
        except Preempted as p:
            log(f"{p}; rerun with -recover {p.path} to continue "
                f"(exit {EX_RESUMABLE})")
            return EX_RESUMABLE
    summary = {"mode": "validate", "ok": res.ok,
               "traces": res.traces_checked,
               "accepted": res.accepted,
               "divergences": len(res.divergences or []),
               "first_divergence": res.first_divergence,
               "traces_per_sec": round(res.traces_per_sec, 1),
               "error": res.error,
               "elapsed_s": round(res.elapsed, 3),
               "metrics": summary_metrics(res.metrics)}
    if res.divergences:
        print(_format_divergence(res.divergences[0]),
              file=sys.stderr)
    if args.json:
        print(json.dumps(summary))
    else:
        for k, v in summary.items():
            if k != "first_divergence":
                print(f"{k}: {v}")
    return EX_OK if res.ok else EX_VIOLATION


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    # dispatch-service verbs (ISSUE 6): `python -m tpuvsr serve|submit|
    # status|cancel ...` routes to tpuvsr/service/api.py before the
    # TLC-compatible parser ever sees the argv (a positional spec named
    # "serve" is implausible; use ./serve to check a file of that name)
    if argv and argv[0] in ("serve", "submit", "status", "cancel",
                            "telemetry"):
        from ..service.api import main as service_main
        return service_main(argv)
    parser = build_parser()
    args = parser.parse_args(argv)
    validate_args(parser, args)
    if args.lower:
        os.environ["TPUVSR_COMPILED"] = "1"
    if args.lint == "off":
        os.environ["TPUVSR_LINT"] = "off"
    if args.inject:
        from ..resilience import faults
        faults.install(args.inject)
    from ..engine.spec import load_spec
    from ..engine.trace import format_trace
    from ..platform_select import ensure_backend

    cfg_path = args.config or os.path.splitext(args.spec)[0] + ".cfg"
    spec = load_spec(args.spec, cfg_path)

    if args.lint == "full":
        # lint-only mode: full report (all five passes), no dispatch
        from ..analysis import run_lint
        report = run_lint(spec)
        print(report.to_json() if args.json else report.render())
        return report.exit_code

    # spec-dependent -symmetry/-spill conflicts (exit 2, like the
    # parse-time ones — the cfg had to load first)
    if args.symmetry == "on" and spec.temporal_props:
        parser.error("-symmetry on cannot be combined with temporal "
                     "properties: liveness checking requires SYMMETRY "
                     "off (the reference cfg comments insist, and the "
                     "behavior graph must distinguish orbit members)")
    if args.symmetry == "on" and not spec.symmetry_perms:
        parser.error("-symmetry on: the cfg declares no SYMMETRY — "
                     "there is no permutation group to reduce by")
    if args.spill is not None and spec.temporal_props:
        parser.error("-spill cannot be combined with temporal "
                     "properties (the liveness graph enumeration "
                     "needs whole levels resident)")
    if args.edges == "on" and not spec.temporal_props:
        parser.error("-edges on: the cfg declares no PROPERTY — "
                     "there is no temporal check to consume the "
                     "behavior-graph stream")
    if args.por == "on" and spec.temporal_props:
        parser.error("-por on cannot be combined with temporal "
                     "properties: the reduced run preserves "
                     "invariant/deadlock verdicts, not the full "
                     "behavior graph the liveness checker consumes")

    engine = _pick_engine(args.engine, args.fpset, spec)
    if args.spill is not None:
        if engine == "interp":
            # auto-resolution landed on the interpreter (no compiled
            # kernel): dropping the disk-tier request silently would
            # betray the flag — same loud contract as the explicit
            # -engine interp conflict
            parser.error("-spill needs the paged device engine; this "
                         "spec resolved to the interpreter (no "
                         "compiled device kernel)")
        engine = "paged"            # -spill implies the paged engine
    if args.por == "on" and engine == "interp":
        # same loud contract as -spill: auto-resolution landing on
        # the interpreter must not leave a forced -por silently inert
        parser.error("-por on needs a compiled device kernel (the "
                     "ample-set filter is a stage of the fused level "
                     "kernel); this spec resolved to the interpreter")
    if args.pipeline is None:
        # default 2 on every device engine (ISSUE 9: the sharded step
        # now donates its buffers, so the K-generations-in-HBM cost
        # that made its window opt-in is gone)
        args.pipeline = 2
    # packed frontier (ISSUE 9): default on for device engines ("auto"
    # packs whenever the codec declares plane_bounds — every
    # registered layout); -pack off runs the dense format
    pack_kw = False if args.pack == "off" else "auto"
    # level-kernel commit mode (ISSUE 10): fused is the default
    commit_kw = args.commit or "fused"
    # symmetry canonicalization (ISSUE 11): on iff declared, unless
    # the flag forces it
    symmetry_kw = {"on": True, "off": False}.get(args.symmetry, "auto")
    # bounds pre-pass consumption (ISSUE 13): "auto" = on iff the
    # speclint gate is live (engine/bounds.resolve_bounds)
    bounds_kw = {"on": True, "off": False}.get(args.bounds, "auto")
    # ample-set POR (ISSUE 16): "auto" = on iff the speclint gate is
    # live and no soundness blocker applies (engine/por.resolve_por);
    # forced-on conflicts were rejected above, so resolve_por's own
    # TLAError only fires for spec-level refusals (poisoned facts)
    por_kw = args.por or "auto"
    spill_kw = ({"spill_dir": args.spill} if args.spill is not None
                else {})

    def log(msg):
        print(f"[tpuvsr] {msg}", file=sys.stderr)

    if args.supervise and engine == "interp":
        log("-supervise needs the device/paged engine; this spec "
            "resolved to the interpreter — running unsupervised")
        args.supervise = False
    if args.simulate and engine == "interp" and (
            args.walkers is not None or args.split or args.hunt):
        log("-walkers/-split/-hunt need a compiled device kernel "
            "(the walker fleet); this spec resolved to the "
            "interpreter — running plain host simulation")

    if engine in ("device", "paged", "sharded"):
        if engine == "sharded":
            # multi-host env (TPUVSR_MH_*): jax.distributed must
            # initialize before the backend is touched, for BOTH the
            # supervised and plain sharded paths (a supervised pack
            # that skips this sees only local devices and its
            # rank-agreement degenerates to single-process)
            from ..parallel.multihost import init_from_env
            init_from_env()
        backend = ensure_backend(log)
        log(f"backend: {backend}")
    mode = ("trace validation" if args.validate
            else "simulation" if args.simulate else "BFS")
    log(f"spec {spec.module.name}, engine {engine}, {mode}")

    # speclint pre-flight: same gate the engines run, surfaced here as
    # a clean exit instead of a traceback (the engines' own call then
    # hits the per-spec cache).  -lint=off / TPUVSR_LINT=off bypasses.
    from ..analysis import LintError, preflight
    try:
        preflight(spec, log=log)
    except LintError as e:
        print(f"[tpuvsr] {e}", file=sys.stderr)
        return EX_LINT

    # observability: one RunObserver rides the whole engine run —
    # journal (JSONL event stream), metrics collector, profiler hooks.
    # Supervised runs get per-attempt observers from the supervisor
    # instead (same journal file, fresh run_id per attempt).
    from ..obs import RunObserver
    obs = None if args.supervise else RunObserver(
        journal_path=args.journal, metrics_path=args.metrics, log=log)

    def summary_metrics(m):
        """The -json merge: collector output minus the per-level rows
        (those live in the -metrics file; the one-line summary stays
        one line)."""
        if not m:
            return None
        return {k: m[k] for k in ("run_id", "phases", "counters",
                                  "gauges") if k in m}

    if args.validate:
        # trace-validation mode (ISSUE 8): its own engine, its own
        # exit-code handling — the branch returns directly
        return _run_validate(args, spec, engine, obs, log,
                             summary_metrics)

    if args.simulate:
        if engine in ("device", "paged"):
            # the walker fleet (tpuvsr/sim) is the simulation backend
            # (it supersedes engine/device_sim's scan loop): sharded
            # across every visible device, deterministic per
            # (seed, walk id) at any walker count/mesh shape
            from ..sim import fleet_simulate, run_hunt
            walkers = args.walkers or 1024
            split = True if args.split else None
            if args.hunt:
                res = run_hunt(spec, walkers=walkers,
                               depth=args.depth, seed=args.seed,
                               num=args.num, split=split,
                               pipeline=args.pipeline,
                               max_seconds=args.maxseconds,
                               symmetry=symmetry_kw,
                               obs=obs, log=log)
            else:
                res = fleet_simulate(
                    spec, num=args.num, depth=args.depth,
                    seed=args.seed, walkers=walkers, split=split,
                    pipeline=args.pipeline,
                    check_deadlock=args.deadlock, log=log,
                    max_seconds=args.maxseconds, obs=obs,
                    symmetry=symmetry_kw)
        else:
            from ..engine.simulate import simulate
            res = simulate(spec, num=args.num, depth=args.depth,
                           seed=args.seed, check_deadlock=args.deadlock,
                           log=log, time_budget=args.maxseconds,
                           obs=obs)
        summary = {"mode": "simulate", "ok": res.ok,
                   "walks": res.walks, "steps": res.steps,
                   "violated": res.violated_invariant,
                   "elapsed_s": round(res.elapsed, 3),
                   "metrics": summary_metrics(res.metrics)}
        if getattr(res, "walkers", 0):
            summary["walkers"] = res.walkers
        if getattr(res, "violations", None) is not None:
            summary["unique_violations"] = len(res.violations)
    else:
        if engine in ("device", "paged", "sharded"):
            from ..engine.device_bfs import DeviceBFS
            from ..engine.paged_bfs import PagedBFS
            ckpt_dir = args.checkpointdir or (
                os.path.splitext(args.spec)[0] + ".ckpt")
            if args.supervise:
                # resilience supervisor: OOM retry/degrade ladder +
                # SIGTERM/SIGINT -> rescue checkpoint + resumable exit
                from ..resilience.supervisor import (EXIT_RESUMABLE,
                                                     Preempted,
                                                     Supervisor)
                sup = Supervisor(
                    spec, engine=engine,
                    checkpoint_path=ckpt_dir,
                    # no explicit -checkpoint: snapshot every level
                    # boundary so a degrade/rescue never loses more
                    # than the in-flight level
                    checkpoint_every=(args.checkpoint * 60.0
                                      if args.checkpoint else None),
                    journal_path=args.journal,
                    metrics_path=args.metrics, log=log,
                    # -fused under -supervise: rescue-quantum-bounded
                    # fused dispatches; resume continues chunked.
                    # -chained likewise: the chained window's
                    # level-boundary rescue seam checkpoints, resume
                    # continues chunked (journaled mode degrade)
                    fused=args.fused and engine == "device",
                    chained=args.chained and engine == "device",
                    engine_kwargs={"pipeline": args.pipeline,
                                   "pack": pack_kw,
                                   "commit": commit_kw,
                                   "symmetry": symmetry_kw,
                                   "bounds": bounds_kw,
                                   "por": por_kw})
                try:
                    res = sup.run(max_states=args.maxstates,
                                  max_seconds=args.maxseconds,
                                  check_deadlock=args.deadlock,
                                  resume_from=args.recover)
                except Preempted as p:
                    log(f"{p}; rerun with -recover {p.path} to "
                        f"continue (exit {EXIT_RESUMABLE})")
                    return EXIT_RESUMABLE
                eng = sup.engine
                log(f"supervised run done: {sup.summary()}")
            elif engine == "sharded":
                # multi-chip BFS over every visible device (the mesh
                # is the whole device set; multi-host runs set the
                # TPUVSR_MH_* env — jax.distributed was initialized
                # with the backend above, so devices() spans hosts)
                import numpy as np

                import jax
                from jax.sharding import Mesh

                from ..parallel.sharded_bfs import ShardedBFS
                mesh = Mesh(np.array(jax.devices()), ("d",))
                log(f"sharded mesh: {mesh.shape['d']} devices")
                eng = ShardedBFS(spec, mesh, pipeline=args.pipeline,
                                 pack=pack_kw, commit=commit_kw,
                                 symmetry=symmetry_kw,
                                 bounds=bounds_kw, por=por_kw)
                res = eng.run(
                    max_states=args.maxstates,
                    max_seconds=args.maxseconds,
                    check_deadlock=args.deadlock, log=log, obs=obs,
                    checkpoint_path=(ckpt_dir if args.checkpoint or
                                     args.recover else None),
                    checkpoint_every=(args.checkpoint * 60.0
                                      if args.checkpoint else
                                      30 * 60.0 if args.recover
                                      else None),
                    resume_from=args.recover)
            else:
                # temporal properties need the behavior graph: run the
                # safety BFS through the paged engine with level
                # retention so the device graph builder reuses the
                # enumeration instead of re-running it
                want_graph = bool(spec.temporal_props) and \
                    not spec.symmetry_perms
                if want_graph:
                    # edge stream on by default (ISSUE 15): the
                    # behavior graph flows out of the safety BFS;
                    # -edges off keeps the two-pass re-expansion
                    # (DeviceGraph mode="two-pass") as the oracle
                    eng = PagedBFS(spec, retain_levels=True,
                                   edges=args.edges != "off",
                                   pipeline=args.pipeline,
                                   pack=pack_kw, commit=commit_kw,
                                   symmetry=symmetry_kw,
                                   bounds=bounds_kw)
                elif engine == "paged":
                    eng = PagedBFS(spec, pipeline=args.pipeline,
                                   pack=pack_kw, commit=commit_kw,
                                   symmetry=symmetry_kw,
                                   bounds=bounds_kw, por=por_kw,
                                   **spill_kw)
                else:
                    eng = DeviceBFS(spec, pipeline=args.pipeline,
                                    pack=pack_kw, commit=commit_kw,
                                    symmetry=symmetry_kw,
                                    bounds=bounds_kw, por=por_kw)
                use_fused = (args.fused and isinstance(eng, DeviceBFS)
                             and not isinstance(eng, PagedBFS))
                if args.fused and not use_fused:
                    log("-fused needs the plain device engine (no "
                        "temporal properties / -fpset paged); using "
                        "chunked run")
                if use_fused and (args.checkpoint or args.recover):
                    log("-fused excludes -checkpoint/-recover; "
                        "using chunked run")
                    use_fused = False
                use_chained = (args.chained
                               and isinstance(eng, DeviceBFS)
                               and not isinstance(eng, PagedBFS))
                if args.chained and not use_chained:
                    log("-chained needs the plain device engine (no "
                        "temporal properties / -fpset paged); using "
                        "chunked run")
                if use_fused:
                    res = eng.run_fused(
                        max_states=args.maxstates,
                        max_seconds=args.maxseconds,
                        check_deadlock=args.deadlock, log=log, obs=obs)
                elif use_chained:
                    # the chained window is checkpointable through its
                    # level-boundary rescue seam (ISSUE 10 satellite)
                    # — no more silent fallback to run() for
                    # checkpointed runs
                    res = eng.run_chained(
                        max_states=args.maxstates,
                        max_seconds=args.maxseconds,
                        check_deadlock=args.deadlock, log=log, obs=obs,
                        checkpoint_path=(ckpt_dir if args.checkpoint
                                         else None),
                        checkpoint_every=(args.checkpoint * 60.0
                                          if args.checkpoint
                                          else None))
                else:
                    res = eng.run(
                        max_states=args.maxstates,
                        max_seconds=args.maxseconds,
                        check_deadlock=args.deadlock, log=log, obs=obs,
                        checkpoint_path=(ckpt_dir if args.checkpoint or
                                         args.recover else None),
                        # checkpoint_every=None means "every level
                        # boundary"; a resumed run without an explicit
                        # -checkpoint gets TLC's default 30-minute
                        # cadence instead of an unrequested full
                        # snapshot per level
                        checkpoint_every=(args.checkpoint * 60.0
                                          if args.checkpoint else
                                          30 * 60.0 if args.recover
                                          else None),
                        resume_from=args.recover)
        else:
            if args.checkpoint or args.recover:
                log("checkpoint/recover is a device-engine feature; "
                    "ignored for the interpreter")
            from ..engine.bfs import bfs_check
            res = bfs_check(spec, check_deadlock=args.deadlock,
                            max_states=args.maxstates, log=log, obs=obs)
        summary = {"mode": "bfs", "ok": res.ok,
                   "distinct_states": res.distinct_states,
                   "states_generated": res.states_generated,
                   "diameter": res.diameter,
                   "states_per_sec": round(res.states_per_sec, 1),
                   "violated": res.violated_invariant,
                   "error": res.error,
                   "elapsed_s": round(res.elapsed, 3),
                   "metrics": summary_metrics(res.metrics)}
        if args.supervise:
            summary["supervisor"] = sup.summary()
        if res.ok and not res.error and spec.temporal_props:
            from ..engine.liveness import liveness_check
            log(f"checking temporal properties: "
                f"{', '.join(spec.temporal_props)}")
            graph = None
            if engine in ("device", "paged", "sharded") and \
                    not spec.symmetry_perms:
                # device-built behavior graph, streamed out of the
                # safety BFS itself (ISSUE 15; -edges off keeps the
                # historical two-pass re-expansion as the oracle).
                # A resumed edge-stream run restores its retained
                # blocks + edge rows from the snapshot, so reuse
                # works across -recover too; runs without retained
                # blocks (supervised/sharded, or a snapshot written
                # without the stream) re-enumerate from scratch.
                from ..core.values import TLAError
                from ..engine.device_liveness import DeviceGraph
                gmode = "two-pass" if args.edges == "off" else "stream"
                if args.supervise or engine == "sharded":
                    graph = DeviceGraph(spec, log=log, mode=gmode)
                else:
                    try:
                        graph = DeviceGraph(spec, engine=eng,
                                            result=res, log=log)
                    except (TLAError, ValueError) as e:
                        log(f"retained enumeration unusable ({e}); "
                            f"re-enumerating for the liveness graph")
                        graph = DeviceGraph(spec, log=log, mode=gmode)
            # the liveness pass gets its own observer segment in the
            # same journal (second run_start/run_end pair, engine
            # "liveness"); the -metrics file stays the BFS engine's
            lobs = RunObserver(journal_path=args.journal, log=log)
            lres = liveness_check(spec, max_states=args.maxstates,
                                  log=log, graph=graph, obs=lobs)
            summary["liveness"] = summary_metrics(lres.metrics)
            summary["properties_ok"] = lres.ok
            if not lres.ok:
                res.ok = False
                res.trace = lres.trace
                summary["ok"] = False
                summary["violated"] = lres.property_name or lres.error
                res.violated_invariant = lres.property_name
                print(f"Error: Temporal property "
                      f"{lres.property_name or lres.error} is violated.",
                      file=sys.stderr)

    if not res.ok and res.trace:
        print(f"Error: Invariant {res.violated_invariant} is violated.",
              file=sys.stderr)
        print(format_trace(res.trace))
    if args.json:
        print(json.dumps(summary))
    else:
        for k, v in summary.items():
            print(f"{k}: {v}")
    # TLC's code 12 = safety violation (tpuvsr/exitcodes.py table)
    return EX_OK if res.ok else EX_VIOLATION


if __name__ == "__main__":
    sys.exit(main())
