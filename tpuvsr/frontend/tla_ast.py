"""AST node definitions for the TLA+ frontend.

Expressions are plain tuples ``(tag, ...)`` for fast dispatch in the
evaluator; definitions and modules are small classes.  Source locations
are tracked per-definition (and per-action via the definition) so the
trace reconstructor can emit TLC-style ``_TEAction`` annotations
(reference: state_transfer_violation_trace.txt:3-7).

Expression tags:
  ('num', int) ('str', s) ('bool', b) ('id', name)
  ('call', name, [args])
  ('and', [e..]) ('or', [e..]) ('not', e) ('neg', e)
  ('binop', op, a, b)   op in: in notin union setdiff intersect div mod
                        plus minus times concat lt le gt ge eq ne range
                        merge mapsto implies equiv subseteq
  ('exists', groups, body) / ('forall', groups, body)
        groups = [([names], set_expr), ...]
  ('choose', name, set_expr, body)
  ('lambda', [params], body)
  ('setenum', [e..]) ('setfilter', name, set_expr, pred)
  ('setmap', elem_expr, groups)
  ('tuple', [e..])
  ('fnctor', groups, body) ('record', [(name, e)..]) ('fnset', dom, rng)
  ('recordset', [(name, set_expr)..])
  ('except', f, [ (path, val) ])   path = [('idx', e) | ('fld', name)]
  ('at',)                           the @ inside EXCEPT values
  ('apply', f, arg) ('dot', e, field) ('prime', e)
  ('if', c, t, e) ('case', [(guard, val)..], other_or_None)
  ('let', [Def..], body)
  ('unchanged', e) ('enabled', e) ('domain', e) ('powerset', e)
  ('box', e) ('diamond', e) ('boxaction', act, sub) ('wf', sub, act)
  ('sf', sub, act)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class Def:
    name: str
    params: list           # parameter names ([] for constant operators)
    body: Any              # expression tuple
    recursive: bool = False
    # Source span of the whole definition (for _TEAction location output).
    line0: int = 0
    col0: int = 0
    line1: int = 0
    col1: int = 0
    module: str = ""


@dataclass
class Module:
    name: str
    extends: list = field(default_factory=list)
    constants: list = field(default_factory=list)
    variables: list = field(default_factory=list)
    defs: dict = field(default_factory=dict)      # name -> Def (ordered)
    assumes: list = field(default_factory=list)

    def get(self, name: str) -> Optional[Def]:
        return self.defs.get(name)
