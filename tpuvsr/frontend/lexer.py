"""Tokenizer for the TLA+ subset exercised by the reference corpus.

Covers the constructs inventoried in SURVEY.md §2.6: junction lists
(column-sensitive /\\ and \\/ bullets — columns are recorded on every
token and the parser enforces the alignment rules), backslash operators
(\\in, \\notin, \\E, \\A, \\div, \\union, and bare \\ set difference),
nested block comments, module separator lines, primes, EXCEPT paths, and
the temporal tokens ([], <>, ~>) used by the liveness specs.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Token:
    kind: str   # 'ID', 'NUM', 'STR', 'OP', 'SEP' (---- line), 'END' (==== line), 'EOF'
    text: str
    line: int   # 1-based
    col: int    # 1-based

    def __repr__(self) -> str:
        return f"{self.kind}({self.text!r}@{self.line}:{self.col})"


# Longest-match-first symbol table.
_SYMBOLS = [
    "|->", "<=>", "==", "=>", "<=", ">=", "~>", "..", "@@", ":>",
    "<<", ">>", "[]", "<>", "/\\", "\\/", "->",
    "=", "#", "<", ">", "+", "-", "%", "*",
    "(", ")", "[", "]", "{", "}", ",", ":", ".", "'", "!", "@", "~", "_", ";",
]

# \word operators that are meaningful in the corpus.
_BACKSLASH_WORDS = {
    "in", "notin", "union", "cup", "intersect", "cap", "div", "o",
    "E", "A", "X", "subseteq", "subset",
}

_KEYWORDS = {
    "MODULE", "EXTENDS", "CONSTANT", "CONSTANTS", "VARIABLE", "VARIABLES",
    "RECURSIVE", "LET", "IN", "IF", "THEN", "ELSE", "CASE", "OTHER",
    "CHOOSE", "LAMBDA", "DOMAIN", "SUBSET", "UNION", "UNCHANGED", "EXCEPT",
    "ENABLED", "ASSUME", "ASSUMPTION", "THEOREM", "INSTANCE", "LOCAL",
    "TRUE", "FALSE", "BOOLEAN", "OTHER",
}


class LexError(Exception):
    pass


def tokenize(src: str) -> list:
    toks = []
    line = 1
    col = 1
    i = 0
    n = len(src)

    def error(msg):
        raise LexError(f"{msg} at line {line}, col {col}")

    while i < n:
        c = src[i]
        if c == "\n":
            i += 1
            line += 1
            col = 1
            continue
        if c in " \t\r":
            i += 1
            col += 1
            continue
        # line comment
        if c == "\\" and i + 1 < n and src[i + 1] == "*":
            while i < n and src[i] != "\n":
                i += 1
            continue
        # block comment (nested)
        if c == "(" and i + 1 < n and src[i + 1] == "*":
            depth = 1
            i += 2
            col += 2
            while i < n and depth > 0:
                if src[i] == "(" and i + 1 < n and src[i + 1] == "*":
                    depth += 1
                    i += 2
                    col += 2
                elif src[i] == "*" and i + 1 < n and src[i + 1] == ")":
                    depth -= 1
                    i += 2
                    col += 2
                elif src[i] == "\n":
                    i += 1
                    line += 1
                    col = 1
                else:
                    i += 1
                    col += 1
            continue
        # separator lines: runs of 4+ '-' or '='
        if c == "-" and src.startswith("----", i):
            j = i
            while j < n and src[j] == "-":
                j += 1
            toks.append(Token("SEP", src[i:j], line, col))
            col += j - i
            i = j
            continue
        if c == "=" and src.startswith("====", i):
            j = i
            while j < n and src[j] == "=":
                j += 1
            toks.append(Token("END", src[i:j], line, col))
            col += j - i
            i = j
            continue
        # number
        if c.isdigit():
            j = i
            while j < n and src[j].isdigit():
                j += 1
            toks.append(Token("NUM", src[i:j], line, col))
            col += j - i
            i = j
            continue
        # identifier / keyword (may start with _ if followed by alnum)
        if c.isalpha() or (c == "_" and i + 1 < n and (src[i + 1].isalnum() or src[i + 1] == "_")):
            j = i
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            text = src[i:j]
            toks.append(Token("ID", text, line, col))
            col += j - i
            i = j
            continue
        # string
        if c == '"':
            j = i + 1
            buf = []
            while j < n and src[j] != '"':
                if src[j] == "\n":
                    error("unterminated string")
                buf.append(src[j])
                j += 1
            if j >= n:
                error("unterminated string")
            toks.append(Token("STR", "".join(buf), line, col))
            col += j + 1 - i
            i = j + 1
            continue
        # backslash operators ('\/' must win over backslash-word scanning)
        if c == "\\":
            if src.startswith("\\/", i):
                toks.append(Token("OP", "\\/", line, col))
                col += 2
                i += 2
                continue
            j = i + 1
            while j < n and src[j].isalpha():
                j += 1
            word = src[i + 1:j]
            if word:
                if word not in _BACKSLASH_WORDS:
                    error(f"unknown operator \\{word}")
                toks.append(Token("OP", "\\" + word, line, col))
                col += j - i
                i = j
            else:
                toks.append(Token("OP", "\\", line, col))
                col += 1
                i += 1
            continue
        # symbols, longest first
        for sym in _SYMBOLS:
            if src.startswith(sym, i):
                toks.append(Token("OP", sym, line, col))
                col += len(sym)
                i += len(sym)
                break
        else:
            error(f"unexpected character {c!r}")
    toks.append(Token("EOF", "", line, col))
    return toks
