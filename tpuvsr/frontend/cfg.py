"""TLC .cfg model-file parser.

Grammar exercised by the corpus (all five reference cfgs, e.g.
vsr-revisited/paper/VSR.cfg): CONSTANTS bindings (model values, sets of
model values, numbers), INIT/NEXT or SPECIFICATION, VIEW, SYMMETRY,
INVARIANT and PROPERTY name lists, and \\* comments.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..core.values import ModelValue


@dataclass
class CfgModel:
    constants: dict = field(default_factory=dict)   # name -> value
    init: str = None
    next: str = None
    specification: str = None
    view: str = None
    symmetry: str = None
    invariants: list = field(default_factory=list)
    properties: list = field(default_factory=list)
    constraints: list = field(default_factory=list)


_SECTIONS = {"CONSTANTS", "CONSTANT", "INIT", "NEXT", "SPECIFICATION",
             "VIEW", "SYMMETRY", "INVARIANT", "INVARIANTS", "PROPERTY",
             "PROPERTIES", "CONSTRAINT", "CONSTRAINTS"}


def _parse_value(text: str):
    text = text.strip()
    if text.startswith("{"):
        inner = text.strip("{}").strip()
        if not inner:
            return frozenset()
        return frozenset(_parse_value(p) for p in inner.split(","))
    if text in ("TRUE", "FALSE"):
        return text == "TRUE"
    if text.lstrip("-").isdigit():
        return int(text)
    if text.startswith('"') and text.endswith('"'):
        return text[1:-1]
    return ModelValue(text)


def parse_cfg_text(src: str) -> CfgModel:
    cfg = CfgModel()
    # strip comments
    lines = []
    for raw in src.splitlines():
        idx = raw.find("\\*")
        if idx >= 0:
            raw = raw[:idx]
        if raw.strip():
            lines.append(raw.strip())

    section = None
    i = 0
    while i < len(lines):
        line = lines[i]
        head = line.split()[0]
        if head in _SECTIONS:
            section = head
            rest = line[len(head):].strip()
            i += 1
            if rest:
                _feed(cfg, section, rest)
                if section in ("INIT", "NEXT", "SPECIFICATION", "VIEW", "SYMMETRY"):
                    section = None
            continue
        if section is None:
            raise ValueError(f"cfg line outside any section: {line!r}")
        _feed(cfg, section, line)
        i += 1
    return cfg


def _feed(cfg: CfgModel, section: str, line: str):
    if section in ("CONSTANTS", "CONSTANT"):
        if "=" in line:
            name, val = line.split("=", 1)
            cfg.constants[name.strip()] = _parse_value(val)
        elif "<-" in line:
            name, val = line.split("<-", 1)
            cfg.constants[name.strip()] = _parse_value(val)
        else:
            raise ValueError(f"bad CONSTANTS line: {line!r}")
    elif section == "INIT":
        cfg.init = line.strip()
    elif section == "NEXT":
        cfg.next = line.strip()
    elif section == "SPECIFICATION":
        cfg.specification = line.strip()
    elif section == "VIEW":
        cfg.view = line.strip()
    elif section == "SYMMETRY":
        cfg.symmetry = line.strip()
    elif section in ("INVARIANT", "INVARIANTS"):
        cfg.invariants.extend(line.split())
    elif section in ("PROPERTY", "PROPERTIES"):
        cfg.properties.extend(line.split())
    elif section in ("CONSTRAINT", "CONSTRAINTS"):
        cfg.constraints.extend(line.split())


def parse_cfg_file(path: str) -> CfgModel:
    with open(path) as f:
        return parse_cfg_text(f.read())
