"""Recursive-descent / Pratt parser for the TLA+ subset in the corpus.

Junction lists (the column-sensitive /\\ and \\/ bullet lists that
structure every action in the reference, e.g. VSR.tla:366-394) are
handled with a ``min_col`` threshold threaded through expression parsing:
a bullet list started at column c parses each item with ``min_col = c``,
and any token at column <= c terminates the item — which is exactly the
TLA+ alignment rule for well-formed specs.  Tokens inside brackets are
exempt (we reset min_col to 0 inside (), [], {}, <<>>), which is a
conservative relaxation.

Top-level definition boundaries are pre-scanned (a token at column 1
starting ``Name ==``, ``Name(..) ==``, or a section keyword) so a
definition body can never swallow the next definition.
"""

from __future__ import annotations

from .lexer import Token, tokenize
from .tla_ast import Def, Module


class ParseError(Exception):
    pass


_SECTION_KEYWORDS = {
    "EXTENDS", "CONSTANT", "CONSTANTS", "VARIABLE", "VARIABLES",
    "RECURSIVE", "ASSUME", "ASSUMPTION", "THEOREM", "INSTANCE", "LOCAL",
}

# infix operator -> (binding power, ast op tag); follows the TLA+ operator
# precedence table (Specifying Systems, ch. 15): = (5) binds looser than
# @@ (6) / :> (7), set ops at 8, .. at 9, arithmetic at 10/13.
_INFIX = {
    "=>": (1, "implies"), "<=>": (2, "equiv"), "~>": (2, "leadsto"),
    "\\/": (3, "or"), "/\\": (4, "and"),
    "=": (5, "eq"), "#": (5, "ne"),
    "<": (5, "lt"), ">": (5, "gt"), "<=": (5, "le"), ">=": (5, "ge"),
    "\\in": (5, "in"), "\\notin": (5, "notin"), "\\subseteq": (5, "subseteq"),
    "@@": (6, "merge"), ":>": (7, "mapsto"),
    "\\union": (8, "union"), "\\cup": (8, "union"),
    "\\intersect": (8, "intersect"), "\\cap": (8, "intersect"),
    "\\": (8, "setdiff"),
    "..": (9, "range"),
    "+": (10, "plus"), "-": (10, "minus"),
    "\\o": (13, "concat"),
    "%": (13, "mod"), "\\div": (13, "div"), "*": (13, "times"),
}


class Parser:
    def __init__(self, src: str, filename: str = "<string>"):
        self.toks = tokenize(src)
        self.pos = 0
        self.filename = filename
        self.unit_starts = self._scan_unit_starts()

    # ------------------------------------------------------------------
    def _scan_unit_starts(self):
        starts = set()
        toks = self.toks
        n = len(toks)
        for i, t in enumerate(toks):
            if t.kind in ("SEP", "END", "EOF"):
                starts.add(i)
                continue
            if t.col != 1:
                continue
            if t.kind == "ID":
                if t.text in _SECTION_KEYWORDS:
                    starts.add(i)
                    continue
                # Name ==   or   Name(params) ==
                if i + 1 < n and toks[i + 1].kind == "OP":
                    if toks[i + 1].text == "==":
                        starts.add(i)
                    elif toks[i + 1].text == "(":
                        j = i + 2
                        depth = 1
                        while j < n and depth > 0:
                            if toks[j].kind == "OP" and toks[j].text == "(":
                                depth += 1
                            elif toks[j].kind == "OP" and toks[j].text == ")":
                                depth -= 1
                            j += 1
                        if j < n and toks[j].kind == "OP" and toks[j].text == "==":
                            starts.add(i)
        return starts

    # ------------------------------------------------------------------
    def peek(self, off: int = 0) -> Token:
        return self.toks[min(self.pos + off, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.pos]
        if t.kind != "EOF":
            self.pos += 1
        return t

    def at_op(self, text: str, off: int = 0) -> bool:
        t = self.peek(off)
        return t.kind == "OP" and t.text == text

    def at_id(self, text: str = None, off: int = 0) -> bool:
        t = self.peek(off)
        return t.kind == "ID" and (text is None or t.text == text)

    def expect_op(self, text: str) -> Token:
        t = self.next()
        if t.kind != "OP" or t.text != text:
            self.err(f"expected {text!r}, got {t}")
        return t

    def expect_id(self, text: str = None) -> Token:
        t = self.next()
        if t.kind != "ID" or (text is not None and t.text != text):
            self.err(f"expected identifier {text or ''}, got {t}")
        return t

    def err(self, msg: str):
        t = self.peek()
        raise ParseError(f"{self.filename}:{t.line}:{t.col}: {msg}")

    def _at_boundary(self) -> bool:
        return self.pos in self.unit_starts or self.peek().kind in ("END", "EOF")

    # ------------------------------------------------------------------
    # Module structure
    # ------------------------------------------------------------------
    def parse_module(self) -> Module:
        # ---- MODULE Name ----
        while self.peek().kind == "SEP":
            self.next()
            break
        self.expect_id("MODULE")
        name = self.expect_id().text
        if self.peek().kind == "SEP":
            self.next()
        mod = Module(name=name)
        recursive_decls = set()
        while True:
            if self.peek().kind in ("END", "EOF"):
                break
            if self.peek().kind == "SEP":
                self.next()
                continue
            t = self.peek()
            if t.kind == "ID" and t.text == "EXTENDS":
                self.next()
                mod.extends.append(self.expect_id().text)
                while self.at_op(","):
                    self.next()
                    mod.extends.append(self.expect_id().text)
            elif t.kind == "ID" and t.text in ("CONSTANTS", "CONSTANT"):
                self.next()
                mod.constants.append(self.expect_id().text)
                while self.at_op(","):
                    self.next()
                    mod.constants.append(self.expect_id().text)
            elif t.kind == "ID" and t.text in ("VARIABLES", "VARIABLE"):
                self.next()
                mod.variables.append(self.expect_id().text)
                while self.at_op(","):
                    self.next()
                    mod.variables.append(self.expect_id().text)
            elif t.kind == "ID" and t.text == "RECURSIVE":
                self.next()
                while True:
                    rname = self.expect_id().text
                    recursive_decls.add(rname)
                    if self.at_op("("):
                        self.next()
                        while not self.at_op(")"):
                            self.next()
                        self.next()
                    if self.at_op(","):
                        self.next()
                        continue
                    break
            elif t.kind == "ID" and t.text in ("ASSUME", "ASSUMPTION"):
                self.next()
                mod.assumes.append(self.parse_expr(0, 0))
            elif t.kind == "ID" and t.text == "LOCAL":
                self.next()  # treat LOCAL defs as ordinary defs
            elif t.kind == "ID":
                d = self.parse_definition()
                d.module = mod.name
                d.recursive = d.name in recursive_decls
                mod.defs[d.name] = d
            else:
                self.err(f"unexpected token at module level: {t}")
        return mod

    def parse_definition(self) -> Def:
        t0 = self.peek()
        name = self.expect_id().text
        params = []
        if self.at_op("("):
            self.next()
            while True:
                p = self.next()
                if p.kind == "ID":
                    params.append(p.text)
                elif p.kind == "OP" and p.text == "_":
                    params.append("_")
                else:
                    self.err(f"bad parameter {p}")
                if self.at_op(","):
                    self.next()
                    continue
                break
            self.expect_op(")")
        self.expect_op("==")
        body = self.parse_expr(0, 0)
        t1 = self.toks[self.pos - 1]
        return Def(name=name, params=params, body=body,
                   line0=t0.line, col0=t0.col, line1=t1.line,
                   col1=t1.col + len(t1.text) - 1)

    # ------------------------------------------------------------------
    # Expressions
    # ------------------------------------------------------------------
    def parse_expr(self, min_col: int, rbp: int):
        left = self.parse_primary(min_col)
        while True:
            if self._at_boundary():
                break
            t = self.peek()
            if t.kind != "OP":
                break
            info = _INFIX.get(t.text)
            if info is None:
                break
            lbp, tag = info
            if lbp <= rbp or t.col <= min_col:
                break
            self.next()
            right = self.parse_expr(min_col, lbp)
            if tag == "and":
                items = left[1] if left[0] == "and" else [left]
                left = ("and", items + [right])
            elif tag == "or":
                items = left[1] if left[0] == "or" else [left]
                left = ("or", items + [right])
            else:
                left = ("binop", tag, left, right)
        return left

    def parse_primary(self, min_col: int):
        if self._at_boundary():
            self.err("unexpected end of definition")
        t = self.peek()

        # junction lists
        if t.kind == "OP" and t.text in ("/\\", "\\/"):
            return self.parse_junction(min_col)

        if t.kind == "NUM":
            self.next()
            return self.postfix(("num", int(t.text)), min_col)
        if t.kind == "STR":
            self.next()
            return self.postfix(("str", t.text), min_col)

        if t.kind == "ID":
            return self.parse_id_led(min_col)

        if t.kind != "OP":
            self.err(f"unexpected token {t}")

        txt = t.text
        if txt == "~":
            self.next()
            return ("not", self.parse_expr(min_col, 4))
        if txt == "-":
            self.next()
            return ("neg", self.parse_expr(min_col, 12))
        if txt == "[]":
            self.next()
            if self.at_op("["):
                # [][A]_vars
                self.next()
                act = self.parse_expr(0, 0)
                self.expect_op("]")
                sub = self._parse_subscript()
                return ("boxaction", act, sub)
            return ("box", self.parse_expr(min_col, 4))
        if txt == "<>":
            self.next()
            return ("diamond", self.parse_expr(min_col, 4))
        if txt == "(":
            self.next()
            e = self.parse_expr(0, 0)
            self.expect_op(")")
            return self.postfix(e, min_col)
        if txt == "{":
            return self.postfix(self.parse_set(), min_col)
        if txt == "[":
            return self.postfix(self.parse_bracket(), min_col)
        if txt == "<<":
            self.next()
            items = []
            if not self.at_op(">>"):
                items.append(self.parse_expr(0, 0))
                while self.at_op(","):
                    self.next()
                    items.append(self.parse_expr(0, 0))
            self.expect_op(">>")
            return self.postfix(("tuple", items), min_col)
        if txt == "@":
            self.next()
            return self.postfix(("at",), min_col)
        if txt in ("\\E", "\\A"):
            self.next()
            groups = self.parse_bound_groups()
            self.expect_op(":")
            body = self.parse_expr(min_col, 0)
            return ("exists" if txt == "\\E" else "forall", groups, body)
        self.err(f"unexpected operator {txt!r}")

    def _parse_subscript(self):
        # the `_vars` after `[][Next]` — lexed as a single identifier
        t = self.next()
        if t.kind != "ID" or not t.text.startswith("_"):
            self.err(f"expected _subscript after ]: got {t}")
        return ("id", t.text[1:])

    def parse_id_led(self, min_col: int):
        t = self.next()
        name = t.text
        if name == "IF":
            cond = self.parse_expr(min_col, 0)
            self.expect_id("THEN")
            then = self.parse_expr(min_col, 0)
            self.expect_id("ELSE")
            els = self.parse_expr(min_col, 0)
            return ("if", cond, then, els)
        if name == "CASE":
            arms = []
            other = None
            while True:
                if self.at_id("OTHER"):
                    self.next()
                    self.expect_op("->")
                    other = self.parse_expr(min_col, 0)
                    break
                guard = self.parse_expr(min_col, 0)
                self.expect_op("->")
                val = self.parse_expr(min_col, 0)
                arms.append((guard, val))
                if self.at_op("[]"):
                    self.next()
                    continue
                break
            return ("case", arms, other)
        if name == "LET":
            defs = []
            while not self.at_id("IN"):
                if self.at_id("RECURSIVE"):
                    # RECURSIVE decl inside LET
                    self.next()
                    rn = self.expect_id().text
                    if self.at_op("("):
                        self.next()
                        while not self.at_op(")"):
                            self.next()
                        self.next()
                    defs.append(("__recursive__", rn))
                    continue
                d = self.parse_definition_inline()
                defs.append(d)
            self.expect_id("IN")
            body = self.parse_expr(min_col, 0)
            rec_names = {x[1] for x in defs if isinstance(x, tuple)}
            real_defs = [d for d in defs if isinstance(d, Def)]
            for d in real_defs:
                if d.name in rec_names:
                    d.recursive = True
            return ("let", real_defs, body)
        if name == "CHOOSE":
            var = self.expect_id().text
            self.expect_op("\\in")
            s = self.parse_expr(min_col, 0)
            self.expect_op(":")
            body = self.parse_expr(min_col, 0)
            return ("choose", var, s, body)
        if name == "LAMBDA":
            params = [self.expect_id().text]
            while self.at_op(","):
                self.next()
                params.append(self.expect_id().text)
            self.expect_op(":")
            body = self.parse_expr(min_col, 0)
            return ("lambda", params, body)
        if name == "DOMAIN":
            return ("domain", self.parse_expr(min_col, 15))
        if name == "SUBSET":
            return ("powerset", self.parse_expr(min_col, 15))
        if name == "UNION":
            return ("bigunion", self.parse_expr(min_col, 15))
        if name == "UNCHANGED":
            return ("unchanged", self.parse_expr(min_col, 15))
        if name == "ENABLED":
            return ("enabled", self.parse_expr(min_col, 15))
        if name == "TRUE":
            return self.postfix(("bool", True), min_col)
        if name == "FALSE":
            return self.postfix(("bool", False), min_col)
        if name.startswith("WF_") or name.startswith("SF_"):
            sub = ("id", name[3:])
            self.expect_op("(")
            act = self.parse_expr(0, 0)
            self.expect_op(")")
            return ("wf" if name.startswith("WF_") else "sf", sub, act)
        # plain identifier or operator call
        if self.at_op("(") and self.peek().col > min_col:
            self.next()
            args = [self.parse_expr(0, 0)]
            while self.at_op(","):
                self.next()
                args.append(self.parse_expr(0, 0))
            self.expect_op(")")
            return self.postfix(("call", name, args), min_col)
        return self.postfix(("id", name), min_col)

    def parse_definition_inline(self) -> Def:
        """A definition inside LET (no column-1 constraint)."""
        t0 = self.peek()
        name = self.expect_id().text
        params = []
        if self.at_op("("):
            self.next()
            while True:
                p = self.next()
                if p.kind == "ID":
                    params.append(p.text)
                elif p.kind == "OP" and p.text == "_":
                    params.append("_")
                else:
                    self.err(f"bad parameter {p}")
                if self.at_op(","):
                    self.next()
                    continue
                break
            self.expect_op(")")
        self.expect_op("==")
        body = self.parse_expr(t0.col, 0)
        t1 = self.toks[self.pos - 1]
        return Def(name=name, params=params, body=body, line0=t0.line,
                   col0=t0.col, line1=t1.line, col1=t1.col + len(t1.text) - 1)

    def parse_junction(self, min_col: int):
        t = self.peek()
        op = t.text
        col = t.col
        items = []
        while self.at_op(op) and self.peek().col == col and not self._at_boundary():
            self.next()
            items.append(self.parse_expr(col, 0))
        tag = "and" if op == "/\\" else "or"
        if len(items) == 1:
            return items[0] if tag == "and" else ("or", items)
        return (tag, items)

    def parse_bound_groups(self):
        """``x, y \\in S, m \\in T`` -> [([x, y], S), ([m], T)]"""
        groups = []
        while True:
            names = [self.expect_id().text]
            while self.at_op(","):
                # could be another name in this group or a new group; a new
                # group also starts with ID, so look for the \in that closes
                # this group: names continue while the token after the ID is
                # ',' or '\in'.
                if self.at_id(off=1) and (self.at_op(",", off=2) or self.at_op("\\in", off=2)):
                    self.next()
                    names.append(self.expect_id().text)
                else:
                    break
            self.expect_op("\\in")
            s = self.parse_expr(0, 0)
            groups.append((names, s))
            if self.at_op(","):
                self.next()
                continue
            break
        return groups

    def parse_set(self):
        self.expect_op("{")
        if self.at_op("}"):
            self.next()
            return ("setenum", [])
        # try {x \in S : p}
        if self.at_id() and self.at_op("\\in", off=1):
            save = self.pos
            var = self.expect_id().text
            self.expect_op("\\in")
            s = self.parse_expr(0, 0)
            if self.at_op(":"):
                self.next()
                p = self.parse_expr(0, 0)
                self.expect_op("}")
                return ("setfilter", var, s, p)
            self.pos = save  # it was an enumeration of a membership test
        e = self.parse_expr(0, 0)
        if self.at_op(":"):
            self.next()
            groups = self.parse_bound_groups()
            self.expect_op("}")
            return ("setmap", e, groups)
        items = [e]
        while self.at_op(","):
            self.next()
            items.append(self.parse_expr(0, 0))
        self.expect_op("}")
        return ("setenum", items)

    def parse_bracket(self):
        self.expect_op("[")
        # function constructor [x \in S |-> e] (possibly multiple groups)
        if self.at_id() and (self.at_op("\\in", off=1) or
                             (self.at_op(",", off=1) and self.at_id(off=2))):
            save = self.pos
            try:
                groups = self.parse_bound_groups()
                if self.at_op("|->"):
                    self.next()
                    body = self.parse_expr(0, 0)
                    self.expect_op("]")
                    return ("fnctor", groups, body)
            except ParseError:
                pass
            self.pos = save
        # record literal [f |-> e, ...]
        if self.at_id() and self.at_op("|->", off=1):
            fields = []
            while True:
                fname = self.expect_id().text
                self.expect_op("|->")
                fields.append((fname, self.parse_expr(0, 0)))
                if self.at_op(","):
                    self.next()
                    continue
                break
            self.expect_op("]")
            return ("record", fields)
        # record set [f : S, ...]
        if self.at_id() and self.at_op(":", off=1):
            fields = []
            while True:
                fname = self.expect_id().text
                self.expect_op(":")
                fields.append((fname, self.parse_expr(0, 0)))
                if self.at_op(","):
                    self.next()
                    continue
                break
            self.expect_op("]")
            return ("recordset", fields)
        e = self.parse_expr(0, 0)
        if self.at_id("EXCEPT"):
            self.next()
            specs = []
            while True:
                self.expect_op("!")
                path = []
                while True:
                    if self.at_op("["):
                        self.next()
                        idx = self.parse_expr(0, 0)
                        self.expect_op("]")
                        path.append(("idx", idx))
                    elif self.at_op("."):
                        self.next()
                        path.append(("fld", self.expect_id().text))
                    else:
                        break
                if not path:
                    self.err("empty EXCEPT path")
                self.expect_op("=")
                val = self.parse_expr(0, 0)
                specs.append((path, val))
                if self.at_op(","):
                    self.next()
                    continue
                break
            self.expect_op("]")
            return ("except", e, specs)
        if self.at_op("->"):
            self.next()
            rng = self.parse_expr(0, 0)
            self.expect_op("]")
            return ("fnset", e, rng)
        if self.at_op("]"):
            # [A]_vars action form
            self.next()
            sub = self._parse_subscript()
            return ("boxaction_inner", e, sub)
        self.err("cannot parse [ ... ] form")

    def postfix(self, e, min_col: int):
        while True:
            if self._at_boundary():
                return e
            t = self.peek()
            if t.kind != "OP" or t.col <= min_col:
                return e
            if t.text == "'":
                self.next()
                e = ("prime", e)
            elif t.text == "[":
                self.next()
                idx = self.parse_expr(0, 0)
                while self.at_op(","):
                    self.next()
                    idx2 = self.parse_expr(0, 0)
                    idx = ("tuple", [idx, idx2]) if idx[0] != "tuple" else ("tuple", idx[1] + [idx2])
                self.expect_op("]")
                e = ("apply", e, idx)
            elif t.text == "." and self.peek(1).kind == "ID":
                self.next()
                e = ("dot", e, self.expect_id().text)
            else:
                return e


def parse_module_text(src: str, filename: str = "<string>") -> Module:
    return Parser(src, filename).parse_module()


def parse_module_file(path: str) -> Module:
    with open(path) as f:
        return parse_module_text(f.read(), path)


def parse_expr_text(src: str):
    p = Parser(src)
    return p.parse_expr(0, 0)
