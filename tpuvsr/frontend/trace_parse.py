"""TLC counterexample-trace parser and replayer.

Reads the TLC trace artifact format — a ``<< ... >>`` sequence of state
records, each carrying a ``_TEAction |-> [position, name, location]``
header followed by the full variable assignment
(/root/reference/state_transfer_violation_trace.txt:3-26) — into
interpreter states, so recorded TLC counterexamples become golden
regression oracles: `replay_trace` re-executes the action sequence
through this framework's successor enumeration and fails loudly if any
recorded transition is not reproducible.

A trace may have been recorded against an older revision of the spec
(the reference's state-transfer trace predates VSR.tla's recovery
variables), so states are compared only on the variables the trace
actually binds; variables the trace omits are carried from the
replayed state.
"""

from __future__ import annotations

import re

from ..core.values import ModelValue, TLAError
from ..engine.trace import TraceEntry
from ..interp.evalr import EMPTY_ENV, EvalCtx
from .parser import parse_expr_text

_NIL_LOCATION = "Unknown location"


def _model_value_env(cfg):
    """Members of cfg-bound model-value *sets* (e.g. v1 in
    ``Values = {v1, v2, v3}``) are not constants themselves; bind them
    by name so trace expressions mentioning them evaluate."""
    extra = {}
    for val in cfg.constants.values():
        if isinstance(val, frozenset):
            for m in val:
                if isinstance(m, ModelValue):
                    extra[m.name] = m
    return EMPTY_ENV.extend(extra)


def parse_trace_text(text: str, spec) -> list:
    """Parse a TLC trace dump into ``TraceEntry`` rows whose states are
    interpreter value dicts (only the variables the trace binds)."""
    body = text.strip()
    if not body.startswith("<<") or not body.rstrip().endswith(">>"):
        raise TLAError("not a TLC trace dump (expected << ... >>)")
    body = body[2:].rstrip()[:-2]
    parts = re.split(r"\],\s*\n\[", body)
    env = _model_value_env(spec.cfg)
    ctx = EvalCtx({})
    out = []
    for p in parts:
        p = p.strip()
        if not p.startswith("["):
            p = "[" + p
        if not p.endswith("]"):
            p = p + "]"
        rec = spec.ev.eval(parse_expr_text(p), env, ctx)
        te = rec.apply("_TEAction")
        name = te.apply("name")
        loc = te.apply("location")
        out.append(TraceEntry(
            position=te.apply("position"),
            action_name=None if name == "Initial predicate" else name,
            location=None if loc == _NIL_LOCATION else loc,
            state={k: v for k, v in rec.items if k != "_TEAction"}))
    return out


def parse_trace_file(path: str, spec) -> list:
    with open(path) as f:
        return parse_trace_text(f.read(), spec)


def _matches(st: dict, recorded: dict, position) -> bool:
    """State agreement on every trace-bound variable.  A trace variable
    the spec doesn't declare is an error, not a vacuous match — a trace
    from a mismatched spec must not 'replay' by comparing nothing."""
    for k, v in recorded.items():
        if k not in st:
            raise TLAError(
                f"trace position {position}: trace binds variable {k!r} "
                f"unknown to the spec")
        if st[k] != v:
            return False
    return True


def replay_trace(spec, entries) -> list:
    """Re-execute a parsed trace through the interpreter.

    For each recorded step, search the current state's successors for
    one produced by the recorded action whose state agrees with the
    recorded one on every trace-bound variable.  Since the trace may
    omit variables (older-spec recordings), several successors can
    agree on the recorded projection while diverging on omitted ones —
    the search backtracks across those choices rather than committing
    greedily.  Returns the list of full replayed interpreter states
    (including variables the trace omits).  Raises TLAError when no
    choice sequence matches — i.e. the framework's semantics diverge
    from TLC's on this trace.
    """
    inits = [st for st in spec.init_states()
             if _matches(st, entries[0].state, entries[0].position)]
    if not inits:
        raise TLAError("trace initial state is not an Init state")
    deepest = [entries[0].position]

    def extend(cur, i):
        if i == len(entries):
            return [cur]
        e = entries[i]
        for action, succ in spec.successors(cur):
            if action.name == e.action_name and \
                    _matches(succ, e.state, e.position):
                deepest[0] = max(deepest[0], e.position)
                rest = extend(succ, i + 1)
                if rest is not None:
                    return [cur] + rest
        return None

    for st in inits:
        out = extend(st, 1)
        if out is not None:
            return out
    raise TLAError(
        f"trace does not replay: no successor via "
        f"{entries[deepest[0]].action_name if deepest[0] < len(entries) else '?'} "
        f"matches the recorded state at position {deepest[0] + 1}")
