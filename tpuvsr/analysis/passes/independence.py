"""Pass 7 — static action independence (ISSUE 16 tentpole).

Symmetry (pass 4) and bounds (pass 6) attack value relabeling and
domain blowup; the remaining blowup axis is INTERLEAVING — actions
that touch disjoint state commute, and BFS explores every ordering of
them anyway.  This pass computes the conservative static independence
relation the engines' ample-set filter (``engine/por.py``) consumes:

* **read/write access sets** — per action, the state variables its
  guard and updates read and the variables its updates prime, at
  plane/column granularity: a write through ``v' = [v EXCEPT ![c] = e]``
  with a constant-foldable index records the single column ``c``
  instead of the whole plane (the EXCEPT copy of the other columns is
  the identity and commutes with any column-disjoint write, so it is
  deliberately NOT a read); an indexed read ``v[c]`` with a foldable
  index records one column.  Anything else widens to the full plane.
* **the independence matrix** — actions ``a``, ``b`` are independent
  only when ``W(a) ∩ (R(b) ∪ W(b)) = ∅`` AND
  ``W(b) ∩ (R(a) ∪ W(a)) = ∅`` at that granularity.  Disjoint frames
  in both directions mean the two updates commute as state
  transformers AND neither can change the other's guard — exactly the
  (strong) independence the ample-set theorems need, including
  enabledness preservation (C1): no action can toggle an independent
  action's guard, so an independent action's enabled LANE SET is
  constant along paths that do not fire it.
* **invariant visibility** — an action is *invisible* when its write
  set is disjoint from every cfg invariant's read set (C2: taking it
  cannot change any invariant's truth value).
* **monotone progress witnesses** — per action, a variable ``x`` whose
  only update anywhere in the action is a top-level conjunct
  ``x' = x + c`` with constant ``c >= 1``, and whose reachable
  interval (bounds pass) is finite.  The sharded engine's fully-static
  cycle proviso (engine/por.py) needs these: summed over the eligible
  actions, the witnesses form a bounded measure that strictly
  increases on every ample transition, so no cycle can consist of
  ample shortcuts only.

Refusal discipline (mirrors the bounds pass): any expression shape the
walker cannot attribute — a prime applied to a compound expression, an
unresolvable UNCHANGED frame — POISONS that action to
dependent-with-everything (its matrix row and column go False and it
is never an ample candidate), with the reason journaled.  Poisoning is
per-action, not whole-spec: one exotic action costs its own
reduction, not the corpus's.

Bounds facts prune first: statically dead actions (pass 6) are
excluded from the matrix entirely — the engines prune them from the
kernel lane tables, so the facts and the kernel agree on the action
universe; an engine running ``-bounds off`` keeps dead actions in the
kernel, which then miss from the facts and are treated as
dependent-with-all (sound).

Soundness boundary: the analysis reads the SPEC's guarded commands;
the engines run hand kernels.  The drift pass (pass 5) is the bridge
— it proves the kernel's per-action semantics match the lowered spec,
which is what licenses applying spec-level independence to kernel
lanes.

The facts are cached per spec object like bounds, surfaced through
``LintReport.extras["independence"]`` (``-lint -json``), and carry a
sha digest recorded in checkpoint manifests (a resume under a flipped
``-por`` or changed facts is a policy error, mirroring pack/canon/
bounds).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field

from ..report import SEV_INFO, SEV_WARN
from .bounds import _decompose, analyze as _bounds_analyze
from .vacuity import _fold, _is_int

PASS = "independence"

#: column sentinel: the whole plane (any column)
ALL_COLS = None

_NOFOLD = object()


class _Poison(Exception):
    """This action's access sets cannot be attributed statically; it
    becomes dependent-with-everything (reason journaled)."""


# ----------------------------------------------------------------------
@dataclass
class IndependenceFacts:
    """The facts one bound spec yields — what engine/por.py consumes."""
    module: str
    action_names: list = field(default_factory=list)   # live (post-prune)
    reads: dict = field(default_factory=dict)    # name -> sorted access strs
    writes: dict = field(default_factory=dict)   # name -> sorted access strs
    poisoned: dict = field(default_factory=dict)  # name -> reason
    visible: dict = field(default_factory=dict)  # name -> bool (C2 fails)
    monotone: dict = field(default_factory=dict)  # name -> witness var|None
    matrix: list = field(default_factory=list)   # n x n bool, diag True
    pruned_dead: list = field(default_factory=list)  # bounds-dead, excluded
    inv_refused: str = None   # invariant read sets unresolvable -> all visible

    @property
    def independent_pairs(self):
        n = len(self.action_names)
        return sum(1 for i in range(n) for j in range(i + 1, n)
                   if self.matrix[i][j])

    def to_dict(self):
        return {"module": self.module,
                "actions": list(self.action_names),
                "reads": {k: list(v) for k, v in sorted(self.reads.items())},
                "writes": {k: list(v)
                           for k, v in sorted(self.writes.items())},
                "poisoned": dict(sorted(self.poisoned.items())),
                "visible": dict(sorted(self.visible.items())),
                "monotone": dict(sorted(self.monotone.items())),
                "matrix": [[bool(x) for x in row] for row in self.matrix],
                "independent_pairs": self.independent_pairs,
                "digest": self.digest}

    @property
    def digest(self):
        """Stable identity of the consumed facts — recorded in
        checkpoint manifests so a resume under a flipped ``-por`` (or
        changed facts) is a policy error, mirroring bounds/pack/canon."""
        canon = {"module": self.module,
                 "actions": list(self.action_names),
                 "matrix": [[bool(x) for x in row] for row in self.matrix],
                 "poisoned": sorted(self.poisoned),
                 "visible": sorted(k for k, v in self.visible.items() if v),
                 "monotone": sorted((k, v) for k, v in self.monotone.items()
                                    if v)}
        return hashlib.sha256(
            json.dumps(canon, sort_keys=True).encode()).hexdigest()[:12]

    def journal_doc(self):
        """The compact ``independence`` summary inside the run_start
        ``por`` object."""
        return {"independent_pairs": self.independent_pairs,
                "poisoned": sorted(self.poisoned),
                "digest": self.digest}


# ----------------------------------------------------------------------
# access-set machinery: dict var -> ALL_COLS | frozenset(columns)
# ----------------------------------------------------------------------
def _add(acc, var, cols):
    cur = acc.get(var, frozenset())
    if cols is ALL_COLS or cur is ALL_COLS:
        acc[var] = ALL_COLS
    else:
        acc[var] = cur | cols


def _cols_overlap(a, b):
    if a is ALL_COLS or b is ALL_COLS:
        return True
    return bool(a & b)


def _sets_overlap(wa, *others):
    """W(a) against a union of access sets: any shared plane with
    overlapping columns."""
    for var, cols in wa.items():
        for other in others:
            oc = other.get(var)
            if var in other and _cols_overlap(cols, oc):
                return True
    return False


def _const(e, spec):
    """Fold an index expression to a hashable constant, or _NOFOLD."""
    try:
        v = _fold(e, spec, set())
    except Exception:  # noqa: BLE001 — fold helpers raise on exotic AST
        return _NOFOLD
    if _is_int(v) or isinstance(v, (str, bool)):
        return v
    # ModelValues are interned and hashable; anything else is opaque
    from ...core.values import ModelValue
    if isinstance(v, ModelValue):
        return v
    return _NOFOLD


def _col_str(c):
    return getattr(c, "name", None) or str(c)


def _access_strs(acc):
    out = []
    for var in sorted(acc):
        cols = acc[var]
        if cols is ALL_COLS:
            out.append(var)
        else:
            out.append(f"{var}[{','.join(sorted(_col_str(c) for c in cols))}]")
    return out


def _iter_children(e):
    for x in e[1:]:
        if isinstance(x, tuple):
            yield x
        elif isinstance(x, list):
            for y in x:
                if isinstance(y, tuple):
                    yield y


def _is_prime_of_var(e, varnames):
    return (isinstance(e, tuple) and e and e[0] == "prime"
            and isinstance(e[1], tuple) and e[1]
            and e[1][0] == "id" and e[1][1] in varnames)


def _scan_expr(e, spec, varnames, reads, writes, seen):
    """One walker for guards, updates and invariants: collect column-
    refined reads and writes, inlining operator definitions, raising
    :class:`_Poison` on unattributable shapes."""
    if not isinstance(e, tuple) or not e or not isinstance(e[0], str):
        return
    tag = e[0]
    if tag == "prime":
        inner = e[1]
        if _is_prime_of_var(e, varnames):
            _add(writes, inner[1], ALL_COLS)
            return
        raise _Poison(
            f"prime applied to a "
            f"{inner[0] if isinstance(inner, tuple) and inner else inner!r} "
            f"expression — which planes it constrains is not static")
    if tag == "unchanged":
        # x' = x is the identity on every plane: no read, no write
        # (an unresolvable frame still frames SOMETHING unknown)
        try:
            spec.ev.collect_state_vars(e[1], _empty_env())
        except Exception:  # noqa: BLE001
            raise _Poison(
                "UNCHANGED frame does not resolve to a tuple of state "
                "variables") from None
        return
    if tag == "binop" and e[1] == "eq" and _is_prime_of_var(e[2], varnames):
        var = e[2][1][1]
        rhs = e[3]
        if isinstance(rhs, tuple) and rhs and rhs[0] == "except" \
                and isinstance(rhs[1], tuple) and rhs[1] \
                and rhs[1][0] == "id" and rhs[1][1] == var:
            # v' = [v EXCEPT ![c1] = e1, ...]: the untouched-column
            # copy is the identity (commutes with any column-disjoint
            # write), so only the written columns, the index
            # expressions and the replacement values count
            cols, exact = set(), True
            for path, val in rhs[2]:
                if len(path) == 1 and path[0][0] == "idx":
                    c = _const(path[0][1], spec)
                    if c is _NOFOLD:
                        exact = False
                    else:
                        cols.add(c)
                else:
                    exact = False
                for seg in path:
                    if len(seg) > 1 and isinstance(seg[1], tuple):
                        _scan_expr(seg[1], spec, varnames, reads, writes,
                                   seen)
                _scan_expr(val, spec, varnames, reads, writes, seen)
            _add(writes, var, frozenset(cols) if exact else ALL_COLS)
            return
        _add(writes, var, ALL_COLS)
        _scan_expr(rhs, spec, varnames, reads, writes, seen)
        return
    if tag == "apply" and isinstance(e[1], tuple) and e[1] \
            and e[1][0] == "id" and e[1][1] in varnames:
        c = _const(e[2], spec)
        _add(reads, e[1][1],
             ALL_COLS if c is _NOFOLD else frozenset([c]))
        _scan_expr(e[2], spec, varnames, reads, writes, seen)
        return
    if tag == "except":
        # EXCEPT in read position (not the v' = [v EXCEPT ...] shape):
        # conservative — base fully read, paths and values walked
        _scan_expr(e[1], spec, varnames, reads, writes, seen)
        for path, val in e[2]:
            for seg in path:
                if len(seg) > 1 and isinstance(seg[1], tuple):
                    _scan_expr(seg[1], spec, varnames, reads, writes, seen)
            _scan_expr(val, spec, varnames, reads, writes, seen)
        return
    if tag == "id":
        name = e[1]
        if name in varnames:
            _add(reads, name, ALL_COLS)
            return
        d = spec.module.defs.get(name)
        if d is not None and name not in seen:
            _scan_expr(d.body, spec, varnames, reads, writes,
                       seen | {name})
        return
    if tag == "call":
        d = spec.module.defs.get(e[1])
        if d is not None and e[1] not in seen:
            _scan_expr(d.body, spec, varnames, reads, writes,
                       seen | {e[1]})
    for c in _iter_children(e):
        _scan_expr(c, spec, varnames, reads, writes, seen)


def _empty_env():
    from ...interp.evalr import EMPTY_ENV
    return EMPTY_ENV


# ----------------------------------------------------------------------
def _count_primes_of(e, spec, var, seen):
    """Occurrences of ``var'`` anywhere in the action (through defs)."""
    if not isinstance(e, tuple) or not e or not isinstance(e[0], str):
        return 0
    if e[0] == "prime" and isinstance(e[1], tuple) and e[1] \
            and e[1][0] == "id" and e[1][1] == var:
        return 1
    n = 0
    if e[0] in ("call", "id"):
        d = spec.module.defs.get(e[1])
        if d is not None and e[1] not in seen:
            n += _count_primes_of(d.body, spec, var, seen | {e[1]})
    for c in _iter_children(e):
        n += _count_primes_of(c, spec, var, seen)
    return n


def _monotone_witness(action, spec, varnames, bfacts):
    """A strict-progress witness variable, or None.

    Accepted only when the action has exactly one update of ``x``
    anywhere, it is a TOP-LEVEL conjunct ``x' = x + c`` (so it holds
    on every firing), ``c`` folds to an int >= 1, and the bounds pass
    proved a finite reachable interval for ``x``."""
    if bfacts is None or not bfacts.tightened:
        return None
    _binders, _guards, updates = _decompose(action.expr, spec)
    cands = {}
    for u in updates:
        if not (isinstance(u, tuple) and u and u[0] == "binop"
                and u[1] == "eq" and _is_prime_of_var(u[2], varnames)):
            continue
        x = u[2][1][1]
        rhs = u[3]
        if not (isinstance(rhs, tuple) and rhs and rhs[0] == "binop"
                and rhs[1] == "plus"):
            continue
        a_, b_ = rhs[2], rhs[3]
        if isinstance(a_, tuple) and a_ and a_[0] == "id" and a_[1] == x:
            c = _const(b_, spec)
        elif isinstance(b_, tuple) and b_ and b_[0] == "id" and b_[1] == x:
            c = _const(a_, spec)
        else:
            continue
        if c is not _NOFOLD and _is_int(c) and c >= 1:
            cands[x] = cands.get(x, 0) + 1
    for x in sorted(cands):
        if cands[x] != 1:
            continue
        if x not in bfacts.intervals:
            continue
        if _count_primes_of(action.expr, spec, x, set()) != 1:
            continue
        return x
    return None


def _invariant_reads(spec, varnames):
    """(reads access set, refusal reason|None) over every cfg
    invariant, transitively through definitions.  Unresolvable shapes
    widen to every plane (all actions become visible)."""
    reads = {}
    for name in spec.cfg.invariants:
        d = spec.module.defs.get(name)
        if d is None:
            return ({v: ALL_COLS for v in varnames},
                    f"invariant {name} is not defined in the module")
        scratch_w = {}
        try:
            _scan_expr(d.body, spec, varnames, reads, scratch_w,
                       frozenset([name]))
        except _Poison as p:
            return ({v: ALL_COLS for v in varnames},
                    f"invariant {name}: {p}")
        if scratch_w:
            return ({v: ALL_COLS for v in varnames},
                    f"invariant {name} primes state")
    return reads, None


# ----------------------------------------------------------------------
def analyze(spec) -> IndependenceFacts:
    """Compute (and cache per spec object) the independence facts."""
    cached = getattr(spec, "_indep_facts", None)
    if cached is not None:
        return cached
    facts = _analyze(spec)
    spec._indep_facts = facts
    return facts


def _analyze(spec) -> IndependenceFacts:
    varnames = set(spec.module.variables)
    facts = IndependenceFacts(module=spec.module.name)
    bfacts = _bounds_analyze(spec)

    # dead actions never fire: exclude them from the matrix (the
    # engines prune them from the kernel under the same facts)
    dead = set(bfacts.dead_actions)
    live = [a for a in spec.actions if a.name not in dead]
    facts.pruned_dead = sorted(dead)
    facts.action_names = [a.name for a in live]

    inv_reads, inv_refused = _invariant_reads(spec, varnames)
    facts.inv_refused = inv_refused

    access = {}
    for action in live:
        reads, writes = {}, {}
        try:
            _binders, guards, updates = _decompose(action.expr, spec)
            for g in guards:
                _scan_expr(g, spec, varnames, reads, writes, frozenset())
            for u in updates:
                _scan_expr(u, spec, varnames, reads, writes, frozenset())
        except _Poison as p:
            facts.poisoned[action.name] = str(p)
            reads = {v: ALL_COLS for v in varnames}
            writes = {v: ALL_COLS for v in varnames}
        access[action.name] = (reads, writes)
        facts.reads[action.name] = _access_strs(reads)
        facts.writes[action.name] = _access_strs(writes)
        facts.visible[action.name] = _sets_overlap(writes, inv_reads)
        facts.monotone[action.name] = (
            None if action.name in facts.poisoned
            else _monotone_witness(action, spec, varnames, bfacts))

    n = len(live)
    mat = [[False] * n for _ in range(n)]
    for i, ai in enumerate(live):
        mat[i][i] = True
        ri, wi = access[ai.name]
        for j in range(i + 1, n):
            aj = live[j]
            if ai.name in facts.poisoned or aj.name in facts.poisoned:
                continue
            rj, wj = access[aj.name]
            indep = not _sets_overlap(wi, rj, wj) and \
                not _sets_overlap(wj, ri, wi)
            mat[i][j] = mat[j][i] = indep
    facts.matrix = mat
    return facts


# ----------------------------------------------------------------------
# the lint pass
# ----------------------------------------------------------------------
def run(spec, report):
    facts = analyze(spec)
    report.extras["independence"] = facts.to_dict()
    for name, why in sorted(facts.poisoned.items()):
        report.add(PASS, SEV_WARN, name,
                   f"access sets unattributable ({why}); treated as "
                   f"dependent with every action (never an ample "
                   f"candidate)")
    if facts.inv_refused:
        report.add(PASS, SEV_WARN, spec.module.name,
                   f"invariant read sets unresolvable "
                   f"({facts.inv_refused}); every action is treated "
                   f"as visible — POR stands down")
    n = len(facts.action_names)
    report.add(PASS, SEV_INFO, spec.module.name,
               f"{facts.independent_pairs} independent pair(s) over "
               f"{n} live action(s) "
               f"({len(facts.poisoned)} poisoned, "
               f"{sum(1 for v in facts.visible.values() if not v)} "
               f"invariant-invisible, "
               f"{sum(1 for v in facts.monotone.values() if v)} with "
               f"monotone witnesses)")
