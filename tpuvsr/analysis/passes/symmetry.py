"""Pass 4 — symmetry soundness.

TLC's SYMMETRY optimization is only sound when every declared
permutation is a structural automorphism of the state graph.  Two ways
the corpus (or a grown config) can break that:

1. The SYMMETRY definition evaluates to maps that are not bijections
   of the symmetric model-value universe (e.g. a constant map
   ``[v \\in Values |-> v1]``): canonicalization then merges
   non-isomorphic states and the checker silently under-explores.
   Checked semantically on the evaluated ``spec.symmetry_perms``.

2. The spec uses a symmetric model value asymmetrically: a variable
   bound over the symmetric set appearing under an order or arithmetic
   operator (``<``, ``..``, ``+`` — TLC would error at evaluation
   time, long into a run), or a cfg constant pinning a NAME to one
   symmetric value that the spec then references (the classic
   TLC "symmetric model value used in the spec" unsoundness).
   Checked by a taint walk over every definition reachable from
   Init/Next/invariants/VIEW: binders whose domain is a symmetric-set
   constant taint their variable; taints propagate through operator
   calls by position.

``CHOOSE`` over a symmetric domain is reported as info: both TLC and
this port resolve it deterministically over a canonical order, which
is sound for state exploration but makes the chosen element
orbit-dependent — worth knowing when debugging a trace.

Device-soundness (ISSUE 11): the engines now canonicalize states to
orbit representatives ON DEVICE (engine/canon.py), which adds two
machine-checkable preconditions this pass enforces:

3. The evaluated permutation set plus identity must be CLOSED under
   composition — min-over-enumerated-images is only orbit-invariant
   for a group (``Permutations(S)`` always is; a hand-written subset
   may not be).

4. Each permutation must act on the encoded layout as a bijection of
   value ids that fixes the padding id 0, and only through planes the
   kernel's orbit table names.  The pass EMITS that table (via
   ``canon.orbit_planes`` — the same function the canonicalization
   kernel consumes), so lint and kernel cannot disagree about which
   planes a permutation touches.
"""

from __future__ import annotations

from ...core.values import ModelValue
from ..report import SEV_ERROR, SEV_INFO, SEV_WARN

PASS = "symmetry"

_ORDERED_OPS = ("lt", "le", "gt", "ge", "plus", "minus", "times",
                "div", "mod", "range")


def run(spec, report):
    perms = spec.symmetry_perms
    if not perms:
        report.add(PASS, SEV_INFO, spec.module.name,
                   "no SYMMETRY declared; nothing to check")
        return

    moved = set()
    for p in perms:
        moved.update(p.keys())
        moved.update(p.values())

    # ground universe: the cfg constant set(s) the moved values live in
    universe = set()
    sym_set_consts = []
    for cname, cval in spec.ev.constants.items():
        if isinstance(cval, frozenset) and cval & moved:
            universe |= {v for v in cval if isinstance(v, ModelValue)}
            sym_set_consts.append(cname)
    if not universe:
        universe = set(moved)

    for i, p in enumerate(perms):
        stray = (set(p.keys()) | set(p.values())) - universe
        if stray:
            report.add(PASS, SEV_ERROR, f"perm #{i}",
                       f"permutation moves values outside the "
                       f"symmetric set(s) "
                       f"{sorted(c for c in sym_set_consts)}: "
                       f"{sorted(v.name for v in stray)}")
            continue
        image = {p.get(u, u) for u in universe}
        if len(image) != len(universe):
            report.add(PASS, SEV_ERROR, f"perm #{i}",
                       f"not a bijection of the symmetric set: "
                       f"{{{', '.join(sorted(u.name for u in universe))}}} "
                       f"maps onto only {len(image)} of "
                       f"{len(universe)} values — canonicalization "
                       f"would merge non-isomorphic states")

    # device-soundness: group closure + encoded orbit table (ISSUE 11)
    _device_orbit_check(spec, perms, report)

    # cfg constants that pin a NAME to one symmetric value
    pinned = {cname for cname, cval in spec.ev.constants.items()
              if isinstance(cval, ModelValue) and cval in universe
              and cname not in spec.module.variables}

    # taint walk over reachable definitions
    roots = [a.expr for a in spec.actions]
    roots += [d.body for _n, d in spec.invariants]
    init_def = spec.module.defs.get(spec.init_name)
    if init_def is not None:
        roots.append(init_def.body)
    if spec.view_def is not None:
        roots.append(spec.view_def.body)
    walker = _Taint(spec, frozenset(sym_set_consts), pinned, report)
    for root in roots:
        walker.walk(root, frozenset())


def _device_orbit_check(spec, perms, report):
    """Checks 3 and 4 (module docstring): closure of the evaluated
    group, and the kernel/codec orbit table the device
    canonicalization pass consumes."""
    from ...engine.canon import group_closed, orbit_planes
    if not group_closed(perms):
        report.add(PASS, SEV_ERROR, "group",
                   "SYMMETRY permutation set (plus identity) is not "
                   "closed under composition: the orbit-least image "
                   "is then orbit-DEPENDENT and device "
                   "canonicalization (and the host min-image "
                   "fingerprint) would merge or split orbits "
                   "inconsistently.  TLC's Permutations(S) is always "
                   "closed; hand-written SYMMETRY sets must be too")
    try:
        from ...models.registry import _resolve, has_device_model
        from ...models.registry import value_perm_table
    except ImportError:
        return
    if not has_device_model(spec):
        report.add(PASS, SEV_INFO, spec.module.name,
                   "no compiled device kernel for this module; orbit "
                   "table check skipped (the interpreter's "
                   "view-value canonicalization needs no table)")
        return
    codec_cls, kern_cls = _resolve(spec.module.name)
    codec = codec_cls(spec.ev.constants)
    planes = orbit_planes(kern_cls)
    if planes is None:
        report.add(PASS, SEV_ERROR, kern_cls.__name__,
                   "kernel declares no orbit plane table (SYM_PLANES "
                   "or PERM_REP_KEYS/PERM_MSG_KEYS): device "
                   "canonicalization cannot know which planes a "
                   "permutation touches; -symmetry on would fail at "
                   "engine build")
        return
    zero = codec.zero_state()
    missing = sorted(k for k in planes if k not in zero)
    if missing:
        report.add(PASS, SEV_ERROR, kern_cls.__name__,
                   f"orbit table names planes {missing} the codec "
                   f"layout does not declare — lint/kernel drift")
    table = value_perm_table(spec, codec)
    V = int(codec.shape.V)
    for i, row in enumerate(table):
        bad = (int(row[0]) != 0
               or sorted(int(x) for x in row) != list(range(V + 1)))
        if bad:
            report.add(PASS, SEV_ERROR, f"perm #{i}",
                       "permutation does not act as a bijection of "
                       "the encoded value ids fixing the padding id "
                       "0: canonicalizing through this row would "
                       "corrupt non-symmetric fields")
    report.add(PASS, SEV_INFO, kern_cls.__name__,
               f"device orbit table: group order {len(table)} "
               f"(identity included), planes "
               f"{sorted(planes)} — emitted by canon.orbit_planes, "
               f"the same source the canonicalization kernel reads")


class _Taint:
    def __init__(self, spec, sym_consts, pinned, report):
        self.spec = spec
        self.sym_consts = sym_consts       # names of symmetric SETS
        self.pinned = pinned               # names pinned to one value
        self.report = report
        self._reported = set()
        self._def_memo = set()             # (defname, taint signature)

    # ------------------------------------------------------------------
    def _emit(self, sev, subject, msg):
        key = (subject, msg)
        if key not in self._reported:
            self._reported.add(key)
            self.report.add(PASS, sev, subject, msg)

    def _is_sym_domain(self, dom):
        return isinstance(dom, tuple) and dom and dom[0] == "id" \
            and dom[1] in self.sym_consts

    def walk(self, e, tainted):
        """tainted: frozenset of bound-variable names ranging over a
        symmetric set in the current scope."""
        if not isinstance(e, tuple) or not e:
            return
        tag = e[0]
        if tag == "id":
            if e[1] in self.pinned:
                self._emit(
                    SEV_ERROR, e[1],
                    f"constant {e[1]!r} pins symmetric model value "
                    f"{self.spec.ev.constants[e[1]]!r} and is "
                    f"referenced by the spec — symmetry reduction is "
                    f"unsound (TLC's symmetric-value-in-spec rule)")
            return
        if tag == "binop" and e[1] in _ORDERED_OPS:
            for side in (e[2], e[3]):
                if isinstance(side, tuple) and side \
                        and side[0] == "id" and side[1] in tainted:
                    self._emit(
                        SEV_ERROR, side[1],
                        f"symmetric-set variable {side[1]!r} used "
                        f"under order/arithmetic operator "
                        f"{e[1]!r} — permutations are not "
                        f"automorphisms of an ordered use")
        if tag == "setmap":                # ('setmap', elem, groups)
            new = set(tainted)
            for names, dom in e[2]:
                self.walk(dom, tainted)
                if self._is_sym_domain(dom):
                    new.update(names)
            self.walk(e[1], frozenset(new))
            return
        if tag in ("exists", "forall", "fnctor"):
            groups, body = (e[1], e[2])
            new = set(tainted)
            for names, dom in groups:
                self.walk(dom, tainted)
                if self._is_sym_domain(dom):
                    new.update(names)
            self.walk(body, frozenset(new))
            return
        if tag == "setfilter":
            var, dom, pred = e[1], e[2], e[3]
            self.walk(dom, tainted)
            new = set(tainted)
            if self._is_sym_domain(dom):
                new.add(var)
            self.walk(pred, frozenset(new))
            return
        if tag == "choose":
            var, dom, body = e[1], e[2], e[3]
            self.walk(dom, tainted)
            new = set(tainted)
            if self._is_sym_domain(dom):
                new.add(var)
                self._emit(
                    SEV_INFO, var,
                    "CHOOSE over a symmetric set resolves "
                    "deterministically over the canonical value order "
                    "(sound for exploration; orbit-dependent in "
                    "traces)")
            self.walk(body, frozenset(new))
            return
        if tag == "call":
            name, args = e[1], e[2]
            for a in args:
                self.walk(a, tainted)
            d = self.spec.module.defs.get(name)
            if d is not None and len(d.params) == len(args):
                arg_taint = frozenset(
                    p for p, a in zip(d.params, args)
                    if isinstance(a, tuple) and a and a[0] == "id"
                    and a[1] in tainted)
                key = (name, arg_taint)
                if key not in self._def_memo:
                    self._def_memo.add(key)
                    self.walk(d.body, arg_taint)
            return
        for x in e[1:]:
            if isinstance(x, tuple):
                self.walk(x, tainted)
            elif isinstance(x, list):
                for y in x:
                    if isinstance(y, tuple):
                        self.walk(y, tainted)
                    elif isinstance(y, (tuple, list)):
                        for z in y:
                            if isinstance(z, tuple):
                                self.walk(z, tainted)
