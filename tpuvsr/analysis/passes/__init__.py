"""Pass registry for the speclint analyzer.

Each pass module exposes ``PASS`` (its name) and ``run(spec, report)``.
``PASS_ORDER`` is the canonical execution order: cheap pure-AST passes
first, the kernel cross-check (which instantiates a codec/kernel)
last.  ``PREFLIGHT_PASSES`` is the subset the engines gate dispatch on
— spec-level only, so the pre-flight stays well under the 5 s budget
and needs no device model.
"""

from __future__ import annotations

from . import drift, frames, symmetry, vacuity, widths

PASSES = {m.PASS: m.run for m in (frames, widths, vacuity, symmetry,
                                  drift)}
PASS_ORDER = ("frames", "widths", "vacuity", "symmetry", "drift")
PREFLIGHT_PASSES = ("frames", "widths", "vacuity", "symmetry")
