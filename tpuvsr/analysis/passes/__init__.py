"""Pass registry for the speclint analyzer.

Each pass module exposes ``PASS`` (its name) and ``run(spec, report)``.
``PASS_ORDER`` is the canonical execution order: cheap pure-AST passes
first, the kernel cross-check (which instantiates a codec/kernel)
last.  ``PREFLIGHT_PASSES`` is the set the engines gate dispatch on —
since the kernel key tables became class attributes the drift
cross-check is cheap to construct (no jit, no dispatch), so the full
five-pass set runs at every ``run()`` entry (ROADMAP open item, PR 2);
``TPUVSR_LINT=off`` / ``-lint=off`` remains the bypass.
"""

from __future__ import annotations

from . import bounds, drift, frames, independence, symmetry, vacuity, widths

PASSES = {m.PASS: m.run for m in (frames, widths, vacuity, symmetry,
                                  drift, bounds, independence)}
PASS_ORDER = ("frames", "widths", "vacuity", "symmetry", "drift",
              "bounds", "independence")
PREFLIGHT_PASSES = PASS_ORDER
