"""Pass 1 — frame completeness.

The reference corpus's only frame discipline is TLC failing at runtime
with "successor state not completely specified", hours into a run.
This pass proves the same property statically, per action:

* every declared state variable is PRIMED or in the UNCHANGED frame on
  every execution path through the action (ERROR when a variable is
  constrained nowhere at all — the interpreter's ActionEnumerator
  raises exactly then; WARN when it is primed on some paths but not
  provably on all, since path-insensitive analysis over-approximates);
* no double prime (``x''`` — always a typo);
* priming a non-variable identifier is flagged (a primed operator is
  legal TLA+ but outside the corpus subset the lowerer accepts);
* a variable both primed and UNCHANGED across sibling conjuncts of one
  action is flagged (legal TLA+ — it degenerates to an equality guard
  — but in this corpus it is always an editing mistake);
* guard/update classification soundness: a disjunction whose branches
  disagree about priming (some branches update, some are pure guards)
  is flagged, because the lowerer compiles disjunctions of updates
  branch-exclusively (lower/compile.py docstring).

The assignment analysis mirrors interp/actions.ActionEnumerator's
semantics: ``x' = e`` binds, UNCHANGED binds the flattened tuple,
conjunction is sequential, disjunction/IF/CASE fork paths, operator
calls inline when they (transitively) touch primes.
"""

from __future__ import annotations

from ...interp.evalr import EMPTY_ENV
from ..report import SEV_ERROR, SEV_INFO, SEV_WARN

PASS = "frames"


def run(spec, report):
    varnames = set(spec.module.variables)
    if not varnames:
        report.add(PASS, SEV_INFO, spec.module.name,
                   "module declares no VARIABLES; nothing to frame")
        return
    for action in spec.actions:
        _check_action(spec, action, varnames, report)


# ----------------------------------------------------------------------
def _check_action(spec, action, varnames, report):
    ev = spec.ev
    defs = spec.module.defs
    name = action.name

    # liberal over-approximation: every variable primed anywhere in the
    # action (including through called operators and all branches)
    primed_any = set()
    unchanged_any = set()
    notes = {"double_prime": [], "nonvar_prime": set(),
             "bad_frame": set()}
    _scan(action.expr, ev, defs, varnames, primed_any, unchanged_any,
          notes, set(), under_prime=False)

    for sub in notes["double_prime"]:
        report.add(PASS, SEV_ERROR, name,
                   f"double prime on {sub!r} (x'' is never meaningful)")
    for sub in sorted(notes["nonvar_prime"]):
        report.add(PASS, SEV_WARN, name,
                   f"prime applied to {sub!r}, which is not a declared "
                   f"state variable")
    for sub in sorted(notes["bad_frame"]):
        report.add(PASS, SEV_WARN, name,
                   f"UNCHANGED frame {sub!r} does not resolve to a "
                   f"tuple of state variables; coverage assumed from "
                   f"the variables it mentions")

    # strict under-approximation: variables assigned on EVERY path
    assigned_all = _assigned(action.expr, ev, defs, varnames, set())

    for v in sorted(varnames - primed_any - unchanged_any):
        report.add(PASS, SEV_ERROR, name,
                   f"state variable {v!r} is neither primed nor in the "
                   f"UNCHANGED frame (successor under-specified; the "
                   f"interpreter would fail at the first enabled step)")
    for v in sorted((varnames - assigned_all)
                    & (primed_any | unchanged_any)):
        report.add(PASS, SEV_WARN, name,
                   f"state variable {v!r} is framed on some paths but "
                   f"not provably on all execution paths")

    # double frame across sibling conjuncts of the (binder-stripped)
    # top-level conjunction — path-insensitive, so restricted to the
    # one level where it cannot false-positive on IF/\/ branch splits
    conjuncts = _top_conjuncts(action.expr)
    if len(conjuncts) > 1:
        per = [(_primes_direct(c, ev, defs, varnames, set()),
                _unchanged_direct(c, ev, varnames)) for c in conjuncts]
        for i, (pi, _ui) in enumerate(per):
            for j, (_pj, uj) in enumerate(per):
                if i == j:
                    continue
                for v in sorted(pi & uj):
                    report.add(
                        PASS, SEV_WARN, name,
                        f"{v!r} is primed in one conjunct and UNCHANGED "
                        f"in a sibling conjunct (degenerates to an "
                        f"equality guard — almost certainly a stale "
                        f"frame)")

    # guard/update classification: disjunction with mixed branches
    _check_mixed_disjunctions(action.expr, ev, defs, varnames, name,
                              report, set())


# ----------------------------------------------------------------------
# walkers
# ----------------------------------------------------------------------
def _iter_children(e):
    for x in e[1:]:
        if isinstance(x, tuple):
            yield x
        elif isinstance(x, list):
            for y in x:
                if isinstance(y, tuple):
                    yield y
                elif isinstance(y, (list,)):
                    for z in y:
                        if isinstance(z, tuple):
                            yield z


def _scan(e, ev, defs, varnames, primed, unchanged, notes, seen,
          under_prime):
    """Collect primed/UNCHANGED variables anywhere in the expression,
    inlining operator definitions that touch primes."""
    if not isinstance(e, tuple) or not e:
        return
    tag = e[0]
    if tag == "prime":
        inner = e[1]
        if under_prime or _contains_tag(inner, "prime"):
            notes["double_prime"].append(_describe(inner))
        if inner[0] == "id":
            if inner[1] in varnames:
                primed.add(inner[1])
            else:
                notes["nonvar_prime"].add(inner[1])
        else:
            # prime of a compound expression: every state var inside is
            # potentially constrained — treat them as primed (liberal)
            for v in _ids_in(inner, varnames):
                primed.add(v)
            notes["nonvar_prime"].add(_describe(inner))
        _scan(inner, ev, defs, varnames, primed, unchanged, notes, seen,
              under_prime=True)
        return
    if tag == "unchanged":
        try:
            unchanged.update(ev.collect_state_vars(e[1], EMPTY_ENV))
        except Exception:  # noqa: BLE001 — unresolvable frame expr
            # stay liberal: treat every state var mentioned inside the
            # frame as covered, so an exotic-but-correct frame cannot
            # produce a false unframed ERROR (it gets a WARN instead)
            unchanged.update(_ids_in(e[1], varnames))
            notes["bad_frame"].add(_describe(e[1]))
        return
    if tag in ("call", "id"):
        dname = e[1]
        d = defs.get(dname)
        if d is not None and dname not in seen and ev.touches_primes(dname):
            seen = seen | {dname}
            _scan(d.body, ev, defs, varnames, primed, unchanged, notes,
                  seen, under_prime)
    for c in _iter_children(e):
        _scan(c, ev, defs, varnames, primed, unchanged, notes, seen,
              under_prime)


def _assigned(e, ev, defs, varnames, seen):
    """Variables definitely framed on EVERY path (under-approximation:
    mirrors ActionEnumerator's binding forms)."""
    if not isinstance(e, tuple) or not e:
        return frozenset()
    tag = e[0]
    if tag == "and":
        out = set()
        for x in e[1]:
            out |= _assigned(x, ev, defs, varnames, seen)
        return frozenset(out)
    if tag == "or":
        branches = [_assigned(x, ev, defs, varnames, seen) for x in e[1]]
        return frozenset.intersection(*branches) if branches \
            else frozenset()
    if tag == "exists":
        return _assigned(e[2], ev, defs, varnames, seen)
    if tag == "binop" and e[1] == "eq" and e[2][0] == "prime" \
            and e[2][1][0] == "id" and e[2][1][1] in varnames:
        return frozenset((e[2][1][1],))
    if tag == "unchanged":
        try:
            return frozenset(ev.collect_state_vars(e[1], EMPTY_ENV))
        except Exception:  # noqa: BLE001
            return frozenset()
    if tag == "if":
        return _assigned(e[2], ev, defs, varnames, seen) \
            & _assigned(e[3], ev, defs, varnames, seen)
    if tag == "case":
        branches = [_assigned(v, ev, defs, varnames, seen)
                    for _g, v in e[1]]
        if e[2] is not None:
            branches.append(_assigned(e[2], ev, defs, varnames, seen))
        return frozenset.intersection(*branches) if branches \
            else frozenset()
    if tag in ("call", "id"):
        dname = e[1]
        d = defs.get(dname)
        if d is not None and dname not in seen and ev.touches_primes(dname):
            return _assigned(d.body, ev, defs, varnames, seen | {dname})
        return frozenset()
    if tag == "let":
        return _assigned(e[2], ev, defs, varnames, seen)
    return frozenset()


def _top_conjuncts(e):
    """Flatten the top-level conjunction, descending through the
    leading existential chain (the lane-binder shape, lower/ir.py)."""
    if not isinstance(e, tuple):
        return []
    if e[0] == "exists":
        return _top_conjuncts(e[2])
    if e[0] == "and":
        out = []
        for x in e[1]:
            if isinstance(x, tuple) and x[0] == "exists":
                out.extend(_top_conjuncts(x))
            else:
                out.append(x)
        return out
    return [e]


def _primes_direct(e, ev, defs, varnames, seen):
    out, unch = set(), set()
    notes = {"double_prime": [], "nonvar_prime": set(),
             "bad_frame": set()}
    _scan(e, ev, defs, varnames, out, unch, notes, seen,
          under_prime=False)
    return out


def _unchanged_direct(e, ev, varnames):
    out = set()
    if isinstance(e, tuple) and e and e[0] == "unchanged":
        try:
            out.update(ev.collect_state_vars(e[1], EMPTY_ENV))
        except Exception:  # noqa: BLE001
            pass
    return out


def _check_mixed_disjunctions(e, ev, defs, varnames, action_name,
                              report, seen):
    if not isinstance(e, tuple) or not e:
        return
    if e[0] == "or" and len(e[1]) > 1:
        priming = [bool(_primes_direct(x, ev, defs, varnames, set()))
                   for x in e[1]]
        if any(priming) and not all(priming):
            report.add(
                PASS, SEV_WARN, action_name,
                f"disjunction mixes updating and guard-only branches "
                f"({sum(priming)}/{len(priming)} branches prime state); "
                f"the lowerer requires branch-exclusive update "
                f"disjunctions")
    if e[0] in ("call", "id"):
        dname = e[1]
        d = defs.get(dname)
        if d is not None and dname not in seen and ev.touches_primes(dname):
            _check_mixed_disjunctions(d.body, ev, defs, varnames,
                                      action_name, report,
                                      seen | {dname})
    for c in _iter_children(e):
        _check_mixed_disjunctions(c, ev, defs, varnames, action_name,
                                  report, seen)


# ----------------------------------------------------------------------
def _contains_tag(e, tag):
    if not isinstance(e, tuple) or not e:
        return False
    if e[0] == tag:
        return True
    return any(_contains_tag(c, tag) for c in _iter_children(e))


def _ids_in(e, varnames):
    out = set()
    if not isinstance(e, tuple) or not e:
        return out
    if e[0] == "id" and e[1] in varnames:
        out.add(e[1])
    for c in _iter_children(e):
        out |= _ids_in(c, varnames)
    return out


def _describe(e):
    if isinstance(e, tuple) and e and e[0] == "id":
        return e[1]
    if isinstance(e, tuple) and e and e[0] == "call":
        return f"{e[1]}(...)"
    return f"<{e[0]} expression>" if isinstance(e, tuple) and e \
        else repr(e)
