"""Pass 6 — symbolic interval analysis ("bounds"): the Apalache-style
pre-pass over the cfg-instantiated spec (ROADMAP item 5; the TLA+
Trifecta framing, arxiv 2211.07216).

Every other speclint pass proves a property and stops; this one
computes FACTS the engines consume (ISSUE 13 tentpole):

* **reachable intervals** — a least fixpoint of interval/finite-domain
  transfer functions over the state variables, starting from Init and
  joining every action's guarded updates.  The result is a sound
  over-approximation of the reachable values, so a ``plane_bounds``
  budget intersected with it still round-trips every reachable state
  EXACTLY — ``engine/pack.build_pack_spec(tighten=...)`` packs
  *reachable* ranges instead of declared ones (fewer bits/state,
  bit-identical results);
* **statically dead actions** — a guard conjunct that constant-folds
  to FALSE under the bound constants (the vacuity pass's partial
  evaluator), or whose interval refinement against the reachable
  fixpoint is empty, can never fire: the engines drop the action from
  the kernel's lane tables (``engine/bounds.prune_kernel``), shrinking
  the fused commit's guard matrix;
* **per-action fanout** — the product of the action's lane-binder
  domain cardinalities is an upper bound on simultaneously enabled
  lanes per state (exact when no guard mentions a binder): the fused
  commit seeds its per-action expansion caps from it, so exact-bounds
  fixtures run with ZERO growth redraws;
* **state-space upper bound** — ``|S| <= prod(var domain sizes)``
  after dead-variable elimination; the dispatch service's admission
  gate compares it against the requested tier's capacity and rejects
  provably oversized submissions before any device time.

Trust contract: the facts are only consumed when the speclint gate is
live — ``-lint=off`` / ``TPUVSR_LINT=off`` also disables bounds
consumption (``-bounds on`` under a disabled gate is a CLI conflict),
and every engine guards the tightened configuration with the
"bit-identical verdict and counts vs untightened" oracles in
``tests/test_bounds.py``.

Refusal policy: the transfer functions cover the corpus's guarded-
command arithmetic (literals, bound constants, ``+``/``-``, constant
scaling, IF, comparisons and set membership against foldable values).
A guard conjunct that mentions a state variable in a shape the
abstract domain cannot interpret (e.g. a NONLINEAR guard ``x * x < K``)
makes the pass REFUSE tightening outright — ``tightened: false`` is
journaled, engines fall back to declared plane bounds and full action
lists (dead actions proven by pure constant folding are still safe to
prune).  Refusing is deliberately blunter than soundness requires
(ignoring an uninterpretable guard would still over-approximate); the
blunt rule keeps "what did the engines trust" a one-bit answer.

The declared-range side of every comparison comes from ONE source —
``widths.derive_ranges`` — the same table ``plane_bounds``/
``build_pack_spec`` read (ISSUE 13 satellite: a codec width edit
cannot silently diverge from the lint table; the drift pass
round-trips the tightened packing too).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field

from ...core.values import ModelValue
from ...lower.ir import contains_prime
from ..report import SEV_INFO, SEV_WARN
from .vacuity import _fold, _is_int

PASS = "bounds"

#: fixpoint iteration cap; non-convergence refuses tightening (the
#: corpus's monotone counters converge in O(limit) joins)
MAX_ITERS = 64

_INF = float("inf")


class _Refuse(Exception):
    """Tightening must be refused (uninterpretable guard, divergent
    fixpoint); carries the reason journaled as bounds{tightened:false}."""


class _Unsupported(Exception):
    """One expression is outside the abstract domain (poisons its
    target variable, does not refuse the whole analysis)."""


# ----------------------------------------------------------------------
# abstract values: ("ival", lo, hi) closed int interval |
#                  ("set", frozenset) finite value domain |
#                  TOP (unknown/poisoned) — None is bottom (unassigned)
# ----------------------------------------------------------------------
TOP = ("top",)


def _ival(lo, hi):
    return ("ival", int(lo), int(hi))


def _hull(av):
    """Interval hull of an abstract value, or None when not integer."""
    if av is TOP:
        return None
    if av[0] == "ival":
        return av
    if all(_is_int(x) for x in av[1]):
        if not av[1]:
            return None
        return _ival(min(av[1]), max(av[1]))
    return None


def _size(av):
    if av is TOP:
        return None
    if av[0] == "set":
        return len(av[1])
    return av[2] - av[1] + 1


def _as_set(av, limit=64):
    """Promote a small interval to an explicit set (mixed int /
    model-value domains — e.g. an int-0 "unset" slot joined with a
    symmetric value set)."""
    if av[0] == "set":
        return av
    if av[2] - av[1] + 1 <= limit:
        return ("set", frozenset(range(av[1], av[2] + 1)))
    return None


def _join(a, b):
    if a is TOP or b is TOP:
        return TOP
    if a is None:
        return b
    if b is None:
        return a
    if a[0] == "set" or b[0] == "set":
        sa, sb = _as_set(a), _as_set(b)
        if sa is not None and sb is not None:
            merged = sa[1] | sb[1]
            if all(_is_int(x) for x in merged):
                return _ival(min(merged), max(merged))
            return ("set", merged)
    ha, hb = _hull(a), _hull(b)
    if ha is None or hb is None:
        return TOP
    return _ival(min(ha[1], hb[1]), max(ha[2], hb[2]))


def _meet_ival(av, lo, hi):
    """Meet an abstract value with [lo, hi]; returns the new value or
    False when empty (the guard is unsatisfiable)."""
    if av is TOP:
        return TOP                 # unknown var: refinement is a no-op
    if av[0] == "ival":
        nlo, nhi = max(av[1], lo), min(av[2], hi)
        return _ival(nlo, nhi) if nlo <= nhi else False
    kept = frozenset(x for x in av[1]
                     if not _is_int(x) or lo <= x <= hi)
    return ("set", kept) if kept else False


# ----------------------------------------------------------------------
@dataclass
class BoundsFacts:
    """The facts one bound spec yields — what the engines consume."""
    module: str
    tightened: bool
    refused: str = None            # why tightening was refused
    intervals: dict = field(default_factory=dict)   # var -> (lo, hi)
    domain_sizes: dict = field(default_factory=dict)  # var -> |domain|
    dead_actions: list = field(default_factory=list)
    dead_reasons: dict = field(default_factory=dict)
    fanout: dict = field(default_factory=dict)      # action -> int
    fanout_exact: dict = field(default_factory=dict)
    state_bound: int = None

    def to_dict(self):
        return {"module": self.module, "tightened": self.tightened,
                "refused": self.refused,
                "intervals": {k: list(v)
                              for k, v in sorted(self.intervals.items())},
                "dead_actions": list(self.dead_actions),
                "fanout": dict(sorted(self.fanout.items())),
                "state_bound": self.state_bound,
                "digest": self.digest}

    @property
    def digest(self):
        """Stable identity of the consumed facts — recorded in
        checkpoint manifests so a resume under a flipped ``-bounds``
        (or a changed facts table) is a policy error, mirroring the
        pack/canon rules."""
        canon = {"module": self.module, "tightened": self.tightened,
                 "intervals": sorted((k, int(v[0]), int(v[1]))
                                     for k, v in self.intervals.items()),
                 "dead": sorted(self.dead_actions),
                 "state_bound": self.state_bound}
        return hashlib.sha256(
            json.dumps(canon, sort_keys=True).encode()).hexdigest()[:12]

    def plane_tighten(self):
        """The per-plane tightening map ``build_pack_spec`` intersects
        with the codec's declared ``plane_bounds``: reachable int
        intervals keyed by state-variable name (codecs whose plane keys
        are the variable names — the stub family — tighten directly;
        the registered corpus layouts read the shared
        ``widths.derive_ranges`` quantity table instead)."""
        return dict(self.intervals) if self.tightened else {}

    def journal_doc(self):
        """The compact ``bounds`` object journaled on run_start."""
        return {"tightened": self.tightened,
                "dead_actions": list(self.dead_actions),
                "state_bound": self.state_bound}


# ----------------------------------------------------------------------
# expression-level helpers
# ----------------------------------------------------------------------
def _mentions(e, names):
    """Does `e` mention any identifier in `names` (direct, no operator
    expansion — guards hidden behind definitions refine nothing and
    refuse nothing: ignoring them only widens the over-approximation)."""
    if not isinstance(e, tuple) or not e:
        return False
    if e[0] == "id":
        return e[1] in names
    for x in e[1:]:
        if isinstance(x, tuple) and _mentions(x, names):
            return True
        if isinstance(x, list):
            for y in x:
                if isinstance(y, tuple) and _mentions(y, names):
                    return True
    return False


def _primed_vars(e, spec, out, _seen=None):
    """Collect state variables primed (transitively) by `e`."""
    if _seen is None:
        _seen = set()
    if not isinstance(e, tuple) or not e:
        return
    if e[0] == "prime":
        inner = e[1]
        if isinstance(inner, tuple) and inner and inner[0] == "id":
            out.add(inner[1])
        else:
            out.update(spec.module.variables)     # conservative
        return
    if e[0] in ("call", "id"):
        d = spec.module.defs.get(e[1])
        if d is not None and e[1] not in _seen:
            _seen.add(e[1])
            _primed_vars(d.body, spec, out, _seen)
    for x in e[1:]:
        if isinstance(x, tuple):
            _primed_vars(x, spec, out, _seen)
        elif isinstance(x, list):
            for y in x:
                if isinstance(y, tuple):
                    _primed_vars(y, spec, out, _seen)


def _aeval(e, spec, env, benv):
    """Abstract evaluation of an integer/value expression under the
    variable environment `env` and binder domains `benv`."""
    if not isinstance(e, tuple) or not e:
        raise _Unsupported(repr(e))
    tag = e[0]
    if tag == "num":
        return _ival(e[1], e[1])
    if tag == "id":
        name = e[1]
        if name in benv:
            dv = benv[name]
            if dv is None:
                raise _Unsupported(f"binder {name} domain")
            return dv
        if name in env:
            av = env[name]
            if av is TOP or av is None:
                raise _Unsupported(f"variable {name} is unbounded")
            return av
        v = _fold(e, spec, set())
        if _is_int(v):
            return _ival(v, v)
        if isinstance(v, (ModelValue, str, bool)):
            return ("set", frozenset([v]))
        raise _Unsupported(name)
    if tag == "neg":
        h = _hull(_aeval(e[1], spec, env, benv))
        if h is None:
            raise _Unsupported("neg of non-integer")
        return _ival(-h[2], -h[1])
    if tag == "if":
        c = _fold(e[1], spec, set())
        if c is True:
            return _aeval(e[2], spec, env, benv)
        if c is False:
            return _aeval(e[3], spec, env, benv)
        j = _join(_aeval(e[2], spec, env, benv),
                  _aeval(e[3], spec, env, benv))
        if j is TOP:
            raise _Unsupported("if-join")
        return j
    if tag == "binop":
        op = e[1]
        if op in ("plus", "minus", "times"):
            a = _hull(_aeval(e[2], spec, env, benv))
            b = _hull(_aeval(e[3], spec, env, benv))
            if a is None or b is None:
                raise _Unsupported(op)
            if op == "plus":
                return _ival(a[1] + b[1], a[2] + b[2])
            if op == "minus":
                return _ival(a[1] - b[2], a[2] - b[1])
            # times: constant scaling only — general interval products
            # are where precision (and the corpus) ends
            if a[1] == a[2]:
                c, iv = a[1], b
            elif b[1] == b[2]:
                c, iv = b[1], a
            else:
                raise _Unsupported("nonlinear times")
            lo, hi = c * iv[1], c * iv[2]
            return _ival(min(lo, hi), max(lo, hi))
    raise _Unsupported(tag)


def _domain_value(dom, spec):
    """A binder's domain expression -> abstract value (or None when it
    is not statically enumerable)."""
    v = _fold(dom, spec, set())
    if isinstance(v, frozenset):
        return ("set", v) if v else None
    if isinstance(dom, tuple) and dom and dom[0] == "binop" \
            and dom[1] == "range":
        lo = _fold(dom[2], spec, set())
        hi = _fold(dom[3], spec, set())
        if _is_int(lo) and _is_int(hi) and lo <= hi:
            return _ival(lo, hi)
    return None


# ----------------------------------------------------------------------
# action decomposition
# ----------------------------------------------------------------------
def _decompose(expr, spec):
    """(binders, guards, updates) of one action body: the top-level
    existential chain (any statically enumerable domain, not just the
    lane-liftable corpus tags), the non-priming conjuncts, and the
    priming ones."""
    binders, guards, updates = [], [], []

    def walk(e):
        if not isinstance(e, tuple) or not e:
            return
        if e[0] == "and":
            for x in e[1]:
                walk(x)
        elif e[0] == "exists":
            for names, dom in e[1]:
                dv = _domain_value(dom, spec)
                for n in names:
                    binders.append((n, dv))
            walk(e[2])
        elif e[0] == "unchanged":
            pass                    # x' = x: joins nothing new
        elif contains_prime(e, spec.module):
            updates.append(e)
        else:
            guards.append(e)

    walk(expr)
    return binders, guards, updates


_CMP = {"lt": lambda c: (-_INF, c - 1), "le": lambda c: (-_INF, c),
        "gt": lambda c: (c + 1, _INF), "ge": lambda c: (c, _INF),
        "eq": lambda c: (c, c)}
_SWAP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}


def _refine(g, spec, env, benv, varnames):
    """Refine `env` in place by one guard conjunct.  Returns False when
    the guard is unsatisfiable under `env`, True otherwise.  Raises
    :class:`_Refuse` on a state-variable guard outside the domain."""
    v = _fold(g, spec, set())
    if v is False:
        return False
    if v is True:
        return True
    if isinstance(g, tuple) and g and g[0] == "binop":
        op, lhs, rhs = g[1], g[2], g[3]
        if isinstance(rhs, tuple) and rhs[0] == "id" \
                and rhs[1] in varnames and not (
                isinstance(lhs, tuple) and lhs[0] == "id"
                and lhs[1] in varnames):
            lhs, rhs = rhs, lhs
            op = _SWAP.get(op, op)
        if isinstance(lhs, tuple) and lhs[0] == "id" \
                and lhs[1] in varnames:
            var = lhs[1]
            c = _fold(rhs, spec, set())
            if op in _CMP and _is_int(c):
                lo, hi = _CMP[op](c)
                lo = -(1 << 62) if lo == -_INF else lo
                hi = (1 << 62) if hi == _INF else hi
                m = _meet_ival(env.get(var, TOP), lo, hi)
                if m is False:
                    return False
                env[var] = m
                return True
            if op == "eq" and isinstance(c, (ModelValue, str, bool)):
                av = env.get(var, TOP)
                if av is not TOP and av is not None and av[0] == "set":
                    kept = frozenset(
                        x for x in av[1]
                        if isinstance(x, type(c))
                        and (x is c or getattr(x, "name", x)
                             == getattr(c, "name", c)))
                    if not kept:
                        return False
                    env[var] = ("set", kept)
                return True
            if op == "in":
                # the SAME domain logic Init and binder chains use
                # (_domain_value understands folded sets AND lo..hi
                # range expressions), so `x \in 0..K` guards refine
                # instead of triggering the blunt whole-spec refusal
                dv = _domain_value(rhs, spec)
                if dv is None:
                    return True if not _mentions(rhs, varnames) \
                        else _refuse_guard(g)
                av = env.get(var, TOP)
                if av is TOP or av is None:
                    env[var] = dv
                    return True
                if dv[0] == "ival":
                    m = _meet_ival(av, dv[1], dv[2])
                    if m is False:
                        return False
                    env[var] = m
                    return True
                if av[0] == "set":
                    kept = av[1] & dv[1]       # ModelValues interned
                    if not kept:
                        return False
                    env[var] = ("set", kept)
                    return True
                ints = [x for x in dv[1] if _is_int(x)]
                if ints:
                    m = _meet_ival(av, min(ints), max(ints))
                    if m is False:
                        return False
                    env[var] = m
                return True
    if _mentions(g, varnames):
        _refuse_guard(g)
    return True                     # constants/binders only: no-op


def _refuse_guard(g):
    raise _Refuse(
        f"guard conjunct outside the interval domain: {g[0]!r} "
        f"expression over state variables (e.g. nonlinear "
        f"arithmetic) — falling back to declared bounds")


def _init_env(spec, varnames):
    """Abstract environment of Init.  Unassigned / uninterpretable
    variables start TOP (declared bounds); an Init body outside plain
    conjunct shape refuses tightening."""
    d = spec.module.defs.get(spec.init_name)
    if d is None:
        raise _Refuse(f"INIT {spec.init_name} not defined")
    env = {v: None for v in varnames}

    def walk(e):
        if not isinstance(e, tuple) or not e:
            return
        if e[0] == "and":
            for x in e[1]:
                walk(x)
            return
        if e[0] == "binop" and e[1] in ("eq", "in") and \
                isinstance(e[2], tuple) and e[2][0] == "id" \
                and e[2][1] in varnames:
            var, rhs = e[2][1], e[3]
            if e[1] == "eq":
                v = _fold(rhs, spec, set())
                if _is_int(v):
                    env[var] = _join(env[var], _ival(v, v))
                    return
                if isinstance(v, (ModelValue, str, bool)):
                    env[var] = _join(env[var],
                                     ("set", frozenset([v])))
                    return
            else:
                dv = _domain_value(rhs, spec)
                if dv is not None:
                    env[var] = _join(env[var], dv)
                    return
            env[var] = TOP
            return
        # any other conjunct: every variable it mentions is unknown
        for v in varnames:
            if _mentions(e, {v}):
                env[v] = TOP

    walk(d.body)
    for v in varnames:
        if env[v] is None:
            env[v] = TOP
    return env


# ----------------------------------------------------------------------
# the analysis
# ----------------------------------------------------------------------
def analyze(spec) -> BoundsFacts:
    """Compute (and cache per spec object) the bounds facts."""
    cached = getattr(spec, "_bounds_facts", None)
    if cached is not None:
        return cached
    facts = _analyze(spec)
    spec._bounds_facts = facts
    return facts


def _fold_dead(action, spec):
    """Reason string when a guard conjunct constant-folds to FALSE
    (sound independent of the interval fixpoint)."""
    from .vacuity import _guard_conjuncts
    for conj in _guard_conjuncts(action.expr, spec):
        if _fold(conj, spec, set()) is False:
            return "guard conjunct folds to FALSE under the cfg"
    return None


def _analyze(spec) -> BoundsFacts:
    varnames = set(spec.module.variables)
    facts = BoundsFacts(module=spec.module.name, tightened=False)

    # dead-by-folding first: sound even when tightening is refused
    live = []
    for action in spec.actions:
        why = _fold_dead(action, spec)
        if why is not None:
            facts.dead_actions.append(action.name)
            facts.dead_reasons[action.name] = why
        else:
            live.append(action)

    # fanout upper bounds from the statically enumerable binder chain
    for action in live:
        binders, guards, _updates = _decompose(action.expr, spec)
        if any(dv is None for _n, dv in binders):
            continue
        prod = 1
        for _n, dv in binders:
            prod *= _size(dv)
        bnames = {n for n, _dv in binders}
        facts.fanout[action.name] = prod
        facts.fanout_exact[action.name] = not any(
            _mentions(g, bnames) for g in guards)

    # interval fixpoint (refusal falls through with tightened=False)
    try:
        env = _fixpoint(spec, varnames, live, facts)
    except _Refuse as e:
        facts.refused = str(e)
        return facts

    facts.tightened = True
    for v in sorted(varnames):
        av = env.get(v)
        h = _hull(av) if av is not TOP and av is not None else None
        if h is not None:
            facts.intervals[v] = (h[1], h[2])
        sz = _size(av) if av is not TOP and av is not None else None
        if sz is not None:
            facts.domain_sizes[v] = sz
    if varnames and all(v in facts.domain_sizes for v in varnames):
        bound = 1
        for v in varnames:
            bound *= facts.domain_sizes[v]
        facts.state_bound = bound
    return facts


def _fixpoint(spec, varnames, live, facts):
    env = _init_env(spec, varnames)
    for _it in range(MAX_ITERS):
        changed = False
        for action in live:
            out = _transfer(action, spec, env, varnames)
            if out is None:
                continue
            for v, av in out.items():
                j = _join(env.get(v), av)
                if j != env.get(v):
                    env[v] = j
                    changed = True
        if not changed:
            break
    else:
        raise _Refuse(f"interval fixpoint did not converge within "
                      f"{MAX_ITERS} iterations")

    # interval-proven dead actions: guard refinement empty at fixpoint
    for action in live:
        binders, guards, _updates = _decompose(action.expr, spec)
        benv = dict(binders)
        ref = dict(env)
        sat = True
        for g in guards:
            if not _refine(g, spec, ref, benv, varnames):
                sat = False
                break
        if not sat and action.name not in facts.dead_actions:
            facts.dead_actions.append(action.name)
            facts.dead_reasons[action.name] = \
                "guard unsatisfiable against the reachable intervals"
    return env


def _transfer(action, spec, env, varnames):
    """One action's contribution to the next environment: the guarded
    updates evaluated under the guard-refined env, or None when the
    guard is unsatisfiable this iteration."""
    binders, guards, updates = _decompose(action.expr, spec)
    benv = dict(binders)
    ref = dict(env)
    for g in guards:
        if not _refine(g, spec, ref, benv, varnames):
            return None
    out = {}
    for upd in updates:
        if isinstance(upd, tuple) and upd[0] == "binop" \
                and upd[1] == "eq" and isinstance(upd[2], tuple) \
                and upd[2][0] == "prime" \
                and isinstance(upd[2][1], tuple) \
                and upd[2][1][0] == "id" \
                and upd[2][1][1] in varnames:
            var = upd[2][1][1]
            try:
                out[var] = _aeval(upd[3], spec, ref, benv)
            except _Unsupported:
                out[var] = TOP
        else:
            primed = set()
            _primed_vars(upd, spec, primed)
            for v in primed & varnames:
                out[v] = TOP
    return out


# ----------------------------------------------------------------------
# the lint pass
# ----------------------------------------------------------------------
def run(spec, report):
    facts = analyze(spec)
    report.extras["bounds"] = facts.to_dict()
    for name in facts.dead_actions:
        report.add(PASS, SEV_INFO, name,
                   f"statically dead under the cfg "
                   f"({facts.dead_reasons.get(name)}); the engines "
                   f"prune it from the kernel lane tables")
    if not facts.tightened:
        report.add(PASS, SEV_WARN, spec.module.name,
                   f"interval tightening refused: {facts.refused} — "
                   f"engines run declared plane bounds "
                   f"(bounds{{tightened:false}})")
        return
    tight = ", ".join(f"{k}=[{lo},{hi}]"
                      for k, (lo, hi) in sorted(facts.intervals.items()))
    report.add(PASS, SEV_INFO, spec.module.name,
               f"reachable intervals: {tight or '(none)'}; "
               f"state bound "
               f"{facts.state_bound if facts.state_bound is not None else 'unbounded'}; "
               f"{len(facts.dead_actions)} dead action(s)")
