"""Pass 3 — vacuity / dead-action lint.

Constant-folds every action's guard conjuncts and every registered
invariant under the bound cfg constants.  A guard that folds to FALSE
means the action can never fire under this configuration (dead action
— WARN, because config-gating an action via a zero limit is sometimes
intentional, e.g. CrashLimit = 0); an invariant that folds to TRUE is
vacuous (WARN — it checks nothing); one that folds to FALSE would fail
on every state (ERROR).  IF conditions that fold constant mark an
unreachable branch.

Folding is a partial evaluator: literals, bound integer/boolean/
model-value constants, parameterless operator definitions, boolean and
arithmetic operators over folded operands.  State variables fold to
"unknown" — EXCEPT for the monotone aux counters (aux_svc,
aux_restart, no_progress_ctr), which are known nonnegative from their
Init/update discipline, so ``counter < K`` folds to FALSE whenever the
limit K folds to a value <= 0.  That is exactly the corpus's
config-gating idiom (TimerSendSVC under StartViewOnTimerLimit,
RestartEmpty under RestartEmptyLimit, NoProgressChange under
NoProgressChangeLimit).
"""

from __future__ import annotations

from ...core.values import ModelValue
from ..report import SEV_ERROR, SEV_INFO, SEV_WARN

PASS = "vacuity"

# scalar state counters provably >= 0 (established at Init = 0 and only
# ever incremented); used to kill `ctr < K` guards for K <= 0
NONNEG_COUNTERS = ("aux_svc", "aux_restart", "no_progress_ctr")

_UNKNOWN = object()


def run(spec, report):
    for action in spec.actions:
        dead = False
        for conj in _guard_conjuncts(action.expr, spec):
            v = _fold(conj, spec, set())
            if v is False and not dead:
                dead = True
                report.add(PASS, SEV_WARN, action.name,
                           "guard conjunct is statically FALSE under "
                           "the bound cfg constants — the action can "
                           "never fire (dead action)")
            elif v is True:
                report.add(PASS, SEV_INFO, action.name,
                           "guard conjunct is trivially TRUE under the "
                           "bound cfg constants")
        _scan_branches(action.expr, spec, action.name, report, set())

    for inv_name, d in spec.invariants:
        v = _fold(d.body, spec, set())
        if v is True:
            report.add(PASS, SEV_WARN, inv_name,
                       "invariant folds to TRUE under the bound cfg "
                       "constants — it is vacuous and checks nothing")
        elif v is False:
            report.add(PASS, SEV_ERROR, inv_name,
                       "invariant folds to FALSE under the bound cfg "
                       "constants — every state would violate it")


# ----------------------------------------------------------------------
def _guard_conjuncts(e, spec):
    """Top-level non-priming conjuncts, descending through the leading
    existential chain (the uniform corpus action shape)."""
    from ...lower.ir import contains_prime
    out = []

    def walk(x):
        if not isinstance(x, tuple) or not x:
            return
        if x[0] == "exists":
            walk(x[2])
        elif x[0] == "and":
            for item in x[1]:
                walk(item)
        elif not contains_prime(x, spec.module):
            out.append(x)
    walk(e)
    return out


def _scan_branches(e, spec, action_name, report, seen):
    """Flag IF conditions that fold constant (unreachable branch)."""
    if not isinstance(e, tuple) or not e:
        return
    if e[0] == "if":
        v = _fold(e[1], spec, set())
        if v in (True, False):
            report.add(PASS, SEV_WARN, action_name,
                       f"IF condition folds to {v} under the bound cfg "
                       f"constants — the "
                       f"{'ELSE' if v else 'THEN'} branch is "
                       f"unreachable")
    if e[0] in ("call", "id"):
        d = spec.module.defs.get(e[1])
        if d is not None and e[1] not in seen \
                and spec.ev.touches_primes(e[1]):
            _scan_branches(d.body, spec, action_name, report,
                           seen | {e[1]})
    for x in e[1:]:
        if isinstance(x, tuple):
            _scan_branches(x, spec, action_name, report, seen)
        elif isinstance(x, list):
            for y in x:
                if isinstance(y, tuple):
                    _scan_branches(y, spec, action_name, report, seen)


# ----------------------------------------------------------------------
# partial evaluator
# ----------------------------------------------------------------------
def _fold(e, spec, seen):
    """Fold to a Python value, or _UNKNOWN."""
    return _fold_inner(e, spec, seen)


def _fold_inner(e, spec, seen):
    if not isinstance(e, tuple) or not e:
        return _UNKNOWN
    tag = e[0]
    if tag == "num":
        return e[1]
    if tag == "bool":
        return e[1]
    if tag == "str":
        return e[1]
    if tag == "id":
        name = e[1]
        c = spec.ev.constants.get(name)
        if isinstance(c, (int, bool, str, frozenset, ModelValue)):
            return c
        d = spec.module.defs.get(name)
        if d is not None and not d.params and name not in seen:
            return _fold_inner(d.body, spec, seen | {name})
        return _UNKNOWN
    if tag == "not":
        v = _fold_inner(e[1], spec, seen)
        return (not v) if isinstance(v, bool) else _UNKNOWN
    if tag == "neg":
        v = _fold_inner(e[1], spec, seen)
        return -v if _is_int(v) else _UNKNOWN
    if tag == "and":
        vals = [_fold_inner(x, spec, seen) for x in e[1]]
        if any(v is False for v in vals):
            return False
        if all(v is True for v in vals):
            return True
        return _UNKNOWN
    if tag == "or":
        vals = [_fold_inner(x, spec, seen) for x in e[1]]
        if any(v is True for v in vals):
            return True
        if all(v is False for v in vals):
            return False
        return _UNKNOWN
    if tag == "if":
        c = _fold_inner(e[1], spec, seen)
        if c is True:
            return _fold_inner(e[2], spec, seen)
        if c is False:
            return _fold_inner(e[3], spec, seen)
        return _UNKNOWN
    if tag == "binop":
        return _fold_binop(e, spec, seen)
    return _UNKNOWN


def _fold_binop(e, spec, seen):
    op = e[1]
    a = _fold_inner(e[2], spec, seen)
    b = _fold_inner(e[3], spec, seen)

    # nonneg-counter special case: `ctr < K` / `ctr >= K` with K folded
    if a is _UNKNOWN and _is_counter(e[2]) and _is_int(b):
        if op == "lt" and b <= 0:
            return False
        if op == "le" and b < 0:
            return False
        if op == "ge" and b <= 0:
            return True
        if op == "gt" and b < 0:
            return True
        return _UNKNOWN
    if a is _UNKNOWN or b is _UNKNOWN:
        return _UNKNOWN

    if op in ("plus", "minus", "times", "div", "mod") and _is_int(a) \
            and _is_int(b):
        if op == "plus":
            return a + b
        if op == "minus":
            return a - b
        if op == "times":
            return a * b
        if op == "div" and b != 0:
            return a // b
        if op == "mod" and b != 0:
            return a % b
        return _UNKNOWN
    if op in ("lt", "le", "gt", "ge") and _is_int(a) and _is_int(b):
        return {"lt": a < b, "le": a <= b,
                "gt": a > b, "ge": a >= b}[op]
    if op == "eq":
        return _const_eq(a, b)
    if op == "ne":
        v = _const_eq(a, b)
        return (not v) if isinstance(v, bool) else _UNKNOWN
    if op == "in" and isinstance(b, frozenset):
        return a in b
    if op == "notin" and isinstance(b, frozenset):
        return a not in b
    return _UNKNOWN


def _const_eq(a, b):
    if isinstance(a, ModelValue) or isinstance(b, ModelValue):
        # TLC model-value semantics: equal only to itself; comparison
        # with a different *kind* of value is an error, not False —
        # stay unknown unless both are model values
        if isinstance(a, ModelValue) and isinstance(b, ModelValue):
            return a is b or a.name == b.name
        return _UNKNOWN
    if type(a) is type(b):
        return a == b
    return _UNKNOWN


def _is_int(v):
    return isinstance(v, int) and not isinstance(v, bool)


def _is_counter(e):
    return isinstance(e, tuple) and e and e[0] == "id" \
        and e[1] in NONNEG_COUNTERS
