"""Pass 2 — width/overflow abstract interpretation.

The dense layouts (models/*.py) pack narrow protocol fields into wider
lanes: VSR's deterministic-CHOOSE sort key packs (client_id, operation,
request_number, view_number) into one int32 at bit offsets 20/16/8/0
(vsr_kernel._entry_sort_key), and the whole A01→CP06 family packs log
entries as ``value_id << 8 | view_number`` (ENTRY_VIEW_BITS).  A cfg
whose bound constants let a field exceed its lane silently corrupts
fingerprints and CHOOSE tie-breaks — the classic "wraps after hours"
failure the reference never had because TLC has no packed layouts.

This pass derives per-field value ranges from the bound cfg constants
alone (interval abstract interpretation over the constant bindings —
no codec construction, so it still fires when the codec itself would
refuse the config) and proves each range fits its allocated bit-width:

* view_number  <= 1 + StartViewOnTimerLimit (+ RestartEmptyLimit on
  VSR: views are only minted by TimerSendSVC under ``aux_svc < limit``,
  VSR.tla:578-580; a restarted replica can re-reach old views)
* op_number / request_number / operation id <= |Values| (each value is
  requested at most once — the aux_client_acked ghost guard)
* client_id <= ClientCount
* recovery nonce x <= 1 + CrashLimit (UniqueNumber mints one per crash)

plus a generic int31 check on every derived range and every integer
constant (all dense planes are int32 lanes).
"""

from __future__ import annotations

from ..report import SEV_ERROR, SEV_INFO, SEV_WARN

PASS = "widths"

INT31 = 1 << 31

# Packed-field budgets per layout family: (field, limit, where) — a
# field whose derived max REACHES the limit no longer fits.
_VSR_PACKED = (
    ("client_id", 1 << 11, "packed sort key bits 20..30 "
                           "(vsr_kernel._entry_sort_key)"),
    ("operation", 1 << 4, "packed sort key bits 16..19 "
                          "(vsr_kernel._entry_sort_key)"),
    ("request_number", 1 << 8, "packed sort key bits 8..15 "
                               "(vsr_kernel._entry_sort_key)"),
    ("view_number", 1 << 8, "packed sort key bits 0..7 "
                            "(vsr_kernel._entry_sort_key)"),
)
_PACKED_ENTRY = (
    ("view_number", 1 << 8, "packed log entry low byte "
                            "(ENTRY_VIEW_BITS, models/a01.py)"),
    ("operation", 1 << 23, "packed log entry high bits "
                           "(value_id << 8 must fit int32)"),
)

# module name -> packed-field table (absent = generic checks only)
FAMILY_PACKED = {
    "VSR": _VSR_PACKED,
    "VR_STATE_TRANSFER": (),          # scalar int32 entries, no packing
    "VR_ASSUME_NEWVIEWCHANGE": _PACKED_ENTRY,
    "VR_INC_RESEND": _PACKED_ENTRY,
    "VR_APP_STATE": _PACKED_ENTRY,
    "VR_REPLICA_RECOVERY": _PACKED_ENTRY,
    "VR_REPLICA_RECOVERY_ASYNC_LOG": _PACKED_ENTRY,
    "VR_REPLICA_RECOVERY_CP": _PACKED_ENTRY,
}


def derive_ranges(spec):
    """Interval ranges of the protocol quantities, from cfg constants
    alone.  Returns {} entries only for derivable quantities."""
    c = spec.ev.constants
    rng = {}

    def geti(name, default=None):
        v = c.get(name, default)
        return v if isinstance(v, int) and not isinstance(v, bool) \
            else None

    timer = geti("StartViewOnTimerLimit")
    restarts = geti("RestartEmptyLimit", 0)
    crashes = geti("CrashLimit", 0)
    values = c.get("Values")
    nvalues = len(values) if isinstance(values, frozenset) else None
    clients = geti("ClientCount", 1)
    replicas = geti("ReplicaCount")

    if timer is not None:
        extra = restarts or 0
        if spec.module.name != "VSR":
            extra = 0          # only VSR's RestartEmpty re-mints views
        rng["view_number"] = (0, 1 + timer + extra)
    if nvalues is not None:
        rng["operation"] = (0, nvalues)
        rng["op_number"] = (0, nvalues)        # MAX_OPS = |Values|
        rng["commit_number"] = (0, nvalues)
        rng["request_number"] = (0, nvalues)
    if clients is not None:
        rng["client_id"] = (0, clients)
    if replicas is not None:
        rng["replica_id"] = (0, replicas)
    if crashes is not None:
        rng["recovery_nonce"] = (0, 1 + crashes)
    return rng


def run(spec, report):
    rng = derive_ranges(spec)
    c = spec.ev.constants

    # generic int31 lane check: every derived range and every integer
    # constant must fit a signed 32-bit dense plane
    for name, (_lo, hi) in sorted(rng.items()):
        if hi >= INT31:
            report.add(PASS, SEV_ERROR, name,
                       f"derived range [0, {hi}] exceeds the int32 "
                       f"dense-plane width")
    for name, v in sorted(c.items()):
        if isinstance(v, int) and not isinstance(v, bool) and \
                abs(v) >= INT31:
            report.add(PASS, SEV_ERROR, name,
                       f"constant {v} does not fit an int32 lane")

    packed = FAMILY_PACKED.get(spec.module.name)
    if packed is None:
        report.add(PASS, SEV_INFO, spec.module.name,
                   "no registered packed layout for this module; "
                   "generic int32 checks only")
        return

    for fld, limit, where in packed:
        if fld not in rng:
            report.add(PASS, SEV_WARN, fld,
                       f"cannot derive a static bound for {fld!r} from "
                       f"the cfg constants; packed width {limit} in "
                       f"{where} is unverified")
            continue
        lo, hi = rng[fld]
        if hi >= limit:
            report.add(PASS, SEV_ERROR, fld,
                       f"derived range [{lo}, {hi}] overflows the "
                       f"{limit.bit_length() - 1}-bit field in {where} "
                       f"(max representable {limit - 1}); values would "
                       f"wrap silently")
        else:
            report.add(PASS, SEV_INFO, fld,
                       f"range [{lo}, {hi}] fits {where} "
                       f"(headroom {limit - 1 - hi})")
