"""Pass 2 — width/overflow abstract interpretation.

The dense layouts (models/*.py) pack narrow protocol fields into wider
lanes: VSR's deterministic-CHOOSE sort key packs (client_id, operation,
request_number, view_number) into one int32 at bit offsets 20/16/8/0
(vsr_kernel._entry_sort_key), and the whole A01→CP06 family packs log
entries as ``value_id << 8 | view_number`` (ENTRY_VIEW_BITS).  A cfg
whose bound constants let a field exceed its lane silently corrupts
fingerprints and CHOOSE tie-breaks — the classic "wraps after hours"
failure the reference never had because TLC has no packed layouts.

This pass derives per-field value ranges from the bound cfg constants
alone (interval abstract interpretation over the constant bindings —
no codec construction, so it still fires when the codec itself would
refuse the config) and proves each range fits its allocated bit-width:

* view_number  <= 1 + StartViewOnTimerLimit (+ RestartEmptyLimit on
  VSR: views are only minted by TimerSendSVC under ``aux_svc < limit``,
  VSR.tla:578-580; a restarted replica can re-reach old views)
* op_number / request_number / operation id <= |Values| (each value is
  requested at most once — the aux_client_acked ghost guard)
* client_id <= ClientCount
* recovery nonce x <= 1 + CrashLimit (UniqueNumber mints one per crash)

plus a generic int31 check on every derived range and every integer
constant (all dense planes are int32 lanes).
"""

from __future__ import annotations

from ..report import SEV_ERROR, SEV_INFO, SEV_WARN

PASS = "widths"

INT31 = 1 << 31

# Packed-field budgets per layout family: (field, limit, where) — a
# field whose derived max REACHES the limit no longer fits.
_VSR_PACKED = (
    ("client_id", 1 << 11, "packed sort key bits 20..30 "
                           "(vsr_kernel._entry_sort_key)"),
    ("operation", 1 << 4, "packed sort key bits 16..19 "
                          "(vsr_kernel._entry_sort_key)"),
    ("request_number", 1 << 8, "packed sort key bits 8..15 "
                               "(vsr_kernel._entry_sort_key)"),
    ("view_number", 1 << 8, "packed sort key bits 0..7 "
                            "(vsr_kernel._entry_sort_key)"),
)
_PACKED_ENTRY = (
    ("view_number", 1 << 8, "packed log entry low byte "
                            "(ENTRY_VIEW_BITS, models/a01.py)"),
    ("operation", 1 << 23, "packed log entry high bits "
                           "(value_id << 8 must fit int32)"),
)

# AL05 reverts to plain value-id entries (al05.py undoes RR05's
# 2-field packing), so _PACKED_ENTRY's attributions are wrong for it —
# but AL05Codec still INHERITS RR05Codec.__init__'s MAX_VIEW < 256
# construction guard, so the view bound itself is real.  Its
# module-specific hazard is the re-based recovery suffix log
# (dedicated plane check: FAMILY_PLANES).
_AL05_PACKED = (
    ("view_number", 1 << 8, "inherited packed-entry construction "
                            "guard (AL05Codec <- RR05Codec.__init__: "
                            "MAX_VIEW < 256)"),
)
# CP06 entries are plain ids too (NoOp = |Values|+1, cp06.py), but
# WinningDVC packs its suffix sort keys as domain*64 + entry_code
# (cp06_kernel._winning_dvc) — entry codes must stay under 64 or the
# deterministic-CHOOSE tie-break silently mis-sorts.
_CP06_PACKED = (
    ("view_number", 1 << 8, "inherited packed-entry construction "
                            "guard (CP06Codec <- RR05Codec.__init__: "
                            "MAX_VIEW < 256)"),
    ("entry_code", 64, "packed suffix sort key domain*64 + entry "
                       "(cp06_kernel._winning_dvc; NoOp id = "
                       "|Values|+1)"),
)

# module name -> packed-field table (absent = generic checks only)
FAMILY_PACKED = {
    "VSR": _VSR_PACKED,
    "VR_STATE_TRANSFER": (),          # scalar int32 entries, no packing
    "VR_ASSUME_NEWVIEWCHANGE": _PACKED_ENTRY,
    "VR_INC_RESEND": _PACKED_ENTRY,
    "VR_APP_STATE": _PACKED_ENTRY,
    "VR_REPLICA_RECOVERY": _PACKED_ENTRY,
    "VR_REPLICA_RECOVERY_ASYNC_LOG": _AL05_PACKED,
    "VR_REPLICA_RECOVERY_CP": _CP06_PACKED,
}

# module name -> dedicated plane-budget checks (ISSUE 4 satellite;
# ROADMAP follow-up): (field, bounded quantity, where).  The plane
# capacity is MAX_OPS = |Values| rows, derived from the same cfg —
# normally an INFO fit/headroom line, a WARN when the bound is
# underivable from the constants, an ERROR should the derived range
# ever exceed the plane.
FAMILY_PLANES = {
    "VR_REPLICA_RECOVERY_ASYNC_LOG": (
        ("suffix_log", "op_number",
         "re-based recovery suffix rows rec_log/m_log[MAX_OPS] "
         "(al05.py _encode_rec: first_op = prefix_ceil + 1)"),),
    "VR_REPLICA_RECOVERY_CP": (
        ("checkpoint_plane", "cp_number",
         "checkpoint payload rows m_cp/rec_cp/dvc_cp[MAX_OPS] "
         "(cp06.py zero_state)"),),
}


def derive_ranges(spec):
    """Interval ranges of the protocol quantities, from cfg constants
    alone.  Returns {} entries only for derivable quantities."""
    return derive_ranges_from(spec.ev.constants, spec.module.name)


def derive_ranges_from(constants, module_name):
    """``derive_ranges`` without a SpecModel: the same table from a
    bare constants dict + module name.  This is what the packed
    frontier encoding (engine/pack.py, ISSUE 9) builds its per-plane
    bit budgets from — the ranges this pass VERIFIES are the single
    source of truth for field widths, so capacity tooling and codec
    ``plane_bounds`` can derive them without parsing a .tla module."""
    c = constants
    rng = {}

    def geti(name, default=None):
        v = c.get(name, default)
        return v if isinstance(v, int) and not isinstance(v, bool) \
            else None

    timer = geti("StartViewOnTimerLimit")
    restarts = geti("RestartEmptyLimit", 0)
    crashes = geti("CrashLimit", 0)
    values = c.get("Values")
    nvalues = len(values) if isinstance(values, frozenset) else None
    clients = geti("ClientCount", 1)
    replicas = geti("ReplicaCount")

    if timer is not None:
        extra = restarts or 0
        if module_name != "VSR":
            extra = 0          # only VSR's RestartEmpty re-mints views
        rng["view_number"] = (0, 1 + timer + extra)
    if nvalues is not None:
        rng["operation"] = (0, nvalues)
        rng["op_number"] = (0, nvalues)        # MAX_OPS = |Values|
        rng["commit_number"] = (0, nvalues)
        rng["request_number"] = (0, nvalues)
        # checkpoints cover committed prefixes: cp_number <= commit
        rng["cp_number"] = (0, nvalues)
        # dense log entry codes: value ids 1..|Values| plus CP06's
        # NoOp id |Values|+1 (cp06.py noop_id)
        rng["entry_code"] = (0, nvalues + 1)
    if clients is not None:
        rng["client_id"] = (0, clients)
    if replicas is not None:
        rng["replica_id"] = (0, replicas)
    if crashes is not None:
        rng["recovery_nonce"] = (0, 1 + crashes)
    return rng


def run(spec, report):
    rng = derive_ranges(spec)
    c = spec.ev.constants

    # generic int31 lane check: every derived range and every integer
    # constant must fit a signed 32-bit dense plane
    for name, (_lo, hi) in sorted(rng.items()):
        if hi >= INT31:
            report.add(PASS, SEV_ERROR, name,
                       f"derived range [0, {hi}] exceeds the int32 "
                       f"dense-plane width")
    for name, v in sorted(c.items()):
        if isinstance(v, int) and not isinstance(v, bool) and \
                abs(v) >= INT31:
            report.add(PASS, SEV_ERROR, name,
                       f"constant {v} does not fit an int32 lane")

    packed = FAMILY_PACKED.get(spec.module.name)
    if packed is None:
        report.add(PASS, SEV_INFO, spec.module.name,
                   "no registered packed layout for this module; "
                   "generic int32 checks only")
        return

    # dedicated plane-row budgets (AL05 suffix log, CP06 checkpoint
    # plane): the quantity must provably fit the MAX_OPS = |Values|
    # rows its dense plane allocates
    values = c.get("Values")
    nvalues = len(values) if isinstance(values, frozenset) else None
    for fld, qty, where in FAMILY_PLANES.get(spec.module.name, ()):
        if nvalues is None or qty not in rng:
            report.add(PASS, SEV_WARN, fld,
                       f"cannot derive the {fld} bound ({qty} vs the "
                       f"MAX_OPS = |Values| plane rows) from the cfg "
                       f"constants; {where} is unverified")
            continue
        lo, hi = rng[qty]
        if hi > nvalues:
            report.add(PASS, SEV_ERROR, fld,
                       f"derived {qty} range [{lo}, {hi}] exceeds the "
                       f"{nvalues}-row plane in {where}; rows would "
                       f"clip silently")
        else:
            slack = "exactly" if hi == nvalues else \
                f"(headroom {nvalues - hi})"
            report.add(PASS, SEV_INFO, fld,
                       f"{qty} range [{lo}, {hi}] fits the "
                       f"{nvalues}-row plane in {where} {slack}")

    for fld, limit, where in packed:
        if fld not in rng:
            report.add(PASS, SEV_WARN, fld,
                       f"cannot derive a static bound for {fld!r} from "
                       f"the cfg constants; packed width {limit} in "
                       f"{where} is unverified")
            continue
        lo, hi = rng[fld]
        if hi >= limit:
            report.add(PASS, SEV_ERROR, fld,
                       f"derived range [{lo}, {hi}] overflows the "
                       f"{limit.bit_length() - 1}-bit field in {where} "
                       f"(max representable {limit - 1}); values would "
                       f"wrap silently")
        else:
            report.add(PASS, SEV_INFO, fld,
                       f"range [{lo}, {hi}] fits {where} "
                       f"(headroom {limit - 1 - hi})")
