"""Pass 5 — kernel/IR drift detection.

The hand-written kernels (models/*_kernel.py) and the lowerer
(lower/compile.py) are two implementations of the same spec; the
kernels are also the differential oracle the lowerer is held to.  The
hazard this pass guards against is silent drift: a spec edit renames
or adds an action, the lowerer picks it up from the AST automatically,
and the hand kernel keeps exploring the OLD action set — every
differential test still passes on the states both can reach.

Cross-checks, per registered module:

* action list — the kernel's ``action_names`` must equal the Next
  disjunct names the spec model derives (a renamed/missing/extra
  action is an ERROR; an order difference only reorders lane ids and
  is a WARN);
* lane-binder domains — for every action whose top-level existential
  chain the IR extractor can lift (lower/ir.extract_action), the
  binder-domain product must equal the kernel's ``_lane_count``
  (a mismatch means the kernel enumerates a different bound-variable
  space than the spec declares: WARN, since hand kernels may
  legitimately over-enumerate and mask the excess with guards);
* state layout — the kernel's hashed key tables (REP_KEYS/MSG_KEYS/
  AUX_KEYS and, where present, GLOBAL_KEYS) must exactly cover the
  codec's ``zero_state`` planes: a plane the kernel does not hash is
  invisible to fingerprinting (ERROR), a key without a plane is a
  stale layout reference (ERROR);
* packed-frontier bounds (ISSUE 9) — the codec's ``plane_bounds``
  tables feed the engine/pack bit budgets, and the widths-pass range
  table is their single source of truth.  A codec width/layout edit
  that is not reflected in the bounds packs real values into too few
  bits and wraps silently, so the pass cross-checks: bound keys must
  name real ``zero_state`` planes (stale reference: ERROR),
  per-column bound arity must match the plane shape (ERROR, surfaced
  from build_pack_spec), the all-zero padding row and every encoded
  init state must round-trip the packed format EXACTLY (a wrap here
  is a bound that no longer covers the layout: ERROR).
"""

from __future__ import annotations

from ...core.values import TLAError
from ...lower.ir import extract_action
from ..report import SEV_ERROR, SEV_INFO, SEV_WARN

PASS = "drift"


def run(spec, report):
    from ...models import registry
    try:
        codec_cls, kern_cls = registry._resolve(spec.module.name)
    except KeyError:
        report.add(PASS, SEV_INFO, spec.module.name,
                   "no registered device kernel for this module; "
                   "nothing to cross-check")
        return
    try:
        codec = codec_cls(spec.ev.constants)
    except TLAError as e:
        report.add(PASS, SEV_WARN, spec.module.name,
                   f"dense layout refuses these constants ({e}); "
                   f"kernel cross-check skipped")
        return
    except Exception as e:       # noqa: BLE001
        # a non-TLAError here is either a real codec regression (must
        # stay loud — this pass IS the gate for it) or a spec that
        # merely shares a registered module's name; err on loud, with
        # the standard -lint=off / TPUVSR_LINT=off bypass for forks
        report.add(PASS, SEV_ERROR, spec.module.name,
                   f"dense layout construction failed "
                   f"({type(e).__name__}: {e}); drift cross-check "
                   f"could not run (TPUVSR_LINT=off bypasses if this "
                   f"spec only shares the module name)")
        return
    try:
        kern = kern_cls(codec,
                        perms=registry.value_perm_table(spec, codec))
    except Exception as e:       # noqa: BLE001
        # the codec ACCEPTED these constants, so this is almost
        # certainly a real kernel-side regression, not a name-shared
        # foreign spec — keep the corpus lint gate loud (ERROR)
        report.add(PASS, SEV_ERROR, spec.module.name,
                   f"kernel construction failed after its codec "
                   f"accepted the constants "
                   f"({type(e).__name__}: {e}); drift cross-check "
                   f"could not run")
        return
    check_drift(spec, codec, kern, report)
    check_pack_drift(spec, codec, report)
    check_bounds_drift(spec, codec, report)


def check_drift(spec, codec, kern, report):
    """Cross-check one (spec, codec, kernel) triple.  Split out from
    ``run`` so tests can drive it with a stub kernel."""
    spec_actions = [a.name for a in spec.actions]
    kern_actions = list(kern.action_names)

    missing = [n for n in spec_actions if n not in kern_actions]
    extra = [n for n in kern_actions if n not in spec_actions]
    for n in missing:
        report.add(PASS, SEV_ERROR, n,
                   "spec action has no kernel implementation (the "
                   "kernel's action list has drifted from the spec's "
                   "Next disjuncts)")
    for n in extra:
        report.add(PASS, SEV_ERROR, n,
                   "kernel implements an action the spec's Next does "
                   "not mention (renamed or removed in the spec)")
    if not missing and not extra and spec_actions != kern_actions:
        report.add(PASS, SEV_WARN, spec.module.name,
                   "kernel action order differs from the spec's Next "
                   "disjunct order (lane ids are permuted)")

    # lane-binder domains vs kernel lane counts
    shape = codec.shape
    dims = {"replicas": shape.R, "values": shape.V,
            "msgs": shape.MAX_MSGS, "subsets": 1 << shape.R,
            "tracker": shape.R, "intrange": shape.MAX_OPS + 1}
    for action in spec.actions:
        if action.name not in kern_actions:
            continue
        air = extract_action(action.name, action.expr)
        if not air.binders:
            continue               # nothing liftable to compare
        expected = 1
        for b in air.binders:
            expected *= dims[b.domain]
        got = kern._lane_count(action.name)
        if got != expected:
            doms = "x".join(b.domain for b in air.binders)
            report.add(PASS, SEV_WARN, action.name,
                       f"kernel enumerates {got} lanes but the spec's "
                       f"binder chain ({doms}) spans {expected} "
                       f"combinations — lane plan drift")

    # state-layout coverage: hashed keys vs dense planes
    keys = set()
    for attr in ("REP_KEYS", "MSG_KEYS", "AUX_KEYS", "GLOBAL_KEYS"):
        keys.update(getattr(kern, attr, ()))
    planes = set(codec.zero_state().keys())
    for k in sorted(planes - keys):
        report.add(PASS, SEV_ERROR, k,
                   "dense state plane is not covered by the kernel's "
                   "hashed key tables — the plane would be invisible "
                   "to fingerprint dedup")
    for k in sorted(keys - planes):
        report.add(PASS, SEV_ERROR, k,
                   "kernel key table names a plane the codec layout "
                   "does not allocate (stale layout reference)")


def check_pack_drift(spec, codec, report):
    """Packed-frontier bound drift (ISSUE 9 satellite).  Split out
    from ``run`` so tests can drive it with a deliberately-stale stub
    codec (the fixture: a codec width edit WITHOUT a widths-table /
    bounds edit must fail speclint, not wrap at runtime)."""
    import numpy as np

    if not hasattr(codec, "plane_bounds"):
        report.add(PASS, SEV_INFO, spec.module.name,
                   "codec declares no plane_bounds; the packed "
                   "frontier runs at ratio 1.0 (no bit budgets to "
                   "cross-check)")
        return
    from ...engine.pack import build_pack_spec
    from .widths import derive_ranges
    ranges = derive_ranges(spec)
    planes = set(codec.zero_state().keys())
    for k in sorted(set(codec.plane_bounds(ranges)) - planes):
        report.add(PASS, SEV_ERROR, k,
                   "plane_bounds names a plane the codec layout does "
                   "not allocate (stale packing reference)")
    try:
        pk = build_pack_spec(codec, ranges=ranges)
    except TLAError as e:
        report.add(PASS, SEV_ERROR, spec.module.name,
                   f"packing-spec construction failed ({e}) — the "
                   f"plane_bounds tables have drifted from the dense "
                   f"layout")
        return

    def roundtrip_errors(row, what):
        batch = {k: np.asarray(v)[None] for k, v in row.items()}
        rt = pk.unpack_np(pk.pack_np(batch))
        bad = sorted(k for k in batch
                     if not np.array_equal(batch[k], rt[k]))
        for k in bad:
            report.add(PASS, SEV_ERROR, k,
                       f"{what} does not round-trip the packed "
                       f"format (plane {k!r}: a value lies outside "
                       f"its declared bit budget and would wrap "
                       f"silently) — the codec layout has drifted "
                       f"from its plane_bounds / the widths table")
        return bad

    # the all-zero row is the padding every growth path re-packs;
    # a bound excluding 0 breaks pad_msgs/_grow_msgs invisibly
    zero = codec.zero_state()
    if roundtrip_errors({k: np.asarray(v, np.int32)
                         for k, v in zero.items()}, "the zero row"):
        return
    ok = 0
    for i, st in enumerate(spec.init_states()):
        if i >= 64:
            break                  # static smoke, not an enumeration
        if roundtrip_errors(codec.encode(st), f"init state {i}"):
            return
        ok += 1
    report.add(PASS, SEV_INFO, spec.module.name,
               f"packed layout {pk.packed_bytes} B/state "
               f"({pk.ratio:.2f}x vs dense); zero row and {ok} init "
               f"state(s) round-trip exactly")


def check_bounds_drift(spec, codec, report):
    """Bounds-tightened packing drift (ISSUE 13 satellite, extending
    the PR 9 pack-drift fixture): the widths table, the codec's
    ``plane_bounds`` and the bounds pass's tightened intervals must
    agree on ONE layout — a codec width edit that diverges from the
    shared range table shows up as a tightened round-trip failure
    here, at lint time, not as a silent wrap inside a ``-bounds on``
    run.  Checks: every encoded init state round-trips the TIGHTENED
    packing exactly (the reachable intervals over-approximate
    reachability, so init states are always inside them)."""
    import numpy as np

    if not hasattr(codec, "plane_bounds"):
        return
    from ...engine.pack import build_pack_spec
    from .bounds import analyze
    from .widths import derive_ranges
    facts = analyze(spec)
    tighten = facts.plane_tighten()
    if not tighten:
        return                      # untightened = pack-drift covered
    ranges = derive_ranges(spec)
    try:
        pk = build_pack_spec(codec, ranges=ranges, tighten=tighten)
    except TLAError as e:
        report.add(PASS, SEV_ERROR, spec.module.name,
                   f"bounds-tightened packing-spec construction "
                   f"failed ({e}) — the tightened intervals have "
                   f"drifted from the dense layout")
        return
    if pk is None:
        return
    bad = []
    for i, st in enumerate(spec.init_states()):
        if i >= 64:
            break
        row = codec.encode(st)
        batch = {k: np.asarray(v)[None] for k, v in row.items()}
        rt = pk.unpack_np(pk.pack_np(batch))
        bad = sorted(k for k in batch
                     if not np.array_equal(batch[k], rt[k]))
        if bad:
            for k in bad:
                report.add(PASS, SEV_ERROR, k,
                           f"init state {i} does not round-trip the "
                           f"bounds-TIGHTENED packing (plane {k!r}): "
                           f"the codec layout stores values outside "
                           f"the reachable interval the bounds pass "
                           f"derived — width tables have drifted")
            return
    report.add(PASS, SEV_INFO, spec.module.name,
               f"bounds-tightened packing ({pk.total_bits} bits/state "
               f"vs declared) round-trips every init state exactly")
