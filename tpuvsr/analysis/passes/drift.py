"""Pass 5 — kernel/IR drift detection.

The hand-written kernels (models/*_kernel.py) and the lowerer
(lower/compile.py) are two implementations of the same spec; the
kernels are also the differential oracle the lowerer is held to.  The
hazard this pass guards against is silent drift: a spec edit renames
or adds an action, the lowerer picks it up from the AST automatically,
and the hand kernel keeps exploring the OLD action set — every
differential test still passes on the states both can reach.

Cross-checks, per registered module:

* action list — the kernel's ``action_names`` must equal the Next
  disjunct names the spec model derives (a renamed/missing/extra
  action is an ERROR; an order difference only reorders lane ids and
  is a WARN);
* lane-binder domains — for every action whose top-level existential
  chain the IR extractor can lift (lower/ir.extract_action), the
  binder-domain product must equal the kernel's ``_lane_count``
  (a mismatch means the kernel enumerates a different bound-variable
  space than the spec declares: WARN, since hand kernels may
  legitimately over-enumerate and mask the excess with guards);
* state layout — the kernel's hashed key tables (REP_KEYS/MSG_KEYS/
  AUX_KEYS and, where present, GLOBAL_KEYS) must exactly cover the
  codec's ``zero_state`` planes: a plane the kernel does not hash is
  invisible to fingerprinting (ERROR), a key without a plane is a
  stale layout reference (ERROR).
"""

from __future__ import annotations

from ...core.values import TLAError
from ...lower.ir import extract_action
from ..report import SEV_ERROR, SEV_INFO, SEV_WARN

PASS = "drift"


def run(spec, report):
    from ...models import registry
    try:
        codec_cls, kern_cls = registry._resolve(spec.module.name)
    except KeyError:
        report.add(PASS, SEV_INFO, spec.module.name,
                   "no registered device kernel for this module; "
                   "nothing to cross-check")
        return
    try:
        codec = codec_cls(spec.ev.constants)
    except TLAError as e:
        report.add(PASS, SEV_WARN, spec.module.name,
                   f"dense layout refuses these constants ({e}); "
                   f"kernel cross-check skipped")
        return
    except Exception as e:       # noqa: BLE001
        # a non-TLAError here is either a real codec regression (must
        # stay loud — this pass IS the gate for it) or a spec that
        # merely shares a registered module's name; err on loud, with
        # the standard -lint=off / TPUVSR_LINT=off bypass for forks
        report.add(PASS, SEV_ERROR, spec.module.name,
                   f"dense layout construction failed "
                   f"({type(e).__name__}: {e}); drift cross-check "
                   f"could not run (TPUVSR_LINT=off bypasses if this "
                   f"spec only shares the module name)")
        return
    try:
        kern = kern_cls(codec,
                        perms=registry.value_perm_table(spec, codec))
    except Exception as e:       # noqa: BLE001
        # the codec ACCEPTED these constants, so this is almost
        # certainly a real kernel-side regression, not a name-shared
        # foreign spec — keep the corpus lint gate loud (ERROR)
        report.add(PASS, SEV_ERROR, spec.module.name,
                   f"kernel construction failed after its codec "
                   f"accepted the constants "
                   f"({type(e).__name__}: {e}); drift cross-check "
                   f"could not run")
        return
    check_drift(spec, codec, kern, report)


def check_drift(spec, codec, kern, report):
    """Cross-check one (spec, codec, kernel) triple.  Split out from
    ``run`` so tests can drive it with a stub kernel."""
    spec_actions = [a.name for a in spec.actions]
    kern_actions = list(kern.action_names)

    missing = [n for n in spec_actions if n not in kern_actions]
    extra = [n for n in kern_actions if n not in spec_actions]
    for n in missing:
        report.add(PASS, SEV_ERROR, n,
                   "spec action has no kernel implementation (the "
                   "kernel's action list has drifted from the spec's "
                   "Next disjuncts)")
    for n in extra:
        report.add(PASS, SEV_ERROR, n,
                   "kernel implements an action the spec's Next does "
                   "not mention (renamed or removed in the spec)")
    if not missing and not extra and spec_actions != kern_actions:
        report.add(PASS, SEV_WARN, spec.module.name,
                   "kernel action order differs from the spec's Next "
                   "disjunct order (lane ids are permuted)")

    # lane-binder domains vs kernel lane counts
    shape = codec.shape
    dims = {"replicas": shape.R, "values": shape.V,
            "msgs": shape.MAX_MSGS, "subsets": 1 << shape.R,
            "tracker": shape.R, "intrange": shape.MAX_OPS + 1}
    for action in spec.actions:
        if action.name not in kern_actions:
            continue
        air = extract_action(action.name, action.expr)
        if not air.binders:
            continue               # nothing liftable to compare
        expected = 1
        for b in air.binders:
            expected *= dims[b.domain]
        got = kern._lane_count(action.name)
        if got != expected:
            doms = "x".join(b.domain for b in air.binders)
            report.add(PASS, SEV_WARN, action.name,
                       f"kernel enumerates {got} lanes but the spec's "
                       f"binder chain ({doms}) spans {expected} "
                       f"combinations — lane plan drift")

    # state-layout coverage: hashed keys vs dense planes
    keys = set()
    for attr in ("REP_KEYS", "MSG_KEYS", "AUX_KEYS", "GLOBAL_KEYS"):
        keys.update(getattr(kern, attr, ()))
    planes = set(codec.zero_state().keys())
    for k in sorted(planes - keys):
        report.add(PASS, SEV_ERROR, k,
                   "dense state plane is not covered by the kernel's "
                   "hashed key tables — the plane would be invisible "
                   "to fingerprint dedup")
    for k in sorted(keys - planes):
        report.add(PASS, SEV_ERROR, k,
                   "kernel key table names a plane the codec layout "
                   "does not allocate (stale layout reference)")
