"""Finding/report model for the speclint static analyzer.

A lint run produces one ``LintReport`` per bound spec: an ordered list
of ``Finding``s, each attributed to the pass that raised it, with a
TLC-operator-level subject (action/invariant/variable name) so the
report reads like a compiler diagnostic, not a stack trace.

Exit-code contract (documented in README "Static analysis"):

  0   no error-severity findings (warnings/info allowed)
  1   at least one error-severity finding
  2   usage error (bad flags — raised by argparse, not this module)

The engine pre-flight path wraps an erroring report in ``LintError``
(a ``TLAError`` subclass, so existing CLI/engine error handling treats
a lint abort like any other refused-to-run condition).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from ..core.values import TLAError

SEV_ERROR = "error"
SEV_WARN = "warning"
SEV_INFO = "info"

_SEV_RANK = {SEV_ERROR: 0, SEV_WARN: 1, SEV_INFO: 2}


@dataclass
class Finding:
    passname: str        # which analyzer pass raised it
    severity: str        # SEV_ERROR | SEV_WARN | SEV_INFO
    subject: str         # action/invariant/variable the finding is about
    message: str

    def to_dict(self):
        return {"pass": self.passname, "severity": self.severity,
                "subject": self.subject, "message": self.message}

    def __str__(self):
        return (f"{self.severity:>7}  [{self.passname}] "
                f"{self.subject}: {self.message}")


@dataclass
class LintReport:
    module: str = ""
    findings: list = field(default_factory=list)
    passes_run: list = field(default_factory=list)
    # structured per-pass sections beyond findings (ISSUE 13: the
    # bounds pass attaches its facts under extras["bounds"] so
    # `-lint -json` surfaces intervals/dead actions/state_bound)
    extras: dict = field(default_factory=dict)

    def add(self, passname, severity, subject, message):
        self.findings.append(Finding(passname, severity, subject, message))

    def by_severity(self, severity):
        return [f for f in self.findings if f.severity == severity]

    @property
    def errors(self):
        return self.by_severity(SEV_ERROR)

    @property
    def warnings(self):
        return self.by_severity(SEV_WARN)

    @property
    def ok(self):
        return not self.errors

    @property
    def exit_code(self):
        return 0 if self.ok else 1

    def to_dict(self):
        out = {"module": self.module, "ok": self.ok,
               "passes": list(self.passes_run),
               "errors": len(self.errors),
               "warnings": len(self.warnings),
               "findings": [f.to_dict() for f in self.findings]}
        out.update(self.extras)
        return out

    def to_json(self):
        return json.dumps(self.to_dict())

    def render(self):
        """Human-readable multi-line report (severity-sorted)."""
        lines = [f"speclint: module {self.module} — "
                 f"{len(self.errors)} error(s), "
                 f"{len(self.warnings)} warning(s), "
                 f"passes: {', '.join(self.passes_run)}"]
        for f in sorted(self.findings,
                        key=lambda f: _SEV_RANK.get(f.severity, 3)):
            lines.append(str(f))
        return "\n".join(lines)


class LintError(TLAError):
    """Raised by the engine pre-flight when the analyzer finds
    error-severity defects; carries the full report."""

    def __init__(self, report: LintReport):
        self.report = report
        errs = "; ".join(f"[{f.passname}] {f.subject}: {f.message}"
                         for f in report.errors)
        super().__init__(
            f"speclint pre-flight failed for module {report.module} "
            f"({len(report.errors)} error(s)): {errs} — rerun with "
            f"-lint for the full report, or -lint=off / TPUVSR_LINT=off "
            f"to bypass the gate")
