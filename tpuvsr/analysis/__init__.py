"""speclint — static analysis over the frontend AST, the guarded-
command IR, and the dense kernel layouts, gating every checking run.

The reference corpus's only "type system" is TLC failing hours into a
run; the TPU port adds a second hazard the reference never had: packed
narrow-dtype layouts and hand-written kernels that can silently drift
from the lowered spec semantics.  This package proves the structural
properties that are provable BEFORE dispatch:

  frames    every state variable framed in every action (pass 1)
  widths    cfg-derived value ranges fit the packed bit-widths (pass 2)
  vacuity   dead actions / vacuous invariants under the cfg (pass 3)
  symmetry  SYMMETRY perms are structural automorphisms (pass 4)
  drift     hand kernel vs lowerer-derived ActionIR divergence (pass 5)
  bounds    symbolic interval pre-pass (pass 6, ISSUE 13): reachable
            per-variable intervals, statically dead actions, fanout
            and state-space upper bounds — FACTS the engines consume
            (tightened packing, pruned lane tables, exact expansion
            caps, service admission), not just properties they check
  independence  static action-independence relation (pass 7, ISSUE
            16): column-refined read/write access sets, the n x n
            independence matrix, invariant visibility and monotone
            progress witnesses — the facts behind the engines'
            ample-set partial-order reduction (``-por``,
            engine/por.py); unattributable actions poison to
            dependent-with-all, mirroring bounds' refusal discipline

Entry points:

* ``run_lint(spec)`` — full report (CLI ``-lint``,
  scripts/lint_corpus.py);
* ``preflight(spec)`` — the engine gate: all seven passes (the drift
  kernel cross-check became cheap once the key tables moved to class
  attributes; the bounds fixpoint and independence matrix are
  pure-AST and cached), raises ``LintError`` on error-severity
  findings, caches per spec object, honors ``TPUVSR_LINT=off`` (the
  CLI's ``-lint=off``).
"""

from __future__ import annotations

import os

from .passes import PASS_ORDER, PASSES, PREFLIGHT_PASSES
from .report import (Finding, LintError, LintReport, SEV_ERROR, SEV_INFO,
                     SEV_WARN)

__all__ = ["run_lint", "preflight", "lint_enabled", "Finding",
           "LintError", "LintReport", "SEV_ERROR", "SEV_WARN",
           "SEV_INFO", "PASS_ORDER", "PREFLIGHT_PASSES"]


def run_lint(spec, passes=None) -> LintReport:
    """Run the requested passes (default: all seven, in canonical
    order) over a bound spec and return the report."""
    report = LintReport(module=spec.module.name)
    for name in (passes if passes is not None else PASS_ORDER):
        PASSES[name](spec, report)
        report.passes_run.append(name)
    return report


def lint_enabled() -> bool:
    return os.environ.get("TPUVSR_LINT", "").lower() not in (
        "off", "0", "false", "no")


def preflight(spec, log=None):
    """Fail-fast gate the engines call before dispatch.

    Runs all seven passes (including the kernel drift cross-check) once
    per spec object; raises ``LintError`` if any error-severity finding
    survives.  Returns the report (or None when disabled via
    TPUVSR_LINT=off)."""
    if not lint_enabled():
        return None
    cached = getattr(spec, "_speclint_report", None)
    if cached is not None:
        if not cached.ok:
            raise LintError(cached)
        return cached
    report = run_lint(spec, passes=PREFLIGHT_PASSES)
    spec._speclint_report = report
    if log is not None:
        for f in report.warnings:
            log(f"speclint: {f}")
    if not report.ok:
        raise LintError(report)
    return report
