"""NVMe/disk spill tier for the paged engine's host frontier pages
(ISSUE 11, the CAPACITY.md mitigation-2 ladder).

The paged engine (engine/paged_bfs.py) already tiers the frontier out
of HBM into host RAM — which prices ~189 M packed defect-layout states
on a 125 GB host (CAPACITY.md).  TLC solved the same wall with a
disk-backed state queue and burned 500 GB on the reference corpus
(arxiv 2211.07216 frames that as the bound to beat); this module adds
the equivalent third rung: when a level's accumulated host pages
exceed a RAM budget, whole pages are flushed to append-only level
files on disk and re-read sequentially when the next level pages them
through the device, turning the host-RAM ceiling into a disk-priced
10^9-state ceiling.

Design:

* one :class:`SpillTier` per FRONTIER LEVEL — the paged engine's
  drains append blocks (packed ``[n, words]`` uint32 rows, or dense
  plane dicts when packing is off) in commit order;
* an in-RAM page index only: ``(path, rows)`` per flushed file plus
  the un-flushed RAM tail — the tier never holds more than
  ``ram_rows`` resident rows (plus one in-flight drain block);
* level files are append-only and immutable once written
  (``L<level>_<seq>.npz``); the consumed level's tier is dropped
  (files deleted) once the next level is assembled, so steady-state
  disk usage is two levels' worth of packed rows;
* reads are sequential block gathers (``block(start, n)``) matching
  the chunk-in transfer pattern, plus ``row(i)`` random access for
  violation/deadlock parent materialization;
* ``map_pages`` rewrites every page through a transform — the
  MAX_MSGS bag-growth re-pack rides it;
* checkpoints store DENSE planes regardless (the engine-agnostic
  interchange format), so a resume re-packs and re-spills under the
  resuming run's own budget.  Snapshot WRITES stream (ISSUE 13
  satellite — the PR 11 residual): ``save_checkpoint`` accepts a
  block iterator (``frontier_blocks``) and the paged engine feeds it
  the tier's pages one at a time (``PagedBFS._front_dense_blocks``),
  so peak residency during a checkpoint is one page, not the dense
  frontier; ``load_checkpoint`` reassembles the chunked payload
  transparently and a resume re-spills past the RAM budget as before.

The journal records each disk flush as a ``spill`` event with
``tier: "disk"`` (device->host RAM drains carry no ``tier`` key), and
the engine gauges cumulative ``spill_tier_bytes``.
"""

from __future__ import annotations

import glob
import os

import numpy as np


def _block_rows(block):
    if isinstance(block, dict):
        k = next(iter(block))
        return int(block[k].shape[0])
    return int(block.shape[0])


def _concat(blocks):
    if isinstance(blocks[0], dict):
        return {k: np.concatenate([b[k] for b in blocks])
                for k in blocks[0]}
    return np.concatenate(blocks)


def _slice(block, lo, hi):
    if isinstance(block, dict):
        return {k: v[lo:hi] for k, v in block.items()}
    return block[lo:hi]


class SpillTier:
    """Append-only disk-backed row store for one frontier level."""

    def __init__(self, dirpath, level, ram_rows, obs=None, depth=None):
        self.dir = dirpath
        self.level = int(level)
        self.ram_rows = max(1, int(ram_rows))
        self._ram = []           # un-flushed blocks, in append order
        self._ram_count = 0
        self._pages = []         # [(path, rows)], flush order
        self._seq = 0
        self.rows = 0
        self.disk_bytes = 0      # cumulative bytes written to disk
        self._obs = obs
        self._depth = depth if depth is not None else level
        self._last = None        # (path, data) — one-page read cache
        os.makedirs(dirpath, exist_ok=True)
        # a killed run may have left THIS level's files behind; the
        # resumed run can flush fewer/differently-sized pages under
        # the same names, so stale leftovers would leak past drop()
        # forever — reclaim them up front (the tier owns its dir)
        for stale in glob.glob(os.path.join(
                dirpath, f"L{self.level:05d}_*.npz")):
            try:
                os.unlink(stale)
            except OSError:
                pass

    # -- write side ----------------------------------------------------
    def append(self, block):
        n = _block_rows(block)
        if n == 0:
            return
        self._ram.append(block)
        self._ram_count += n
        self.rows += n
        if self._ram_count > self.ram_rows:
            self._flush()

    def _flush(self):
        if not self._ram_count:
            return
        block = _concat(self._ram)
        path = os.path.join(self.dir,
                            f"L{self.level:05d}_{self._seq:05d}.npz")
        self._seq += 1
        with open(path, "wb") as f:
            if isinstance(block, dict):
                np.savez(f, **block)
            else:
                np.savez(f, rows=block)
            f.flush()
            os.fsync(f.fileno())
        nbytes = os.path.getsize(path)
        self._pages.append((path, self._ram_count))
        self.disk_bytes += nbytes
        if self._obs is not None:
            self._obs.spill(self._depth, self._ram_count, nbytes,
                            tier="disk")
        self._ram = []
        self._ram_count = 0

    # -- read side -----------------------------------------------------
    def _load(self, path):
        # one-page cache: the chunk loop's reads are monotonic, so a
        # page overlapping several chunks would otherwise be re-read
        # (and re-decoded) once per chunk instead of once per level
        if self._last is not None and self._last[0] == path:
            return self._last[1]
        with np.load(path, allow_pickle=False) as z:
            if z.files == ["rows"]:
                data = z["rows"]
            else:
                data = {k: z[k] for k in z.files}
        self._last = (path, data)
        return data

    def _iter_pages(self):
        """Yield (start_row, rows, loader) over disk pages then the
        RAM tail, in global row order."""
        pos = 0
        for path, n in self._pages:
            yield pos, n, (lambda p=path: self._load(p))
            pos += n
        for b in self._ram:
            n = _block_rows(b)
            yield pos, n, (lambda b=b: b)
            pos += n

    def block(self, start, n):
        """Rows [start, start+n) assembled across page boundaries."""
        assert 0 <= start and start + n <= self.rows
        parts = []
        for pos, pn, load in self._iter_pages():
            if pos + pn <= start or pos >= start + n:
                continue
            data = load()
            lo = max(0, start - pos)
            hi = min(pn, start + n - pos)
            parts.append(_slice(data, lo, hi))
        return _concat(parts)

    def row(self, i):
        return self.block(int(i), 1)

    def all_rows(self):
        if self.rows == 0:
            return _concat([b for b in self._ram]) if self._ram else None
        return self.block(0, self.rows)

    # -- maintenance ---------------------------------------------------
    def map_pages(self, fn):
        """Rewrite every page (disk and RAM) through ``fn(block) ->
        block`` — the bag-growth re-pack path.  Row counts must be
        preserved."""
        new_pages = []
        for path, n in self._pages:
            block = fn(self._load(path))
            assert _block_rows(block) == n
            self.disk_bytes -= os.path.getsize(path)
            with open(path, "wb") as f:
                if isinstance(block, dict):
                    np.savez(f, **block)
                else:
                    np.savez(f, rows=block)
                f.flush()
                os.fsync(f.fileno())
            self.disk_bytes += os.path.getsize(path)
            new_pages.append((path, n))
        self._pages = new_pages
        self._ram = [fn(b) for b in self._ram]
        self._last = None

    def drop(self):
        """Delete this level's files (the level has been consumed)."""
        for path, _n in self._pages:
            try:
                os.unlink(path)
            except OSError:
                pass
        self._pages = []
        self._ram = []
        self._ram_count = 0
        self._last = None


class EdgeCSR:
    """Incremental host CSR builder for the streamed behavior graph
    (ISSUE 15).  The level kernel's edge-emission commit drains
    ``(src gid, action id, dst gid)`` triples here in COMMIT ORDER;
    ``finalize(n)`` assembles the CSR arrays ``(indptr[n+1], aid[m],
    tid[m])`` the fair-SCC machinery consumes, preserving the drained
    order within each source's segment (the documented bit-identity
    contract: streamed vs two-pass CSRs agree modulo edge order within
    a (src, level) segment).

    Two storage modes: plain RAM blocks, or — past a RAM budget — the
    :class:`SpillTier` disk tier (append-only edge page files under
    ``<spill_dir>/edges``), so a 10^8-edge graph's triples never
    compete with the frontier for host RAM during the BFS.  A per-src
    degree count accumulates as blocks arrive, so ``finalize`` is two
    sequential passes (prefix-sum the counts, then scatter each block
    into its cursor positions) with no global sort."""

    #: bytes one edge row costs on the device append buffer
    ROW_BYTES = 12          # 3 x int32

    def __init__(self, spill_dir=None, ram_rows=None, obs=None):
        self._tier = None
        self._blocks = []
        if spill_dir:
            self._tier = SpillTier(os.path.join(spill_dir, "edges"),
                                   0, ram_rows or (1 << 20), obs=obs)
        self._counts = np.zeros(1024, np.int64)
        self.rows = 0

    def append(self, src, aid, dst):
        src = np.ascontiguousarray(src, np.int64)
        n = int(src.shape[0])
        if n == 0:
            return
        hi = int(src.max()) + 1
        if hi > self._counts.shape[0]:
            grown = np.zeros(max(hi, 2 * self._counts.shape[0]),
                             np.int64)
            grown[:self._counts.shape[0]] = self._counts
            self._counts = grown
        self._counts[:hi] += np.bincount(src, minlength=hi)
        block = {"src": src,
                 "aid": np.ascontiguousarray(aid, np.int32),
                 "dst": np.ascontiguousarray(dst, np.int32)}
        if self._tier is not None:
            self._tier.append(block)
        else:
            self._blocks.append(block)
        self.rows += n

    def seed(self, block):
        """Re-seed from a checkpoint's reassembled edge payload (one
        dict of concatenated src/aid/dst arrays): the resumed stream
        continues in the same order, so the final CSR is bit-identical
        to an uninterrupted run's."""
        self.append(block["src"], block["aid"], block["dst"])

    def blocks(self):
        """Iterator of the accumulated blocks in drain order — the
        checkpoint writer's streaming input (one page resident at a
        time on the disk tier)."""
        if self._tier is not None:
            for _pos, _n, load in self._tier._iter_pages():
                yield load()
        else:
            yield from self._blocks

    def finalize(self, n):
        """Assemble ``(indptr, aid, tid)`` over node ids ``0..n-1``."""
        assert int(self._counts[n:].sum()) == 0, \
            "edge stream names a src gid beyond the state count"
        if self._counts.shape[0] < n:
            # counts only grow to the highest EDGE-EMITTING src gid —
            # trailing terminal states (no enabled action) are legal
            # zero-degree nodes, so pad rather than crash
            grown = np.zeros(n, np.int64)
            grown[:self._counts.shape[0]] = self._counts
            self._counts = grown
        indptr = np.zeros(n + 1, np.int64)
        np.cumsum(self._counts[:n], out=indptr[1:])
        assert int(indptr[-1]) == self.rows
        aid = np.empty(self.rows, np.int32)
        tid = np.empty(self.rows, np.int32)
        cursor = indptr[:-1].copy()
        for block in self.blocks():
            s = np.asarray(block["src"], np.int64)
            order = np.argsort(s, kind="stable")
            ss = s[order]
            first = np.concatenate([[True], ss[1:] != ss[:-1]])
            starts = np.flatnonzero(first)
            runs = np.diff(np.concatenate([starts, [ss.shape[0]]]))
            rank = np.arange(ss.shape[0]) - np.repeat(starts, runs)
            pos = cursor[ss] + rank
            aid[pos] = np.asarray(block["aid"], np.int32)[order]
            tid[pos] = np.asarray(block["dst"], np.int32)[order]
            cursor[ss[starts]] += runs
        assert (cursor == indptr[1:]).all()
        return indptr, aid, tid

    def drop(self):
        if self._tier is not None:
            self._tier.drop()
        self._blocks = []
