"""Engine-side consumption of the independence pass — ample-set
partial-order reduction (ISSUE 16).

``analysis/passes/independence.py`` computes the facts; this module is
the seam through which the engines trust them:

* :func:`resolve_por` — the one policy switch, mirroring
  ``bounds.resolve_bounds``: ``"auto"`` consumes the facts iff the
  speclint gate is live AND no soundness blocker applies; forcing
  ``"on"`` under the gate off or under a blocker is a loud
  ``TLAError`` (the CLI rejects the flag combinations at parse time;
  this guards library callers).  Blockers: temporal properties
  (PROPERTY — the reduced graph does not preserve LTL without
  visibility conditions far beyond invariants), ``-edges on`` (the
  behavior graph must cover the FULL next-state relation), and
  non-fused commit modes (the ample filter lives in the fused
  commit's staging queue).  Engine constructors default ``por="off"``
  — unlike bounds tightening, the reduction legitimately SHRINKS
  distinct-state counts, so library callers opt in; the CLI's
  ``-por`` defaults to auto for real checking runs.
* :class:`PORFilter` — the device-resident ample tables bound to one
  kernel.

Soundness (the classic ample-set conditions, README "Partial-order
reduction"):

* C0/C1 (persistence): an action is *eligible* only when the facts
  matrix shows it independent of EVERY other kernel action.  That is
  deliberately stronger than "independent of every currently enabled
  action": independence of the enabled set alone is not persistent —
  a currently-disabled conflicting action can become enabled along a
  path of independent actions and then race the ample action.  Full-
  matrix independence closes that hole statically: nothing can ever
  write an eligible action's read set, so its enabled LANE SET is
  constant along every path that does not fire it, and all its
  enabled lanes form a persistent set.
* C2 (invisibility): eligible actions must not write any cfg
  invariant's read set, so skipping interleavings cannot change any
  invariant verdict.  Deadlock detection needs no visibility
  condition (persistent sets preserve deadlocks) and the enabled-any
  reduction in the engines runs on the UNMASKED guard matrix.
* C3 (no ignoring): enforced by the BFS level structure.  A state
  takes the ample shortcut only if its ample successors are FRESH —
  not present in the visited set as of the current level (the FPSet
  gids column stores a level marker per fingerprint; ``marker <=
  frontier level`` means old).  Any cycle in the reduced graph
  contains a state whose cycle successor was discovered at the same
  or an earlier level, so that state refused the shortcut and was
  fully expanded.  States committed *while generating the next level*
  carry ``level+1`` markers and still count as fresh, which makes the
  check timing-immune: pause/re-entry after a mid-level FPSet growth
  and kill/resume from a level-boundary snapshot (markers rebuilt as
  zeros — every stored fingerprint is old at a boundary) reproduce
  bit-identical decisions.
* Sharded C3: the owner-partitioned FPSet cannot probe successor
  freshness locally, so the sharded engine uses a fully static
  proviso instead — only eligible actions with a *monotone progress
  witness* (facts: a bounded variable every firing strictly
  increases) may shortcut.  Because every eligible action is
  independent of every other, no action writes another eligible
  action's witness, so the summed witnesses strictly increase along
  any all-ample path; bounded above, such a path is finite and no
  cycle can consist of ample shortcuts only.  The sharded reduction
  is therefore weaker (counts may shrink less than the single-device
  engines') but deterministic and collective-free.

Trace honesty: with a reduction active, a violation's first-found
witness trace can differ from the unreduced run's (the verdict cannot
— some violating state is always preserved).  The oracles in
``tests/test_por.py`` assert verdict/deadlock identity everywhere and
bit-identical counts wherever the filter is inert.

Checkpoint seam: engines record the facts digest in snapshot
manifests under ``por`` and refuse to resume under a flipped ``-por``
or changed facts (mirroring pack/canon/bounds).
"""

from __future__ import annotations

import numpy as np

from ..core.values import TLAError


def resolve_por(spec, req="off", *, temporal=False, edges=False,
                commit="fused"):
    """The engines' POR switch -> :class:`IndependenceFacts` or None.

    ``req``: ``"auto"`` (on iff the speclint gate is live and no
    blocker applies) | True/"on" (forced; loud error on gate-off or
    blocker) | False/"off"."""
    if req is None or req is False or req == "off":
        return None
    if req not in (True, "on", "auto"):
        raise TLAError(f"por must be 'auto', 'on' or 'off' (got {req!r})")
    forced = req is True or req == "on"
    from ..analysis import lint_enabled
    if not lint_enabled():
        if forced:
            raise TLAError(
                "por=on requires the speclint gate: TPUVSR_LINT=off / "
                "-lint=off disables the static independence analysis "
                "the ample-set filter would trust (drop -por on or "
                "re-enable lint)")
        return None
    blockers = []
    if temporal:
        blockers.append("temporal properties (PROPERTY)")
    if edges:
        blockers.append("-edges on (the behavior graph must cover the "
                        "full next-state relation)")
    if commit != "fused":
        blockers.append(f"commit={commit!r} (the ample filter lives in "
                        f"the fused commit)")
    if blockers:
        if forced:
            raise TLAError(
                f"por=on is unsound under {'; '.join(blockers)} — "
                f"partial-order reduction preserves invariant and "
                f"deadlock verdicts only (drop -por on)")
        return None
    from ..analysis.passes.independence import analyze
    return analyze(spec)


class PORFilter:
    """Ample-set tables for one kernel binding.

    ``amat[a, b]`` is True when expanding only action ``a`` is safe in
    the presence of an enabled ``b`` — rows of ineligible actions are
    all-False (any enabled action, including ``a`` itself, vetoes
    them), so the per-tile-row conflict gather
    ``enabled @ ~amat.T > 0`` rejects them without a separate
    eligibility mask."""

    def __init__(self, facts, kern, *, sharded=False):
        names = list(kern.action_names)
        n = len(names)
        fidx = {nm: i for i, nm in enumerate(facts.action_names)}
        amat = np.zeros((n, n), bool)
        eligible = np.zeros(n, bool)
        for a, nm in enumerate(names):
            i = fidx.get(nm)
            if i is None or nm in facts.poisoned:
                continue       # kernel action unknown to the facts:
                #                dependent-with-all (sound)
            if facts.visible.get(nm, True):
                continue       # C2: writes an invariant's read set
            if facts.inv_refused:
                continue
            if sharded and not facts.monotone.get(nm):
                continue       # sharded C3 needs the static witness
            row_ok = True
            for other in names:
                if other == nm:
                    continue
                j = fidx.get(other)
                if j is None or not facts.matrix[i][j]:
                    row_ok = False
                    break
            if not row_ok:
                continue
            eligible[a] = True
            for b, other in enumerate(names):
                amat[a, b] = (b == a) or facts.matrix[i][fidx[other]]
        self.facts = facts
        self.sharded = bool(sharded)
        self.eligible = eligible
        self.amat = amat
        self.n_actions = n
        self.n_eligible = int(eligible.sum())
        self.any_eligible = bool(eligible.any())
        self.digest = facts.digest

    def journal_doc(self):
        """The ``por`` object journaled on run_start (key-set parity
        across engines; ``None`` journaled when POR is off)."""
        return {"digest": self.digest,
                "actions": self.n_actions,
                "eligible_actions": self.n_eligible,
                "sharded_proviso": self.sharded,
                "independence": self.facts.journal_doc()}

    def manifest(self):
        """The checkpoint-manifest ``por`` entry."""
        return {"digest": self.digest,
                "eligible_actions": self.n_eligible,
                "sharded_proviso": self.sharded}
