"""Spec model: binds a parsed module to a .cfg, decomposes SPECIFICATION
formulas, and exposes the checkable interface (init states, per-action
successor enumeration, invariants, VIEW projection, symmetry).

Replaces TLC's config binder + ModelConfig layer (SURVEY.md §1.2): INIT/
NEXT or SPECIFICATION (``Spec == Init /\\ [][Next]_vars /\\ WF_vars(Next)``
at VSR.tla:968 and the LivenessSpec split at A01:808-809), VIEW
(VSR.cfg:29), SYMMETRY (VSR.cfg:31), INVARIANT/PROPERTY registration.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..core.values import (FnVal, TLAError, permute_value, value_key)
from ..frontend.cfg import CfgModel
from ..frontend.tla_ast import Module
from ..interp.actions import ActionEnumerator
from ..interp.evalr import EMPTY_ENV, EvalCtx, Evaluator


@dataclass
class Action:
    name: str
    expr: tuple
    location: str   # "line a, col b to line c, col d of module M"


class SpecModel:
    def __init__(self, module: Module, cfg: CfgModel):
        self.module = module
        self.cfg = cfg
        missing = [c for c in module.constants if c not in cfg.constants]
        if missing:
            raise TLAError(f"cfg leaves constants unbound: {missing}")
        self.ev = Evaluator(module, cfg.constants)
        self.enum = ActionEnumerator(self.ev)

        self.init_name = cfg.init
        self.next_name = cfg.next
        self.fairness = []          # list of (subscript_expr, action_expr)
        self.temporal_props = list(cfg.properties)
        if cfg.specification:
            self._decompose_spec(cfg.specification)
        if not self.init_name or not self.next_name:
            raise TLAError("cfg must provide INIT/NEXT or SPECIFICATION")

        self.actions = self._action_list()
        self.invariants = [(name, self.module.defs[name])
                           for name in cfg.invariants]
        self.view_def = module.defs.get(cfg.view) if cfg.view else None
        self.symmetry_perms = self._symmetry_perms(cfg.symmetry)

    # ------------------------------------------------------------------
    def _decompose_spec(self, spec_name: str):
        d = self.module.defs.get(spec_name)
        if d is None:
            raise TLAError(f"SPECIFICATION {spec_name} not defined")
        conjuncts = []

        def flatten(e):
            if e[0] == "and":
                for x in e[1]:
                    flatten(x)
            else:
                conjuncts.append(e)
        flatten(d.body)

        def contains_temporal(e):
            if isinstance(e, list):
                return any(contains_temporal(x) for x in e)
            if not isinstance(e, tuple):
                return False
            if e and isinstance(e[0], str) and e[0] in (
                    "boxaction", "wf", "sf", "box", "diamond"):
                return True
            if e and e[0] == "binop" and e[1] == "leadsto":
                return True
            return any(contains_temporal(x) for x in e
                       if isinstance(x, (tuple, list)))

        for c in conjuncts:
            if c[0] == "boxaction":
                act, _sub = c[1], c[2]
                if act[0] == "id":
                    self.next_name = act[1]
                else:
                    self.next_name = "__Next__"
                    self.module.defs["__Next__"] = _synth_def("__Next__", act, self.module.name)
            elif c[0] in ("wf", "sf"):
                self.fairness.append((c[0], c[1], c[2]))
            elif c[0] == "id":
                sub = self.module.defs.get(c[1])
                if sub is not None and contains_temporal(sub.body):
                    # e.g. `Liveness` — a named conjunction of WF formulas
                    saved_init, saved_next = self.init_name, self.next_name
                    self._decompose_into(sub.body)
                    if self.init_name is None:
                        self.init_name = saved_init
                else:
                    if self.init_name is None or self.init_name == c[1]:
                        self.init_name = c[1]
                    else:
                        self.init_name = self.init_name  # keep first
            else:
                raise TLAError(f"cannot decompose spec conjunct {c!r}")

    def _decompose_into(self, body):
        def flatten(e, out):
            if e[0] == "and":
                for x in e[1]:
                    flatten(x, out)
            else:
                out.append(e)
        items = []
        flatten(body, items)
        for c in items:
            if c[0] in ("wf", "sf"):
                self.fairness.append((c[0], c[1], c[2]))
            elif c[0] == "boxaction":
                if c[1][0] == "id":
                    self.next_name = c[1][1]

    # ------------------------------------------------------------------
    def _action_list(self):
        d = self.module.defs.get(self.next_name)
        if d is None:
            raise TLAError(f"NEXT {self.next_name} not defined")
        actions = []

        def flatten_or(e):
            if e[0] == "or":
                for x in e[1]:
                    flatten_or(x)
            elif e[0] == "id" and e[1] in self.module.defs \
                    and not self.module.defs[e[1]].params:
                sub = self.module.defs[e[1]]
                actions.append(Action(
                    name=e[1], expr=sub.body,
                    location=f"line {sub.line0}, col {sub.col0} to line "
                             f"{sub.line1}, col {sub.col1} of module {sub.module}"))
            else:
                actions.append(Action(
                    name=self.next_name, expr=e,
                    location=f"line {d.line0}, col {d.col0} to line "
                             f"{d.line1}, col {d.col1} of module {d.module}"))
        flatten_or(d.body)
        return actions

    def _symmetry_perms(self, symm_name):
        """Evaluate the SYMMETRY definition to permutation dicts (TLC
        Permutations semantics, VSR.tla:151).  Identity is dropped."""
        if not symm_name:
            return []
        d = self.module.defs.get(symm_name)
        if d is None:
            raise TLAError(f"SYMMETRY {symm_name} not defined")
        val = self.ev.eval(d.body, EMPTY_ENV, EvalCtx({}))
        perms = []
        for p in val:
            if not isinstance(p, FnVal):
                raise TLAError("SYMMETRY must evaluate to a set of functions")
            mapping = {k: v for k, v in p.items if k is not v}
            if mapping:
                perms.append(mapping)
        return perms

    # ------------------------------------------------------------------
    # checkable interface
    # ------------------------------------------------------------------
    def init_states(self):
        d = self.module.defs[self.init_name]
        yield from self.enum.init_states(d.body)

    def successors(self, state):
        """Yield (action, successor_state) pairs."""
        for action in self.actions:
            for succ in self.enum.successors(action.expr, state):
                yield action, succ

    def check_invariants(self, state):
        """Return the name of the first violated invariant, or None."""
        ctx = EvalCtx(state)
        for name, d in self.invariants:
            if self.ev.eval(d.body, EMPTY_ENV, ctx) is not True:
                return name
        return None

    def eval_predicate(self, name, state):
        d = self.module.defs[name]
        return self.ev.eval(d.body, EMPTY_ENV, EvalCtx(state)) is True

    def view_value(self, state):
        """Project the state through VIEW (fingerprint identity), fold
        symmetry by taking the least permuted image (SURVEY.md §2.4)."""
        if self.view_def is not None:
            v = self.ev.eval(self.view_def.body, EMPTY_ENV, EvalCtx(state))
        else:
            v = FnVal(sorted(state.items()))
        if self.symmetry_perms:
            best = v
            best_key = value_key(v)
            for p in self.symmetry_perms:
                pv = permute_value(v, p)
                pk = value_key(pv)
                if pk < best_key:
                    best, best_key = pv, pk
            v = best
        return v


def _synth_def(name, body, modname):
    from ..frontend.tla_ast import Def
    return Def(name=name, params=[], body=body, module=modname)


def load_spec(tla_path: str, cfg_path: str) -> SpecModel:
    from ..frontend.cfg import parse_cfg_file
    from ..frontend.parser import parse_module_file
    return SpecModel(parse_module_file(tla_path), parse_cfg_file(cfg_path))
