"""Checker-level checkpoint/resume (SURVEY.md §5): snapshot the BFS
frontier, fingerprint table, visited-state store, and counters so
multi-day runs survive preemption — the analog of TLC's queue/FPSet
checkpointing implied by the reference's 500 GB multi-day guidance
(README:20).

Format: one directory with numbered .npz chunk files plus a manifest;
written atomically (tmp dir + rename) so a crash mid-write leaves the
previous checkpoint intact.
"""

from __future__ import annotations

import json
import os
import shutil

import numpy as np


FORMAT_VERSION = 1


def save_checkpoint(path, *, table, store, frontier, level_base, depth,
                    level_sizes, fp_count, fp_cap, states_generated,
                    max_msgs, elapsed):
    """Write a complete engine snapshot to `path` (atomic)."""
    tmp = path + ".tmp"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    np.savez(os.path.join(tmp, "fpset.npz"),
             tags=np.asarray(table["tags"]),
             rows=np.asarray(table["rows"]))
    np.savez(os.path.join(tmp, "frontier.npz"), **frontier)
    for i, chunk in enumerate(store.chunks):
        np.savez(os.path.join(tmp, f"chunk{i:05d}.npz"), **chunk)
    manifest = {
        "format": FORMAT_VERSION,
        "n_chunks": len(store.chunks),
        "offsets": store.offsets,
        "parents": [[p if p is not None else -1,
                     a if a is not None else -1]
                    for p, a in store.parents],
        "level_base": level_base,
        "depth": depth,
        "level_sizes": level_sizes,
        "fp_count": fp_count,
        "fp_cap": fp_cap,
        "states_generated": states_generated,
        "max_msgs": max_msgs,
        "elapsed": elapsed,
    }
    with open(os.path.join(tmp, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    if os.path.isdir(path):
        shutil.rmtree(path)
    os.rename(tmp, path)


def load_checkpoint(path):
    """Read a snapshot; returns a dict of the save_checkpoint kwargs."""
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    if manifest["format"] != FORMAT_VERSION:
        raise ValueError(f"checkpoint format {manifest['format']} "
                         f"unsupported")
    fp = np.load(os.path.join(path, "fpset.npz"))
    table = {"tags": fp["tags"], "rows": fp["rows"]}
    fr = np.load(os.path.join(path, "frontier.npz"))
    frontier = {k: fr[k] for k in fr.files}
    chunks = []
    for i in range(manifest["n_chunks"]):
        c = np.load(os.path.join(path, f"chunk{i:05d}.npz"))
        chunks.append({k: c[k] for k in c.files})
    parents = [(None if p == -1 else p, None if a == -1 else a)
               for p, a in manifest["parents"]]
    return {
        "table": table, "frontier": frontier, "chunks": chunks,
        "offsets": manifest["offsets"], "parents": parents,
        "level_base": manifest["level_base"], "depth": manifest["depth"],
        "level_sizes": manifest["level_sizes"],
        "fp_count": manifest["fp_count"], "fp_cap": manifest["fp_cap"],
        "states_generated": manifest["states_generated"],
        "max_msgs": manifest["max_msgs"],
        "elapsed": manifest["elapsed"],
    }
