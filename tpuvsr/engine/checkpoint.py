"""Checker-level checkpoint/resume (SURVEY.md §5): snapshot the BFS
engine at a level boundary — fingerprint table, live frontier, host
trace-pointer store, and counters — so multi-day runs survive
preemption, the analog of TLC's queue/FPSet checkpointing implied by
the reference's 500 GB multi-day guidance (README:20).

A checkpoint is one directory holding .npz payloads plus a JSON
manifest, written atomically: the new snapshot is staged in a tmp dir,
the previous checkpoint is renamed aside to ``<path>.old`` (rename is
instant, unlike the rmtree of a multi-GB snapshot), the tmp dir is
renamed into place, and only then is ``.old`` deleted — so a crash or
preemption at any point leaves either the previous or the new snapshot
loadable (``load_checkpoint`` falls back to ``.old``).  Level
boundaries are the one clean point of the device engine: the
next-frontier buffers are empty, so the snapshot is exactly (FPSet,
frontier, trace pointers, counters).

The manifest records a digest of the spec identity (module name,
constants, invariants, view/symmetry) so ``-recover`` with a mismatched
spec or .cfg is rejected instead of silently resuming with
incompatible fingerprints (TLC likewise errors on recover mismatch).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil

import numpy as np

FORMAT_VERSION = 3


def spec_digest(spec) -> str:
    """Stable identity of (module, constants, invariants, view,
    symmetry) for recover-mismatch detection."""
    from ..core.values import fmt
    parts = [spec.module.name]
    for name in sorted(spec.ev.constants):
        parts.append(f"{name}={fmt(spec.ev.constants[name])}")
    parts.append("inv:" + ",".join(sorted(spec.cfg.invariants)))
    parts.append(f"view:{spec.cfg.view}")
    # the full permutation content, not just on/off: resuming under a
    # different SYMMETRY set means a different canonicalization and an
    # incompatible fingerprint space
    perms = sorted(
        ",".join(f"{fmt(a)}>{fmt(b)}" for a, b in sorted(
            p.items(), key=lambda kv: fmt(kv[0])))
        for p in spec.symmetry_perms)
    parts.append("symm:" + ";".join(perms))
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def save_checkpoint(path, *, slots, frontier, n_front, h_parent,
                    h_action, h_param, init_dense, level_sizes, depth,
                    fp_count, states_generated, max_msgs, expand_mults,
                    elapsed, digest=None, extra=None):
    """Write a complete engine snapshot to `path` (atomic).

    `frontier` rows beyond `n_front` are dropped; `h_*` are the
    concatenated host trace-pointer arrays; `init_dense` is the dense
    encoding of the (deduped) initial states, in gid order."""
    tmp = path + ".ckpt-tmp"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    np.savez_compressed(os.path.join(tmp, "fpset.npz"),
                        slots=np.asarray(slots))
    np.savez_compressed(
        os.path.join(tmp, "frontier.npz"),
        **{k: np.asarray(v)[:n_front] for k, v in frontier.items()})
    np.savez_compressed(os.path.join(tmp, "trace.npz"),
                        parent=h_parent, action=h_action, param=h_param)
    np.savez_compressed(
        os.path.join(tmp, "init.npz"),
        **{k: np.stack([np.asarray(d[k]) for d in init_dense])
           for k in init_dense[0]})
    manifest = {
        "format": FORMAT_VERSION,
        "n_front": int(n_front),
        "n_init": len(init_dense),
        "level_sizes": [int(x) for x in level_sizes],
        "depth": int(depth),
        "fp_count": int(fp_count),
        "states_generated": int(states_generated),
        "max_msgs": int(max_msgs),
        "expand_mults": [int(x) for x in expand_mults],
        "elapsed": float(elapsed),
        "spec_digest": digest,
        # engine-specific payload (e.g. the sharded driver's per-shard
        # frontier counts and exchange capacities)
        "extra": extra,
    }
    with open(os.path.join(tmp, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    old = path + ".old"
    if os.path.isdir(old):
        shutil.rmtree(old)
    if os.path.isdir(path):
        os.rename(path, old)
    os.rename(tmp, path)
    if os.path.isdir(old):
        shutil.rmtree(old)


def prior_elapsed(path) -> float:
    """Cumulative wall-clock recorded in a snapshot's manifest (0.0
    when absent/unreadable).  Resumable window scripts add this to
    their window budget: a resumed run's elapsed is CUMULATIVE (run()
    rewinds t0 by it), so a bare window budget would no-op."""
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            return float(json.load(f)["elapsed"])
    except (OSError, ValueError, KeyError):
        return 0.0


def load_checkpoint(path, expect_digest=None):
    """Read a snapshot; returns a dict mirroring save_checkpoint.

    Falls back to ``<path>.old`` when the primary is missing or
    unreadable (a crash between the rename-aside and rename-into-place
    of ``save_checkpoint``)."""
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        old = path + ".old"
        if not os.path.isdir(old):
            raise
        path = old
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
    if manifest["format"] != FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {manifest['format']} unsupported "
            f"(want {FORMAT_VERSION})")
    if expect_digest is not None and manifest.get("spec_digest") and \
            manifest["spec_digest"] != expect_digest:
        raise ValueError(
            "checkpoint was written by a different spec/.cfg "
            f"(digest {manifest['spec_digest']}, this run "
            f"{expect_digest}); refusing to resume")
    fp = np.load(os.path.join(path, "fpset.npz"))
    fr = np.load(os.path.join(path, "frontier.npz"))
    tr = np.load(os.path.join(path, "trace.npz"))
    ini = np.load(os.path.join(path, "init.npz"))
    n_init = manifest["n_init"]
    init_dense = [{k: ini[k][i] for k in ini.files}
                  for i in range(n_init)]
    return {
        "slots": fp["slots"],
        "frontier": {k: fr[k] for k in fr.files},
        "n_front": manifest["n_front"],
        "h_parent": tr["parent"],
        "h_action": tr["action"],
        "h_param": tr["param"],
        "init_dense": init_dense,
        "level_sizes": manifest["level_sizes"],
        "depth": manifest["depth"],
        "fp_count": manifest["fp_count"],
        "states_generated": manifest["states_generated"],
        "max_msgs": manifest["max_msgs"],
        "expand_mults": manifest["expand_mults"],
        "elapsed": manifest["elapsed"],
        "extra": manifest.get("extra"),
    }
