"""Checker-level checkpoint/resume (SURVEY.md §5): snapshot the BFS
engine at a level boundary — fingerprint table, live frontier, host
trace-pointer store, and counters — so multi-day runs survive
preemption, the analog of TLC's queue/FPSet checkpointing implied by
the reference's 500 GB multi-day guidance (README:20).

A checkpoint is one directory holding .npz payloads plus a JSON
manifest, written atomically (tmp dir + rename) so a crash mid-write
leaves the previous checkpoint intact.  Level boundaries are the one
clean point of the device engine: the next-frontier buffers are empty,
so the snapshot is exactly (FPSet, frontier, trace pointers, counters).
"""

from __future__ import annotations

import json
import os
import shutil

import numpy as np

FORMAT_VERSION = 2


def save_checkpoint(path, *, slots, frontier, n_front, h_parent,
                    h_action, h_param, init_dense, level_sizes, depth,
                    fp_count, states_generated, max_msgs, expand_mults,
                    elapsed):
    """Write a complete engine snapshot to `path` (atomic).

    `frontier` rows beyond `n_front` are dropped; `h_*` are the
    concatenated host trace-pointer arrays; `init_dense` is the dense
    encoding of the (deduped) initial states, in gid order."""
    tmp = path + ".ckpt-tmp"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    np.savez_compressed(os.path.join(tmp, "fpset.npz"),
                        slots=np.asarray(slots))
    np.savez_compressed(
        os.path.join(tmp, "frontier.npz"),
        **{k: np.asarray(v)[:n_front] for k, v in frontier.items()})
    np.savez_compressed(os.path.join(tmp, "trace.npz"),
                        parent=h_parent, action=h_action, param=h_param)
    np.savez_compressed(
        os.path.join(tmp, "init.npz"),
        **{k: np.stack([np.asarray(d[k]) for d in init_dense])
           for k in init_dense[0]})
    manifest = {
        "format": FORMAT_VERSION,
        "n_front": int(n_front),
        "n_init": len(init_dense),
        "level_sizes": [int(x) for x in level_sizes],
        "depth": int(depth),
        "fp_count": int(fp_count),
        "states_generated": int(states_generated),
        "max_msgs": int(max_msgs),
        "expand_mults": [int(x) for x in expand_mults],
        "elapsed": float(elapsed),
    }
    with open(os.path.join(tmp, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    if os.path.isdir(path):
        shutil.rmtree(path)
    os.rename(tmp, path)


def load_checkpoint(path):
    """Read a snapshot; returns a dict mirroring save_checkpoint."""
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    if manifest["format"] != FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {manifest['format']} unsupported "
            f"(want {FORMAT_VERSION})")
    fp = np.load(os.path.join(path, "fpset.npz"))
    fr = np.load(os.path.join(path, "frontier.npz"))
    tr = np.load(os.path.join(path, "trace.npz"))
    ini = np.load(os.path.join(path, "init.npz"))
    n_init = manifest["n_init"]
    init_dense = [{k: ini[k][i] for k in ini.files}
                  for i in range(n_init)]
    return {
        "slots": fp["slots"],
        "frontier": {k: fr[k] for k in fr.files},
        "n_front": manifest["n_front"],
        "h_parent": tr["parent"],
        "h_action": tr["action"],
        "h_param": tr["param"],
        "init_dense": init_dense,
        "level_sizes": manifest["level_sizes"],
        "depth": manifest["depth"],
        "fp_count": manifest["fp_count"],
        "states_generated": manifest["states_generated"],
        "max_msgs": manifest["max_msgs"],
        "expand_mults": manifest["expand_mults"],
        "elapsed": manifest["elapsed"],
    }
