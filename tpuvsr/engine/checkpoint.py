"""Checker-level checkpoint/resume (SURVEY.md §5): snapshot the BFS
engine at a level boundary — fingerprint table, live frontier, host
trace-pointer store, and counters — so multi-day runs survive
preemption, the analog of TLC's queue/FPSet checkpointing implied by
the reference's 500 GB multi-day guidance (README:20).

A checkpoint is one directory holding .npz payloads plus a JSON
manifest, written atomically and durably: the payloads are staged in a
tmp dir, fsynced (files, then the staged dir), the previous checkpoint
is renamed aside to ``<path>.old`` (rename is instant, unlike the
rmtree of a multi-GB snapshot), the tmp dir is renamed into place, the
parent directory is fsynced so the renames survive power loss, and
only then is ``.old`` deleted — so a crash or preemption at any point
leaves either the previous or the new snapshot loadable.

The manifest records a CRC32 per payload file; ``load_checkpoint``
verifies them (plus np.load-ability and frontier row counts) and falls
back to ``<path>.old`` on ANY payload-level corruption — a truncated
``fpset.npz`` with an intact manifest recovers the previous snapshot
instead of raising deep inside ``np.load`` (ISSUE 3 hardening).
Policy errors (format version, spec-digest mismatch) never fall back:
``.old`` would carry the same spec identity, and masking them behind a
silent downgrade would resume the wrong model.

The manifest also records a digest of the spec identity (module name,
constants, invariants, view/symmetry) so ``-recover`` with a mismatched
spec or .cfg is rejected instead of silently resuming with
incompatible fingerprints (TLC likewise errors on recover mismatch).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import zipfile
import zlib

import numpy as np
from numpy.lib import format as _npformat

FORMAT_VERSION = 3

#: the payload files of one snapshot directory, in write order
PAYLOADS = ("fpset.npz", "frontier.npz", "trace.npz", "init.npz")


class CheckpointCorrupt(ValueError):
    """A snapshot failed integrity verification (unreadable manifest,
    missing payload, CRC mismatch, undecodable npz, inconsistent row
    counts).  ``load_checkpoint`` falls back to ``.old`` on this."""


def spec_digest(spec) -> str:
    """Stable identity of (module, constants, invariants, view,
    symmetry) for recover-mismatch detection."""
    from ..core.values import fmt
    parts = [spec.module.name]
    for name in sorted(spec.ev.constants):
        parts.append(f"{name}={fmt(spec.ev.constants[name])}")
    parts.append("inv:" + ",".join(sorted(spec.cfg.invariants)))
    parts.append(f"view:{spec.cfg.view}")
    # the full permutation content, not just on/off: resuming under a
    # different SYMMETRY set means a different canonicalization and an
    # incompatible fingerprint space
    perms = sorted(
        ",".join(f"{fmt(a)}>{fmt(b)}" for a, b in sorted(
            p.items(), key=lambda kv: fmt(kv[0])))
        for p in spec.symmetry_perms)
    parts.append("symm:" + ";".join(perms))
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def _crc32_file(path):
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _fsync_path(path):
    """fsync a file or directory by path (directory fsync is what makes
    a rename durable on POSIX)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


#: chunked frontier payload member name: "<plane>.<chunk index>"
_CHUNK_RE = re.compile(r"^(.+)\.(\d{6})$")


def _write_frontier_chunks(path, blocks):
    """Stream an iterable of dense plane-dict blocks into one npz
    (ISSUE 13 satellite — the PR 11 residual): each block is written
    as it arrives (member ``<plane>.<i:06d>``) and dropped, so a
    disk-spilled frontier is checkpointed WITHOUT materializing it in
    RAM.  ``load_checkpoint`` reassembles the chunks transparently.
    Returns the number of rows written."""
    rows = 0
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        for i, block in enumerate(blocks):
            n = None
            for k, v in block.items():
                arr = np.ascontiguousarray(np.asarray(v))
                n = arr.shape[0] if n is None else n
                with zf.open(f"{k}.{i:06d}.npy", "w") as f:
                    _npformat.write_array(f, arr, allow_pickle=False)
            rows += int(n or 0)
    return rows


def _assemble_frontier(fr):
    """Reassemble a frontier payload dict: plain per-plane arrays pass
    through; chunked members (``<plane>.<i>``) concatenate in chunk
    order."""
    if not any(_CHUNK_RE.match(k) for k in fr):
        return dict(fr)
    chunks = {}
    for k, v in fr.items():
        m = _CHUNK_RE.match(k)
        if m is None:
            raise CheckpointCorrupt(
                f"frontier payload mixes chunked and plain members "
                f"({k!r})")
        chunks.setdefault(m.group(1), []).append((int(m.group(2)), v))
    return {plane: np.concatenate(
        [v for _i, v in sorted(parts)]) if len(parts) > 1
        else sorted(parts)[0][1]
        for plane, parts in chunks.items()}


def save_checkpoint(path, *, slots, frontier=None, n_front, h_parent,
                    h_action, h_param, init_dense, level_sizes, depth,
                    fp_count, states_generated, max_msgs, expand_mults,
                    elapsed, digest=None, extra=None, pack=None,
                    canon=None, bounds=None, por=None,
                    frontier_blocks=None,
                    gids=None, edge_blocks=None, graph_blocks=None,
                    obs=None):
    """Write a complete engine snapshot to `path` (atomic + durable).

    `frontier` rows beyond `n_front` are dropped; `h_*` are the
    concatenated host trace-pointer arrays; `init_dense` is the dense
    encoding of the (deduped) initial states, in gid order.

    `pack` is the packed-frontier spec manifest the writing engine ran
    under (engine/pack.PackSpec.manifest(); None = packing off).  The
    frontier payload itself is ALWAYS dense planes — the interchange
    format any engine/pack configuration can resume — but the manifest
    records the spec version so resuming under a MISMATCHED widths
    table is a loud policy error (ISSUE 9 satellite).

    `frontier_blocks` (ISSUE 13 satellite) replaces `frontier` with an
    ITERATOR of dense plane-dict blocks: each block is streamed into
    the staged frontier.npz and released, so a disk-spilled frontier
    (engine/spill.py) checkpoints at page-sized peak residency instead
    of materializing `n_front` dense rows.  The chunked payload is
    read back transparently by ``load_checkpoint``.

    Streamed edge emission (ISSUE 15) adds three OPTIONAL payload
    pieces: `gids` — the FPSet's parallel gid column (fingerprint ->
    graph node id), stored alongside ``slots`` in fpset.npz;
    `edge_blocks` — an iterator of ``{src, aid, dst}`` array blocks
    (the CSR builder's drained rows up to this committed level),
    streamed into edges.npz; `graph_blocks` — an iterator of the
    retained dense level blocks (temporal runs), streamed into
    graph.npz.  All three are restored by ``load_checkpoint``, so a
    SIGTERM'd temporal run resumes to a bit-identical CSR."""
    from ..resilience.faults import fault_point
    tmp = path + ".ckpt-tmp"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    fp_arrs = {"slots": np.asarray(slots)}
    if gids is not None:
        fp_arrs["gids"] = np.asarray(gids)
    np.savez_compressed(os.path.join(tmp, "fpset.npz"), **fp_arrs)
    extra_payloads = []
    if edge_blocks is not None:
        _write_frontier_chunks(os.path.join(tmp, "edges.npz"),
                               edge_blocks)
        extra_payloads.append("edges.npz")
    if graph_blocks is not None:
        _write_frontier_chunks(os.path.join(tmp, "graph.npz"),
                               graph_blocks)
        extra_payloads.append("graph.npz")
    if frontier_blocks is not None:
        rows = _write_frontier_chunks(
            os.path.join(tmp, "frontier.npz"), frontier_blocks)
        if rows != int(n_front):
            raise ValueError(
                f"frontier_blocks yielded {rows} rows, n_front is "
                f"{n_front}")
    else:
        np.savez_compressed(
            os.path.join(tmp, "frontier.npz"),
            **{k: np.asarray(v)[:n_front] for k, v in frontier.items()})
    np.savez_compressed(os.path.join(tmp, "trace.npz"),
                        parent=h_parent, action=h_action, param=h_param)
    np.savez_compressed(
        os.path.join(tmp, "init.npz"),
        **{k: np.stack([np.asarray(d[k]) for d in init_dense])
           for k in init_dense[0]})
    # CRCs are computed over the INTENDED payload bytes, before the
    # corrupt-ckpt fault hook below mangles anything — a fault-injected
    # torn write is therefore CRC-detectable, like a real one
    crcs = {name: _crc32_file(os.path.join(tmp, name))
            for name in list(PAYLOADS) + extra_payloads}
    manifest = {
        "format": FORMAT_VERSION,
        "n_front": int(n_front),
        "n_init": len(init_dense),
        "level_sizes": [int(x) for x in level_sizes],
        "depth": int(depth),
        "fp_count": int(fp_count),
        "states_generated": int(states_generated),
        "max_msgs": int(max_msgs),
        "expand_mults": [int(x) for x in expand_mults],
        "elapsed": float(elapsed),
        "spec_digest": digest,
        "payload_crc32": crcs,
        # packed-frontier spec identity (ISSUE 9): version digest +
        # plane table of the writer's packing spec, None when dense
        "pack": pack,
        # symmetry canonicalization spec (ISSUE 11): version digest +
        # group order + orbit plane table of the writer's CanonSpec,
        # None when the run stored raw (non-canonical) fingerprints.
        # Resuming under a flipped -symmetry or a changed group is a
        # policy error — the FPSet's fingerprint space would not match
        "canon": canon,
        # bounds-facts identity (ISSUE 13): digest of the speclint
        # bounds pass facts the writer consumed (tightened packing +
        # pruned action ids depend on them), None when bounds off.
        # Resuming under a flipped -bounds or changed cfg constants
        # is a policy error, mirroring the pack/canon rules
        "bounds": bounds,
        # independence-facts identity (ISSUE 16): digest of the
        # speclint independence pass facts the writer's ample-set
        # partial-order reduction consumed (the reduced reachable set
        # depends on them), None when POR off.  Resuming under a
        # flipped -por or changed facts is a policy error, mirroring
        # the pack/canon/bounds rules
        "por": por,
        # engine-specific payload (e.g. the sharded driver's per-shard
        # frontier counts and exchange capacities)
        "extra": extra,
    }
    with open(os.path.join(tmp, "manifest.json"), "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    # fault hook: emulate a corrupted write AND leave the previous
    # snapshot as .old (the crash window between rename-into-place and
    # .old cleanup).  Two flavors (resilience/faults.py): corrupt-ckpt
    # truncates the named payload (torn write — np.load chokes);
    # garble-ckpt XOR-flips a byte span mid-file with the size
    # preserved (bit rot — ONLY the manifest CRC32 catches it)
    corrupt = fault_point("checkpoint", depth=depth, path=path, obs=obs)
    if corrupt:
        victim = os.path.join(tmp, corrupt.payload)
        size = os.path.getsize(victim)
        with open(victim, "r+b") as f:
            if corrupt.kind == "garble-ckpt":
                span = max(1, min(64, size // 2))
                f.seek(size // 2)
                chunk = f.read(span)
                f.seek(size // 2)
                f.write(bytes(b ^ 0xFF for b in chunk))
            else:
                f.truncate(max(1, size // 2))
    for name in list(PAYLOADS) + extra_payloads:
        _fsync_path(os.path.join(tmp, name))
    _fsync_path(tmp)
    old = path + ".old"
    if os.path.isdir(old):
        shutil.rmtree(old)
    if os.path.isdir(path):
        os.rename(path, old)
    os.rename(tmp, path)
    parent = os.path.dirname(os.path.abspath(path)) or "."
    _fsync_path(parent)
    if os.path.isdir(old) and not corrupt:
        shutil.rmtree(old)
        _fsync_path(parent)


def snapshot_info(path):
    """Cheap manifest-only summary of a snapshot directory — the
    checkpoint handoff record the dispatch service attaches to a
    requeued job (ISSUE 6): ``{path, depth, distinct, elapsed}`` or
    None when `path` holds no readable manifest.  Reads no payloads,
    so a worker can stamp a rescue onto the queue without touching
    multi-GB npz files."""
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            mf = json.load(f)
        return {"path": path, "depth": int(mf["depth"]),
                "distinct": int(mf["fp_count"]),
                "elapsed": float(mf["elapsed"])}
    except (OSError, ValueError, KeyError, TypeError):
        return None


def prior_elapsed(path) -> float:
    """Cumulative wall-clock recorded in a snapshot's manifest (0.0
    when absent/unreadable).  Resumable window scripts add this to
    their window budget: a resumed run's elapsed is CUMULATIVE (run()
    rewinds t0 by it), so a bare window budget would no-op."""
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            return float(json.load(f)["elapsed"])
    except (OSError, ValueError, KeyError):
        return 0.0


def _read_snapshot(path, expect_digest):
    """Read + verify one snapshot directory.  Raises CheckpointCorrupt
    on any integrity failure (fallback-eligible) and plain ValueError
    on policy mismatches (format version, spec digest — never masked
    by the .old fallback)."""
    mf = os.path.join(path, "manifest.json")
    try:
        with open(mf) as f:
            manifest = json.load(f)
    except OSError as e:
        raise CheckpointCorrupt(f"{mf}: unreadable manifest ({e})")
    except ValueError as e:
        raise CheckpointCorrupt(f"{mf}: manifest is not valid JSON "
                                f"({e})")
    if manifest.get("format") != FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {manifest.get('format')} unsupported "
            f"(want {FORMAT_VERSION})")
    if expect_digest is not None and manifest.get("spec_digest") and \
            manifest["spec_digest"] != expect_digest:
        raise ValueError(
            "checkpoint was written by a different spec/.cfg "
            f"(digest {manifest['spec_digest']}, this run "
            f"{expect_digest}); refusing to resume")
    crcs = manifest.get("payload_crc32") or {}
    arrs = {}
    # optional payloads (edges.npz / graph.npz, the ISSUE 15 edge
    # stream) are verified iff the manifest recorded a CRC for them —
    # a listed-but-missing optional payload is corruption, not absence
    names = list(PAYLOADS) + sorted(set(crcs) - set(PAYLOADS))
    for name in names:
        p = os.path.join(path, name)
        try:
            want = crcs.get(name)
            if want is not None and _crc32_file(p) != int(want):
                raise CheckpointCorrupt(
                    f"{p}: CRC32 mismatch (payload corrupted after "
                    f"write)")
            with np.load(p) as z:
                arrs[name] = {k: z[k] for k in z.files}
        except CheckpointCorrupt:
            raise
        except Exception as e:  # noqa: BLE001 — np.load raises a zoo
            raise CheckpointCorrupt(
                f"{p}: unreadable payload "
                f"({type(e).__name__}: {e})")
    n_front = int(manifest["n_front"])
    arrs["frontier.npz"] = _assemble_frontier(arrs["frontier.npz"])
    for k, v in arrs["frontier.npz"].items():
        if v.shape[0] != n_front:
            raise CheckpointCorrupt(
                f"{path}: frontier plane {k!r} has {v.shape[0]} rows, "
                f"manifest says n_front={n_front}")
    return manifest, arrs


def load_checkpoint(path, expect_digest=None, log=None):
    """Read a snapshot; returns a dict mirroring save_checkpoint.

    Falls back to ``<path>.old`` when the primary is missing or fails
    integrity verification at ANY level — absent/garbled manifest, bad
    payload CRC, truncated/missing .npz, inconsistent frontier rows
    (a crash anywhere inside ``save_checkpoint``'s write/rename
    sequence).  The returned dict records which directory actually
    loaded under ``restored_from``."""
    used = path
    try:
        manifest, arrs = _read_snapshot(path, expect_digest)
    except CheckpointCorrupt as e:
        old = path + ".old"
        if not os.path.isdir(old):
            raise
        if log:
            log(f"checkpoint {path} unusable ({e}); "
                f"falling back to {old}")
        manifest, arrs = _read_snapshot(old, expect_digest)
        used = old
    fp = arrs["fpset.npz"]
    fr = arrs["frontier.npz"]
    tr = arrs["trace.npz"]
    ini = arrs["init.npz"]
    n_init = manifest["n_init"]
    init_dense = [{k: ini[k][i] for k in ini}
                  for i in range(n_init)]

    def _opt_chunked(name):
        d = arrs.get(name)
        if d is None:
            return None
        d = _assemble_frontier(d)
        return d or None        # zero-block payload == absent
    return {
        "slots": fp["slots"],
        # streamed edge emission (ISSUE 15): the gid column and the
        # drained edge / retained graph rows, when the writer ran
        # with edges on (None otherwise)
        "gids": fp.get("gids"),
        "edges": _opt_chunked("edges.npz"),
        "graph": _opt_chunked("graph.npz"),
        "frontier": dict(fr),
        "n_front": manifest["n_front"],
        "h_parent": tr["parent"],
        "h_action": tr["action"],
        "h_param": tr["param"],
        "init_dense": init_dense,
        "level_sizes": manifest["level_sizes"],
        "depth": manifest["depth"],
        "fp_count": manifest["fp_count"],
        "states_generated": manifest["states_generated"],
        "max_msgs": manifest["max_msgs"],
        "expand_mults": manifest["expand_mults"],
        "elapsed": manifest["elapsed"],
        "extra": manifest.get("extra"),
        "pack": manifest.get("pack"),
        "canon": manifest.get("canon"),
        "bounds": manifest.get("bounds"),
        "por": manifest.get("por"),
        "restored_from": used,
    }
