"""Device (TPU) breadth-first model checking engine.

This is the reference's hot loop — TLC's BFS worker (SURVEY.md §3.1) —
restructured as a data-parallel XLA pipeline.  Per frontier tile of T
states, entirely on device:

  tile --step_batch--> [T, L] lane successors     (vsr_kernel.step_all)
       --fingerprint--> symmetry-least 128-bit fp (VIEW projection)
       --invariants --> per-successor pass/fail   (checked on *every*
                        generated state — a superset of TLC's
                        fresh-only checking, sound because generated
                        states are reachable)
       --dedup+FPSet--> fresh mask                (engine/fpset.py)
       --compaction --> packed fresh states, transferred host-side only

The host orchestrates tiles, owns the frontier (numpy), and keeps
(parent, action, lane) pointers per state for counterexample
reconstruction in the reference's trace format (TRACE:3-7).

Scale note: frontier + visited states live in host RAM (the device holds
only fingerprints + the working tile), so capacity is host-memory-bound
at ~5 KB/state; fingerprints in HBM at 16 B/state.  Multi-host sharding
is the next tier (SURVEY.md §5 distributed backend).
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from ..core.values import TLAError
from ..models.vsr import ERR_BAG_OVERFLOW, VSRCodec
from ..models.vsr_kernel import ACTION_NAMES, VSRKernel
from .bfs import CheckResult
from .fpset import dedup_batch, empty_table, grow, insert_batch
from .spec import SpecModel
from .trace import TraceEntry


def _value_perm_table(spec, codec):
    """spec.symmetry_perms (ModelValue maps) -> [P, V+1] id table with the
    identity first (kernel takes the min over rows)."""
    V = codec.shape.V
    rows = [np.arange(V + 1, dtype=np.int32)]
    for p in spec.symmetry_perms:
        row = np.arange(V + 1, dtype=np.int32)
        for mv_from, mv_to in p.items():
            row[codec.value_id[mv_from]] = codec.value_id[mv_to]
        rows.append(row)
    return np.stack(rows)


class _StateStore:
    """Host-side registry of visited dense states, appended per batch;
    gid -> state row lookup for trace reconstruction."""

    def __init__(self):
        self.chunks = []          # list of dict-of-np [n_i, ...]
        self.offsets = [0]
        self.parents = []         # gid -> (parent_gid | None, action_id)

    def append(self, states, parent_gids, action_ids):
        n = len(parent_gids)
        if n:
            self.chunks.append(states)
            self.offsets.append(self.offsets[-1] + n)
            self.parents.extend(zip(parent_gids, action_ids))
        return self.offsets[-1]

    def __len__(self):
        return self.offsets[-1]

    def get(self, gid):
        import bisect
        c = bisect.bisect_right(self.offsets, gid) - 1
        row = gid - self.offsets[c]
        return {k: v[row] for k, v in self.chunks[c].items()}


class DeviceBFS:
    def __init__(self, spec: SpecModel, max_msgs=None, tile_size=32,
                 fpset_capacity=1 << 20, hash_mode="full"):
        self.spec = spec
        self.tile = tile_size
        self.fpset_capacity = fpset_capacity
        self.hash_mode = hash_mode
        self.inv_names = list(spec.cfg.invariants)
        self._build(max_msgs)

    def _build(self, max_msgs):
        """(Re)build codec, kernel, and jitted passes for a message-table
        bound; called again by _grow_msgs on bag overflow."""
        spec = self.spec
        self.codec = VSRCodec(spec.ev.constants, max_msgs=max_msgs)
        self.kern = VSRKernel(self.codec,
                              perms=_value_perm_table(spec, self.codec))
        self.L = self.kern.n_lanes
        inv = self.kern.invariant_fn(self.inv_names)
        kern = self.kern
        incremental = self.hash_mode == "incremental"

        def expand_hash(tile, valid):
            """The fused hot pass: expand every lane, fingerprint and
            invariant-check the successor without keeping it, and emit
            only the per-lane smalls — the [T, L] successor states are
            never engine outputs.  Fresh lanes are re-materialized
            afterwards (a tiny fraction of the lane space)."""
            def per_state(st):
                # incremental: one full-state hash per parent,
                # O(touched rows) per lane
                parts = kern.parent_parts(st) if incremental else None
                outs = []
                for name, fn in zip(ACTION_NAMES, kern._action_fns()):
                    lanes = jnp.arange(kern._lane_count(name),
                                       dtype=jnp.int32)

                    def lane_eval(lane, fn=fn, name=name):
                        succ, en = fn(kern.seed_touch(st), lane)
                        if incremental:
                            ri = kern.lane_replica(name, st, lane)
                            fp = kern.fingerprint_incremental(
                                succ, ri, parts, st)
                        else:
                            fp = kern.fingerprint(
                                {k: v for k, v in succ.items()
                                 if not k.startswith("_")})
                        return fp, inv(succ), succ["err"], en
                    outs.append(jax.vmap(lane_eval)(lanes))
                return tuple(jnp.concatenate([o[i] for o in outs])
                             for i in range(4))
            fps, inv_ok, err, en = jax.vmap(per_state)(tile)
            en = en & valid[:, None]
            fps = fps.reshape(-1, 4)
            en = en.reshape(-1)
            viol = en & ~inv_ok.reshape(-1)
            err = jnp.where(en, err.reshape(-1), 0)
            err_bag = ((err & ERR_BAG_OVERFLOW) != 0).any()
            err_slot = ((err & ~ERR_BAG_OVERFLOW) != 0).any()
            perm, cand = dedup_batch(fps, en)
            return (fps, perm, cand, en, viol.any(), jnp.argmax(viol),
                    err_bag, err_slot)

        def pack_fresh(fps, perm, fresh):
            """order globally-fresh lane indices first for transfer."""
            order = jnp.argsort(~fresh, stable=True)
            sel = perm[order]
            return fps[sel], sel, fresh.sum()

        self._expand = jax.jit(expand_hash)
        self._pack = jax.jit(pack_fresh)
        self._mat = {}          # action id -> jitted vmapped action fn

    def _grow_msgs(self, store):
        """Grow MAX_MSGS in place: all-zero padding slots change no
        fingerprint (only present slots contribute to the bag hash), so
        the FPSet and every registered state stay valid — pad the stored
        chunks and rebuild the jitted passes.  Returns the pad function
        for the caller's frontier/pending chunks."""
        old = self.codec.shape.MAX_MSGS
        new = old * 2
        self._build(new)

        def pad(d):
            out = dict(d)
            for k in ("m_present", "m_count", "m_hdr", "m_entry", "m_log",
                      "m_log_len", "m_has_log"):
                v = d[k]
                shape = list(v.shape)
                shape[1] = new - old
                out[k] = np.concatenate(
                    [v, np.zeros(shape, v.dtype)], axis=1)
            return out
        store.chunks = [pad(c) for c in store.chunks]
        return pad

    # ------------------------------------------------------------------
    def run(self, max_states=None, max_depth=None, max_seconds=None,
            check_deadlock=False, log=None,
            progress_every=10.0) -> CheckResult:
        spec, codec, kern = self.spec, self.codec, self.kern
        res = CheckResult()
        t0 = time.time()
        store = _StateStore()
        fp_cap = self.fpset_capacity
        table = empty_table(fp_cap)
        fp_count = 0

        def emit(msg):
            if log:
                log(msg)

        # --- register init states (host path, tiny) -------------------
        init_dense = [codec.encode(st) for st in spec.init_states()]
        init_batch = {k: np.stack([d[k] for d in init_dense])
                      for k in init_dense[0]}
        fps = np.asarray(kern.fingerprint_batch(init_batch))
        keep, seen = [], set()
        for i in range(len(init_dense)):
            key = tuple(fps[i])
            if key not in seen:
                seen.add(key)
                keep.append(i)
        init_batch = {k: v[keep] for k, v in init_batch.items()}
        table, fresh, _ = insert_batch(
            table, jnp.asarray(fps[keep]),
            jnp.ones((len(keep),), bool))
        fp_count += len(keep)
        store.append(init_batch, [None] * len(keep), [None] * len(keep))
        for i in range(len(keep)):
            bad = self._check_invariants_host(init_batch, i)
            if bad:
                res.ok = False
                res.violated_invariant = bad
                res.trace = self._trace(store, i)
                return self._finish(res, store, t0, 0)
        res.states_generated += len(init_dense)
        frontier = init_batch
        level_base = 0
        depth = 0
        last_progress = t0

        self.level_sizes = [len(frontier["status"])]
        while len(frontier["status"]) > 0:
            if max_depth is not None and depth >= max_depth:
                res.error = f"depth limit {max_depth} reached"
                break
            depth += 1
            n_front = len(frontier["status"])
            fresh_chunks, fresh_parents, fresh_actions = [], [], []
            off = 0
            while off < n_front:
                tile = {k: v[off:off + self.tile]
                        for k, v in frontier.items()}
                n_valid = len(tile["status"])
                if n_valid < self.tile:
                    npad = self.tile - n_valid
                    tile = {k: np.concatenate(
                        [v, np.repeat(v[:1], npad, axis=0)])
                        for k, v in tile.items()}
                valid = np.arange(self.tile) < n_valid

                tile_j = {k: jnp.asarray(v) for k, v in tile.items()}
                (fps, perm, cand, en_flat, has_viol, viol_idx, err_bag,
                 err_slot) = self._expand(tile_j, jnp.asarray(valid))
                en_np = np.asarray(en_flat).reshape(self.tile, self.L)

                if bool(err_slot):
                    raise TLAError(
                        "dense-layout slot collision (a second DVC or "
                        "recovery response from one source in one view): "
                        "this restart-era interleaving needs the "
                        "multi-slot layout (vsr.py docstring)")
                if bool(err_bag):
                    # message table too small for some successor in this
                    # tile: grow in place and re-run the SAME tile (no
                    # inserts happened yet for it)
                    padf = self._grow_msgs(store)
                    frontier = padf(frontier)
                    fresh_chunks = [padf(c) for c in fresh_chunks]
                    kern = self.kern      # _build replaced kernel+codec:
                    codec = self.codec    # lane tables/L are all new
                    emit(f"message table grown to "
                         f"{self.codec.shape.MAX_MSGS} slots (recompiling)")
                    continue
                if check_deadlock:
                    dead = valid & ~en_np.any(axis=1)
                    if dead.any():
                        gid = level_base + off + int(np.argmax(dead))
                        res.ok = False
                        res.error = "deadlock"
                        res.deadlock_state = self.codec.decode(store.get(gid))
                        res.trace = self._trace(store, gid)
                        res.diameter = depth
                        return self._finish(res, store, t0, depth)
                res.states_generated += int(en_np.sum())

                if bool(has_viol):
                    # a generated state violates an invariant: name it
                    # on host and reconstruct the trace
                    vi = int(viol_idx)
                    vstate = {k: v[0] for k, v in self._materialize(
                        tile, np.asarray([vi])).items()}
                    parent_gid = level_base + off + vi // self.L
                    lane = vi % self.L
                    bad = self._check_invariants_host(
                        {k: v[None] for k, v in vstate.items()}, 0)
                    res.ok = False
                    res.violated_invariant = bad or self.inv_names[0]
                    res.trace = self._trace(
                        store, parent_gid,
                        extra=(vstate, int(kern.lane_action[lane])))
                    res.diameter = depth
                    return self._finish(res, store, t0, depth)

                fps_sorted = fps[perm]
                while True:
                    table, fresh, ovf = insert_batch(table, fps_sorted, cand)
                    pfps, sel, n_fresh = self._pack(fps, perm, fresh)
                    n = int(n_fresh)
                    if n:
                        fp_count += n
                        sel_np = np.asarray(sel[:n])
                        fresh_chunks.append(
                            self._materialize(tile, sel_np))
                        fresh_parents.append(
                            level_base + off + sel_np // self.L)
                        fresh_actions.append(
                            kern.lane_action[sel_np % self.L])
                    if bool(ovf) or fp_count > 0.6 * fp_cap:
                        # probe overflow dropped unresolved lanes from
                        # the insert: grow the table and re-insert —
                        # already-inserted fingerprints come back as
                        # duplicates, previously unresolved ones as fresh
                        table = grow(table)
                        fp_cap *= 4
                        if bool(ovf):
                            continue
                    break

                off += self.tile
                now = time.time()
                if now - last_progress >= progress_every:
                    last_progress = now
                    emit(f"depth {depth}: {len(store)} distinct, "
                         f"{res.states_generated} generated, "
                         f"{res.states_generated / (now - t0):.0f} states/s")

            if not fresh_chunks:
                break
            nxt = {k: np.concatenate([c[k] for c in fresh_chunks])
                   for k in fresh_chunks[0]}
            parents = np.concatenate(fresh_parents)
            actions = np.concatenate(fresh_actions)
            level_base = store.append(nxt, parents.tolist(), actions.tolist())
            level_base -= len(parents)
            frontier = nxt
            self.level_sizes.append(len(parents))
            if max_states and len(store) >= max_states:
                res.error = f"state limit {max_states} reached"
                break
            if max_seconds and time.time() - t0 > max_seconds:
                res.error = f"time budget {max_seconds}s reached"
                break

        res.diameter = depth
        return self._finish(res, store, t0, depth)

    # ------------------------------------------------------------------
    def _materialize(self, tile, flat_idx):
        """Re-run only the surviving lanes to produce their successor
        states: group by action, pad each group to a power of two (few
        compiled variants), and vmap the single action function."""
        kern = self.kern
        flat_idx = np.asarray(flat_idx)
        parent_local = flat_idx // self.L
        lane = flat_idx % self.L
        aids = kern.lane_action[lane]
        params = kern.lane_param[lane]
        n = len(flat_idx)
        out = {}
        order = np.argsort(aids, kind="stable")
        pos = 0
        chunks, backperm = [], np.empty(n, np.int64)
        for aid in np.unique(aids):
            sel = order[aids[order] == aid]
            cap = max(8, 1 << int(np.ceil(np.log2(len(sel)))))
            pad = cap - len(sel)
            gi = np.concatenate([parent_local[sel],
                                 np.zeros(pad, np.int64)])
            gp = np.concatenate([params[sel], np.zeros(pad, np.int32)])
            states = {k: v[gi] for k, v in tile.items()}
            fn = self._mat.get(int(aid))
            if fn is None:
                fn = jax.jit(jax.vmap(kern._action_fns()[int(aid)],
                                      in_axes=(0, 0)))
                self._mat[int(aid)] = fn
            succ, _en = fn(states, jnp.asarray(gp))
            chunk = {k: np.asarray(v[:len(sel)]) for k, v in succ.items()
                     if not k.startswith("_")}
            chunks.append(chunk)
            backperm[sel] = np.arange(pos, pos + len(sel))
            pos += len(sel)
        cat = {k: np.concatenate([c[k] for c in chunks])
               for k in chunks[0]}
        # row i of the result is the successor for flat_idx[i]
        return {k: v[backperm] for k, v in cat.items()}

    def _finish(self, res, store, t0, depth):
        res.distinct_states = len(store)
        res.elapsed = time.time() - t0
        return res

    def _check_invariants_host(self, batch, i):
        """Name the violated invariant for one dense state (decode +
        interpreter evaluation; only used on the violation path)."""
        st = self.codec.decode({k: v[i] for k, v in batch.items()})
        return self.spec.check_invariants(st)

    def _trace(self, store, gid, extra=None):
        """Walk parent pointers to the init state, decode, and emit
        TRACE-format entries (action name + source location)."""
        loc = {a.name: a.location for a in self.spec.actions}
        chain = []
        cur = gid
        while cur is not None:
            parent, aid = store.parents[cur]
            chain.append((store.get(cur), aid))
            cur = parent
        chain.reverse()
        if extra is not None:
            vstate, aid = extra
            chain.append((vstate, aid))
        out = []
        for pos, (dense, aid) in enumerate(chain):
            name = ACTION_NAMES[aid] if aid is not None else None
            out.append(TraceEntry(
                position=pos + 1, action_name=name,
                location=loc.get(name), state=self.codec.decode(dense)))
        return out


def device_bfs_check(spec: SpecModel, max_states=None, max_depth=None,
                     check_deadlock=False, tile_size=32, max_msgs=None,
                     log=None) -> CheckResult:
    """Run the device BFS (message-table growth happens in place)."""
    eng = DeviceBFS(spec, max_msgs=max_msgs, tile_size=tile_size)
    return eng.run(max_states=max_states, max_depth=max_depth,
                   check_deadlock=check_deadlock, log=log)
