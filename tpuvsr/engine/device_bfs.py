"""Device (TPU) breadth-first model checking engine.

This is the reference's hot loop — TLC's BFS worker (SURVEY.md §3.1) —
restructured as a data-parallel XLA pipeline.  Per frontier tile of T
states, entirely on device:

  tile --step_batch--> [T, L] lane successors     (vsr_kernel.step_all)
       --fingerprint--> symmetry-least 128-bit fp (VIEW projection)
       --invariants --> per-successor pass/fail   (checked on *every*
                        generated state — a superset of TLC's
                        fresh-only checking, sound because generated
                        states are reachable)
       --dedup+FPSet--> fresh mask                (engine/fpset.py)
       --compaction --> packed fresh states, transferred host-side only

The host orchestrates tiles, owns the frontier (numpy), and keeps
(parent, action, lane) pointers per state for counterexample
reconstruction in the reference's trace format (TRACE:3-7).

Scale note: frontier + visited states live in host RAM (the device holds
only fingerprints + the working tile), so capacity is host-memory-bound
at ~5 KB/state; fingerprints in HBM at 16 B/state.  Multi-host sharding
is the next tier (SURVEY.md §5 distributed backend).
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from ..core.values import TLAError
from ..models.vsr import ERR_BAG_OVERFLOW, VSRCodec
from ..models.vsr_kernel import ACTION_NAMES, VSRKernel
from .bfs import CheckResult
from .fpset import dedup_batch, empty_table, grow, insert_batch
from .spec import SpecModel
from .trace import TraceEntry


def _value_perm_table(spec, codec):
    """spec.symmetry_perms (ModelValue maps) -> [P, V+1] id table with the
    identity first (kernel takes the min over rows)."""
    V = codec.shape.V
    rows = [np.arange(V + 1, dtype=np.int32)]
    for p in spec.symmetry_perms:
        row = np.arange(V + 1, dtype=np.int32)
        for mv_from, mv_to in p.items():
            row[codec.value_id[mv_from]] = codec.value_id[mv_to]
        rows.append(row)
    return np.stack(rows)


class _StateStore:
    """Host-side registry of visited dense states, appended per batch;
    gid -> state row lookup for trace reconstruction."""

    def __init__(self):
        self.chunks = []          # list of dict-of-np [n_i, ...]
        self.offsets = [0]
        self.parents = []         # gid -> (parent_gid | None, action_id)

    def append(self, states, parent_gids, action_ids):
        n = len(parent_gids)
        if n:
            self.chunks.append(states)
            self.offsets.append(self.offsets[-1] + n)
            self.parents.extend(zip(parent_gids, action_ids))
        return self.offsets[-1]

    def __len__(self):
        return self.offsets[-1]

    def get(self, gid):
        import bisect
        c = bisect.bisect_right(self.offsets, gid) - 1
        row = gid - self.offsets[c]
        return {k: v[row] for k, v in self.chunks[c].items()}


class DeviceBFS:
    def __init__(self, spec: SpecModel, max_msgs=None, tile_size=32,
                 fpset_capacity=1 << 20):
        self.spec = spec
        self.codec = VSRCodec(spec.ev.constants, max_msgs=max_msgs)
        self.kern = VSRKernel(self.codec,
                              perms=_value_perm_table(spec, self.codec))
        self.tile = tile_size
        self.fpset_capacity = fpset_capacity
        self.L = self.kern.n_lanes
        names = list(spec.cfg.invariants)
        inv = self.kern.invariant_fn(names)
        self.inv_names = names
        kern = self.kern

        def hash_dedup(succs, en):
            """fingerprint + invariants + intra-batch dedup; independent
            of the FPSet so a table growth never recompiles it."""
            fps = jax.vmap(kern.fingerprint)(succs)
            inv_ok = jax.vmap(inv)(succs)
            viol = en & ~inv_ok
            err = jnp.where(en, succs["err"], 0)
            err_bag = ((err & ERR_BAG_OVERFLOW) != 0).any()
            err_slot = ((err & ~ERR_BAG_OVERFLOW) != 0).any()
            perm, cand = dedup_batch(fps, en)
            return (fps, perm, cand, viol.any(), jnp.argmax(viol),
                    err_bag, err_slot)

        def pack(succs, fps, perm, fresh):
            """compact globally-fresh lanes to the front for transfer."""
            order = jnp.argsort(~fresh, stable=True)
            sel = perm[order]
            packed = {k: v[sel] for k, v in succs.items()}
            return packed, fps[sel], sel, fresh.sum()

        self._hash = jax.jit(hash_dedup)
        self._pack = jax.jit(pack)

    # ------------------------------------------------------------------
    def run(self, max_states=None, max_depth=None, max_seconds=None,
            check_deadlock=False, log=None,
            progress_every=10.0) -> CheckResult:
        spec, codec, kern = self.spec, self.codec, self.kern
        res = CheckResult()
        t0 = time.time()
        store = _StateStore()
        fp_cap = self.fpset_capacity
        table = empty_table(fp_cap)
        fp_count = 0

        def emit(msg):
            if log:
                log(msg)

        # --- register init states (host path, tiny) -------------------
        init_dense = [codec.encode(st) for st in spec.init_states()]
        init_batch = {k: np.stack([d[k] for d in init_dense])
                      for k in init_dense[0]}
        fps = np.asarray(kern.fingerprint_batch(init_batch))
        keep, seen = [], set()
        for i in range(len(init_dense)):
            key = tuple(fps[i])
            if key not in seen:
                seen.add(key)
                keep.append(i)
        init_batch = {k: v[keep] for k, v in init_batch.items()}
        table, fresh, _ = insert_batch(
            table, jnp.asarray(fps[keep]),
            jnp.ones((len(keep),), bool))
        fp_count += len(keep)
        store.append(init_batch, [None] * len(keep), [None] * len(keep))
        for i in range(len(keep)):
            bad = self._check_invariants_host(init_batch, i)
            if bad:
                res.ok = False
                res.violated_invariant = bad
                res.trace = self._trace(store, i)
                return self._finish(res, store, t0, 0)
        res.states_generated += len(init_dense)
        frontier = init_batch
        level_base = 0
        depth = 0
        last_progress = t0

        self.level_sizes = [len(frontier["status"])]
        while len(frontier["status"]) > 0:
            if max_depth is not None and depth >= max_depth:
                res.error = f"depth limit {max_depth} reached"
                break
            depth += 1
            n_front = len(frontier["status"])
            fresh_chunks, fresh_parents, fresh_actions = [], [], []
            for off in range(0, n_front, self.tile):
                tile = {k: v[off:off + self.tile]
                        for k, v in frontier.items()}
                n_valid = len(tile["status"])
                if n_valid < self.tile:
                    pad = self.tile - n_valid
                    tile = {k: np.concatenate(
                        [v, np.repeat(v[:1], pad, axis=0)])
                        for k, v in tile.items()}
                valid = np.arange(self.tile) < n_valid

                succs, en = kern.step_batch(tile)
                en = en & jnp.asarray(valid)[:, None]
                if check_deadlock:
                    dead = valid & ~np.asarray(en.any(axis=1))
                    if dead.any():
                        gid = level_base + off + int(np.argmax(dead))
                        res.ok = False
                        res.error = "deadlock"
                        res.deadlock_state = self.codec.decode(store.get(gid))
                        res.trace = self._trace(store, gid)
                        res.diameter = depth
                        return self._finish(res, store, t0, depth)
                flat = {k: v.reshape((self.tile * self.L,) + v.shape[2:])
                        for k, v in succs.items()}
                en_flat = en.reshape(-1)
                (fps, perm, cand, has_viol, viol_idx, err_bag,
                 err_slot) = self._hash(flat, en_flat)

                if bool(err_slot):
                    raise TLAError(
                        "dense-layout slot collision (a second DVC or "
                        "recovery response from one source in one view): "
                        "this restart-era interleaving needs the "
                        "multi-slot layout (vsr.py docstring)")
                if bool(err_bag):
                    raise _KernelOverflow()
                res.states_generated += int(np.asarray(en_flat).sum())

                if bool(has_viol):
                    # a generated state violates an invariant: name it
                    # on host and reconstruct the trace
                    vi = int(viol_idx)
                    vstate = {k: np.asarray(v[vi]) for k, v in flat.items()}
                    parent_gid = level_base + off + vi // self.L
                    lane = vi % self.L
                    bad = self._check_invariants_host(
                        {k: v[None] for k, v in vstate.items()}, 0)
                    res.ok = False
                    res.violated_invariant = bad or self.inv_names[0]
                    res.trace = self._trace(
                        store, parent_gid,
                        extra=(vstate, int(kern.lane_action[lane])))
                    res.diameter = depth
                    return self._finish(res, store, t0, depth)

                fps_sorted = fps[perm]
                while True:
                    table, fresh, ovf = insert_batch(table, fps_sorted, cand)
                    packed, pfps, sel, n_fresh = self._pack(
                        flat, fps, perm, fresh)
                    n = int(n_fresh)
                    if n:
                        fp_count += n
                        pack_np = {k: np.asarray(v[:n])
                                   for k, v in packed.items()}
                        sel_np = np.asarray(sel[:n])
                        fresh_chunks.append(pack_np)
                        fresh_parents.append(
                            level_base + off + sel_np // self.L)
                        fresh_actions.append(
                            kern.lane_action[sel_np % self.L])
                    if bool(ovf) or fp_count > 0.6 * fp_cap:
                        # probe overflow dropped unresolved lanes from
                        # the insert: grow the table and re-insert —
                        # already-inserted fingerprints come back as
                        # duplicates, previously unresolved ones as fresh
                        table = grow(table)
                        fp_cap *= 4
                        if bool(ovf):
                            continue
                    break

                now = time.time()
                if now - last_progress >= progress_every:
                    last_progress = now
                    emit(f"depth {depth}: {len(store)} distinct, "
                         f"{res.states_generated} generated, "
                         f"{res.states_generated / (now - t0):.0f} states/s")

            if not fresh_chunks:
                break
            nxt = {k: np.concatenate([c[k] for c in fresh_chunks])
                   for k in fresh_chunks[0]}
            parents = np.concatenate(fresh_parents)
            actions = np.concatenate(fresh_actions)
            level_base = store.append(nxt, parents.tolist(), actions.tolist())
            level_base -= len(parents)
            frontier = nxt
            self.level_sizes.append(len(parents))
            if max_states and len(store) >= max_states:
                res.error = f"state limit {max_states} reached"
                break
            if max_seconds and time.time() - t0 > max_seconds:
                res.error = f"time budget {max_seconds}s reached"
                break

        res.diameter = depth
        return self._finish(res, store, t0, depth)

    # ------------------------------------------------------------------
    def _finish(self, res, store, t0, depth):
        res.distinct_states = len(store)
        res.elapsed = time.time() - t0
        return res

    def _check_invariants_host(self, batch, i):
        """Name the violated invariant for one dense state (decode +
        interpreter evaluation; only used on the violation path)."""
        st = self.codec.decode({k: v[i] for k, v in batch.items()})
        return self.spec.check_invariants(st)

    def _trace(self, store, gid, extra=None):
        """Walk parent pointers to the init state, decode, and emit
        TRACE-format entries (action name + source location)."""
        loc = {a.name: a.location for a in self.spec.actions}
        chain = []
        cur = gid
        while cur is not None:
            parent, aid = store.parents[cur]
            chain.append((store.get(cur), aid))
            cur = parent
        chain.reverse()
        if extra is not None:
            vstate, aid = extra
            chain.append((vstate, aid))
        out = []
        for pos, (dense, aid) in enumerate(chain):
            name = ACTION_NAMES[aid] if aid is not None else None
            out.append(TraceEntry(
                position=pos + 1, action_name=name,
                location=loc.get(name), state=self.codec.decode(dense)))
        return out


class _KernelOverflow(Exception):
    pass


def device_bfs_check(spec: SpecModel, max_states=None, max_depth=None,
                     check_deadlock=False, tile_size=32, max_msgs=None,
                     log=None) -> CheckResult:
    """Run the device BFS, growing the message-slot table on overflow
    (the dense layout's only dynamic bound, vsr.py)."""
    attempts = 0
    while True:
        eng = DeviceBFS(spec, max_msgs=max_msgs, tile_size=tile_size)
        try:
            return eng.run(max_states=max_states, max_depth=max_depth,
                           check_deadlock=check_deadlock, log=log)
        except _KernelOverflow:
            attempts += 1
            if attempts > 3:
                raise TLAError("message table overflow after 3 growths")
            max_msgs = eng.codec.shape.MAX_MSGS * 2
            if log:
                log(f"message table overflow; retrying with "
                    f"MAX_MSGS={max_msgs}")
