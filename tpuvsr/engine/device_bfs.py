"""Device-resident (TPU) breadth-first model checking engine.

This is the reference's hot loop — TLC's BFS worker (SURVEY.md §3.1) —
restructured so an entire BFS level runs ON DEVICE inside one jitted
``lax.while_loop``, with a single host synchronization per chunk of
tiles (round 1 synced ~5x per 32-state tile, which over a tunneled TPU
was the whole runtime).  The tile body is the occupancy-packed
THREE-STAGE pass (ISSUE 10, ``commit="fused"``, the default):

  chunk --guard matrix--> every action's guard over every lane of the
                          whole chunk of tiles, one vmapped pass:
                          EXACT per-action enabled counts (generated /
                          per-action counters, deadlock detection,
                          exact cap-overflow `need` so growth hits the
                          true count and level boundaries calibrate
                          the caps back down onto observed maxima)
  tile  --work queue  --> enabled (state, lane) items packed into
                          dense per-action segments of one tile-local
                          staging queue; ONLY real items are expanded
                          (vsr_kernel), fingerprinted (VIEW +
                          symmetry, incremental 128-bit), and
                          invariant-checked — expand FLOPs scale with
                          `generated`, not sum of static caps
  tile  --single commit-> ONE FPSet insert_core batch + ONE scatter
                          set per tile (vs n_actions of each): a
                          stable first-occurrence dedup mask picks the
                          earliest queue item among duplicate
                          fingerprints (= the per-action commit
                          order), the claim column arbitrates distinct
                          fingerprints racing for a slot, and the
                          headroom check at tile entry keeps inserts
                          and scatters atomic

``commit="per-action"`` preserves the historical body — n_actions
serial guard/compact/expand/insert/scatter phases per tile — and the
two modes are BIT-IDENTICAL in counts, level sizes and traces
(tests/test_commit.py; the failure-cause priority and the
committed-action-prefix rule on a failing tile are replicated
verbatim).  One documented edge: an FPSet PROBE-OVERFLOW pause
(R_FPSET_GROW mid-tile, rare — the proactive between-level growth
keeps chains short) commits the resolvable subset of the single batch
where per-action committed an action prefix, so after re-entry that
tile's next-frontier gids may be ORDERED differently between the
modes; the committed sets, counts, level sizes and trace CONTENT
still agree (both orders dedup to the same exploration).

Full states never leave the device.  The host keeps only the compact
(parent gid, action id, lane param) pointer table, and counterexamples
are reconstructed by REPLAYING the recorded action chain from the
initial state (exactly how the recorded choices determine the states),
then emitted in the reference's trace format (TRACE:3-7).

Pause/resume protocol: growth events (message-table too small, FPSet
load, next-buffer capacity), invariant violations, in-action slot
errors and deadlocks surface as a `reason` code; the level kernel
commits NOTHING for the action that failed, so the host can grow the
relevant structure and re-enter the level at the paused tile — lanes
already committed simply dedup against the FPSet on re-run.

Dispatch pipelining (ISSUE 4): the chunked loop keeps a bounded
window of K level-kernel dispatches in flight (``pipeline=K``,
default 2), chained on device-side (start_t, nn) scalars and blocking
only on the oldest — host-side work overlaps device compute, and the
pause protocol above is exactly what makes speculation safe
(engine/pipeline.py has the drain-and-replay argument).  Results are
bit-identical for every K.

Scale note: fingerprints live in HBM at 16 B/state; the frontier and
next-frontier buffers hold dense states in HBM (~state_size x capacity);
the host holds 10 B/state of trace pointers.  Multi-host sharding is
the next tier (SURVEY.md §5 distributed backend, parallel/sharded_bfs).
"""

from __future__ import annotations

import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from ..core.values import TLAError
from ..models import registry
from ..models.vsr import ERR_BAG_OVERFLOW
from ..obs import RunObserver, closes_observer
from ..resilience.faults import fault_point
from ..resilience.supervisor import Preempted, preempt_signal
from .bfs import CheckResult
from .fpset import (dedup_batch, empty_table, grow, insert_batch,
                    insert_core, lookup_gids, store_gids)
from .spec import SpecModel
from .trace import TraceEntry

I32 = jnp.int32

# level-kernel stop reasons
RUNNING = 0
R_VIOLATION = 2      # an invariant failed on a generated state
R_BAG_GROW = 3       # a successor needs more message-table slots
R_FPSET_GROW = 4     # fingerprint probing exhausted (table too full)
R_NEXT_GROW = 5      # next-frontier buffer out of capacity
R_SLOT_ERR = 6       # dense-layout slot collision (config limitation)
R_DEADLOCK = 7       # a frontier state has no enabled successor
R_EXPAND_GROW = 8    # per-action enabled-lane compaction buffer too small
# 9 is reserved (the sharded step's rank-agreed R_EXPAND_GROW vote)
R_EDGE_FLUSH = 10    # edge append buffer out of headroom (ISSUE 15):
#                      the host drains the committed (src, action, dst)
#                      triples into the CSR builder and re-enters —
#                      the paused tile committed nothing, exactly like
#                      the paged engine's R_NEXT_GROW spill

# Back-compat alias: the perm-table builder lives in the registry now.
_value_perm_table = registry.value_perm_table


def _align8(n):
    """Round an expansion-cap target up to a lane multiple of 8 (keeps
    the compaction shapes TPU-register friendly without inflating the
    occupancy denominator)."""
    return ((int(n) + 7) // 8) * 8


# Largest tile width validated against the pinned fixpoint counts on
# the real TPU (axon): tile=1024 mis-explored the flagship config
# (58,957 distinct vs pinned 43,941 — scripts/tile_sweep.json), an
# unresolved TPU-lowering correctness failure.  Until a re-run sweep
# marks wider tiles `correct: true`, the engine refuses them on
# accelerator backends (CPU lowering is validated at all widths).
MAX_VALIDATED_TPU_TILE = 512


class DeviceBFS:
    def __init__(self, spec: SpecModel, max_msgs=None, tile_size=128,
                 fpset_capacity=1 << 20, hash_mode="incremental",
                 next_capacity=1 << 14, chunk_tiles=64, expand_mult=2,
                 expand_mults=None, model_factory=None, pipeline=2,
                 pack="auto", commit="fused", symmetry="auto",
                 bounds="auto", edges=False, por="off"):
        if commit not in ("fused", "per-action"):
            raise TLAError(f"commit must be 'fused' or 'per-action' "
                           f"(got {commit!r})")
        if edges and not getattr(self, "_edges_on", False):
            # the tile bodies support emission on any engine, but the
            # drain seam (R_EDGE_FLUSH -> host CSR builder) lives in
            # the host-paged run loop
            raise TLAError(
                "edge emission needs the host-paged drain loop; "
                "construct PagedBFS(edges=True) (or run the CLI "
                "temporal path, which does)")
        if (tile_size > MAX_VALIDATED_TPU_TILE
                and os.environ.get("TPUVSR_UNSAFE_TILE") != "1"
                and jax.default_backend() != "cpu"):
            raise TLAError(
                f"tile_size={tile_size} exceeds the largest width "
                f"validated against pinned counts on a TPU backend "
                f"({MAX_VALIDATED_TPU_TILE}; tile=1024 mis-explored on "
                f"axon — scripts/tile_sweep.json).  Set "
                f"TPUVSR_UNSAFE_TILE=1 to override for diagnosis runs.")
        self.spec = spec
        # streamed edge emission (ISSUE 15): set by PagedBFS before
        # this constructor runs (the host-paged engine owns the drain
        # seam); when on, the tile bodies resolve every enabled lane's
        # successor fingerprint to a gid on device and append
        # (src gid, action, dst gid) triples to the edge buffer
        self._edges_on = getattr(self, "_edges_on", False)
        self.tile = tile_size
        self.fpset_capacity = fpset_capacity
        self.hash_mode = hash_mode
        self.next_cap = next_capacity
        self.chunk_tiles = chunk_tiles
        # dispatch-window depth: keep up to `pipeline` level-kernel
        # dispatches in flight, blocking only on the oldest (ISSUE 4;
        # 1 = the fully synchronous pre-pipeline behavior)
        self.pipe_window = max(1, int(pipeline))
        # per-action enabled-lane compaction capacity = tile * mult
        # (each action's cap auto-doubles on its own R_EXPAND_GROW;
        # pass a pre-calibrated per-action vector to skip the growth
        # recompiles); dict forms are resolved against the kernel's
        # action names once the kernel exists (_build)
        self.expand_mults = expand_mults
        self._expand_mult_default = expand_mult
        # level-kernel commit mode (ISSUE 10 tentpole).  "fused" (the
        # default) restructures the tile pass into three stages —
        # chunk-wide guard matrix, work-queue compaction, single-commit
        # tiles — so each tile issues ONE FPSet insert batch and ONE
        # scatter instead of n_actions of each, and the per-action
        # expansion caps are sized by EXACT enabled counts instead of
        # tile-multiple guesses.  "per-action" is the pre-ISSUE-10
        # serial-phase body; results are bit-identical between the two
        # (tests/test_commit.py).
        self.commit = commit
        # fused-mode per-action expansion caps (absolute lane counts,
        # exact-count grown/calibrated; run-scoped — snapshots keep the
        # per-action expand_mults format and a resumed fused run simply
        # re-calibrates)
        self.expand_caps = None
        self._need_seen = None
        self.inv_names = list(spec.cfg.invariants)
        # symmetry canonicalization (ISSUE 11): "auto" = on iff the
        # cfg declares SYMMETRY (TLC's semantics — declaring
        # Permutations IS enabling the reduction); True/False force.
        # When on, a CanonSpec (engine/canon.py) maps every successor
        # to the least element of its symmetry orbit PRE-FINGERPRINT
        # inside the jitted level kernel, so the FPSet and frontier
        # hold one entry per orbit; the kernel itself is built with an
        # identity-only perm table (fold_symmetry=False) — the engine
        # seam, not the P-fold hash, owns the reduction, which makes
        # -symmetry off a real A/B lever
        self._symmetry_req = symmetry
        # model_factory(spec, max_msgs=..) -> (codec, kernel); default
        # is the hand-kernel registry, tests/the CLI can pass the
        # AST-compiled factory (lower/compile.make_compiled_model)
        self._model_factory = model_factory or (
            lambda spec, max_msgs=None: registry.make_model(
                spec, max_msgs=max_msgs, fold_symmetry=False))
        # packed frontier encoding (ISSUE 9): "auto" packs whenever the
        # codec declares plane_bounds (every registered layout + the
        # stub harness); False runs dense; True forces the interchange
        # format even without bounds (ratio 1.0).  Results are
        # bit-identical either way — the pack/unpack round trip is
        # exact for in-range values, which the widths lint pass proves.
        self._pack_req = pack
        # speclint bounds pre-pass (ISSUE 13): "auto" consumes the
        # interval-analysis facts iff the lint gate is live — dead
        # actions pruned from the kernel lane tables, packing
        # tightened to reachable intervals, fused expansion caps
        # seeded from static fanout.  False runs declared widths and
        # full action lists (the A/B lever); results are bit-identical
        # either way (tests/test_bounds.py oracles)
        from .bounds import resolve_bounds
        self._facts = resolve_bounds(spec, bounds)
        self._pruned = []
        # ample-set partial-order reduction (ISSUE 16): consume the
        # independence pass's facts behind the same resolve contract
        # as -bounds, with the soundness blockers (temporal
        # properties, edge emission, non-fused commit) refused here
        # for library callers and at argparse time for the CLI.
        # Constructor default is "off" — the reduction shrinks
        # distinct-state counts, so library callers opt in; the CLI's
        # -por defaults to auto
        from .por import resolve_por
        self._por_facts = resolve_por(
            spec, por,
            temporal=bool(getattr(spec, "temporal_props", ())),
            edges=self._edges_on, commit=self.commit)
        self._por = None
        self._por_kept = 0
        self._por_full = 0
        self._por_amp = 0
        registry.ensure_compile_cache()
        self.debug_checks = registry.ensure_debug_flags()
        self._build(max_msgs)

    # ------------------------------------------------------------------
    # kernel + jitted level construction
    # ------------------------------------------------------------------
    def _build(self, max_msgs):
        """(Re)build codec, kernel, and the jitted level pass for a
        message-table bound; called again on bag growth."""
        spec = self.spec
        self.codec, self.kern = self._model_factory(spec,
                                                    max_msgs=max_msgs)
        # statically dead actions (bounds pass): drop them from the
        # kernel's lane tables — the fused commit's guard matrix and
        # staging queue shrink, and a dead guard is never evaluated.
        # Dead actions are never enabled, so results are bit-identical
        if self._facts is not None and self._facts.dead_actions:
            from .bounds import prune_kernel
            dead = [n for n in self._facts.dead_actions
                    if n in self.kern.action_names]
            if dead and len(dead) < len(self.kern.action_names):
                self.kern = prune_kernel(self.kern, dead)
                self._pruned = dead
        names = self.kern.action_names
        if self.expand_mults is None:
            self.expand_mults = [self._expand_mult_default] * len(names)
        elif isinstance(self.expand_mults, dict):
            base = [self._expand_mult_default] * len(names)
            for n, m in self.expand_mults.items():
                base[names.index(n)] = m
            self.expand_mults = base
        else:
            self.expand_mults = list(self.expand_mults)
        if self.commit == "fused":
            tl = [self.tile * self.kern._lane_count(n) for n in names]
            if self.expand_caps is None:
                # modest static start; the exact-count growth events
                # (and the level-boundary calibration) converge the
                # caps onto the observed per-tile maxima
                self.expand_caps = [min(t, max(8, _align8(self.tile)))
                                    for t in tl]
                # static fanout bounds (ISSUE 13): the bounds pass
                # proves at most `fanout` lanes of an action enable
                # per state, so tile*fanout is a sound initial cap —
                # on exact-bounds fixtures the growth redraw count is
                # ZERO (the cap already covers the true maximum)
                if self._facts is not None:
                    for a, n in enumerate(names):
                        fo = self._facts.fanout.get(n)
                        if fo:
                            self.expand_caps[a] = min(
                                tl[a],
                                max(8, _align8(self.tile * fo)))
            else:
                # re-clamp after a MAX_MSGS rebuild (lane counts grow)
                self.expand_caps = [min(t, max(8, int(c)))
                                    for t, c in zip(tl, self.expand_caps)]
            if self._need_seen is None or \
                    len(self._need_seen) != len(names):
                self._need_seen = np.zeros(len(names), np.int64)
        self.L = self.kern.n_lanes
        self._inv = self.kern.invariant_fn(self.inv_names)
        self._mat = {}          # action id -> jitted single-action fn
        # symmetry canonicalization spec (ISSUE 11): rebuilt with the
        # codec (the group table depends on V, the orbit plane table
        # on the kernel class); None = no reduction.  A custom
        # model_factory may hand us a pre-ISSUE-11 FOLDED kernel (its
        # fingerprint already min-hashes over the group): the fold IS
        # the reduction then — the canon seam stands down rather than
        # double-reduce, and -symmetry off is impossible to honor
        # (the fold is baked into the kernel), so forcing it is a
        # loud error, not a silent no-op
        from .canon import build_canon_spec, kernel_fold_order
        self._sym_fold = kernel_fold_order(self.kern)
        if spec.symmetry_perms and self._sym_fold > 1:
            if self._symmetry_req is False:
                raise TLAError(
                    "symmetry=False requested but the model factory "
                    "built a kernel with a FOLDED perm table (its "
                    "fingerprints min-hash over the group); rebuild "
                    "it with fold_symmetry=False "
                    "(registry.make_model) to make -symmetry off real")
            self._canon = None
        else:
            self._canon = build_canon_spec(spec, self.codec, self.kern,
                                           self._symmetry_req)
        if self._edges_on and (self._canon is not None
                               or self._sym_fold > 1):
            raise TLAError(
                "edge emission requires symmetry off: the behavior "
                "graph's nodes are concrete states, so orbit-folded "
                "fingerprints would merge distinct graph nodes "
                "(liveness keeps its SYMMETRY-off requirement)")
        # packed-frontier spec for THIS codec binding (rebuilt with the
        # codec on bag growth: MAX_MSGS changes the lane count).
        # Bounds tightening (ISSUE 13): reachable intervals intersect
        # the declared plane bounds — fewer bits/state, exact round
        # trip for every reachable state.  _pk_decl keeps the
        # untightened spec for the bound_tightening_ratio gauge
        from .pack import build_pack_spec
        tighten = (self._facts.plane_tighten()
                   if self._facts is not None else {})
        if self._pack_req is False:
            self._pk = None
            self._pk_decl = None
        else:
            self._pk = build_pack_spec(self.codec, spec=spec,
                                       force=self._pack_req is True,
                                       tighten=tighten or None)
            self._pk_decl = (build_pack_spec(
                self.codec, spec=spec,
                force=self._pack_req is True) if tighten else self._pk)
        # ample-set filter bound to THIS kernel (ISSUE 16): rebuilt
        # with the kernel so the action-name alignment survives bag
        # growth and pruning.  _por_active gates the device tables —
        # facts with no eligible action journal their digest but leave
        # every jitted graph untouched (bit-identical to por=off)
        if self._por_facts is not None:
            from .por import PORFilter
            self._por = PORFilter(self._por_facts, self.kern)
        self._por_active = (self._por is not None
                            and self._por.any_eligible
                            and self.commit == "fused")
        self._level = jax.jit(self._make_level(),
                              donate_argnums=(0, 4, 5, 6, 7, 10))
        self._ml = None         # fused pass, built lazily (run_fused)
        self._wl = None         # chained window pass (run_chained)
        # obs accounting: the first dispatch after a (re)jit is charged
        # to the "compile" phase (jit traces+compiles at first call)
        self._fresh_jit = True

    def _expand_caps(self):
        """Per-action enabled-lane compaction capacities, in lanes.
        Fused commit: the absolute exact-count caps (grown to observed
        need, calibrated down at level boundaries).  Per-action commit:
        the historical tile-multiple formula.  PagedBFS sizes its
        next-buffer headroom floor from the same list."""
        kern, T = self.kern, self.tile
        if self.commit == "fused":
            return [min(T * kern._lane_count(n), max(8, int(c)))
                    for n, c in zip(kern.action_names, self.expand_caps)]
        return [min(T * kern._lane_count(nm),
                    max(64, T * self.expand_mults[a]))
                for a, nm in enumerate(kern.action_names)]

    def _guard_matrix(self, kern):
        """Stage 1 of the fused pass: a closure evaluating EVERY
        action's guard over a dense state batch in one vmapped sweep —
        returns the per-action [B, L_a] enabled matrices.  Applied
        chunk-wide by _make_level (exact per-action counts for the
        whole chunk of tiles) and tile-wide inside the multilevel
        body."""
        guards = kern._guard_fns()

        def mat(batch):
            segs = []
            for name, guard in zip(kern.action_names, guards):
                lanes = jnp.arange(kern._lane_count(name), dtype=I32)
                segs.append(jax.vmap(lambda st: jax.vmap(
                    lambda ln, g=guard: g(st, ln))(lanes))(batch))
            return segs

        return mat

    def _tile_body_factory(self):
        """Build the one-tile expansion body shared by the chunked
        level pass (_make_level) and the fused multi-level pass
        (_make_multilevel).  Returns (caps, total_E, make_body) where
        make_body(frontier, n_front, want_deadlock, chunk_ctx=None)
        closes over the (possibly traced) frontier and count;
        ``chunk_ctx`` optionally feeds the body a chunk-wide
        precomputed (dense states, guard matrix, start tile) so the
        fused body consumes the hoisted stage-1 pass instead of
        re-deriving it per tile.

        Packed frontier (ISSUE 9): with a pack spec bound, the at-rest
        frontier and next buffers are ``[cap, words]`` uint32 planes —
        the body unpacks a tile on entry and packs successors on exit,
        so the expansion/fingerprint/invariant pipeline in between is
        UNCHANGED and results stay bit-identical with packing on/off
        (the pack/unpack round trip is exact for in-range values)."""
        if self.commit == "fused":
            return self._fused_body_factory()
        kern = self.kern
        inv = self._inv
        pk = self._pk
        T = self.tile
        # symmetry canonicalization (ISSUE 11): fingerprints are taken
        # on the orbit-least image, which cannot be reconstituted from
        # the parent's per-row hash parts — canon runs force the full
        # hash path (the orbit-factor state cut dwarfs the incremental
        # saving)
        canon = self._canon
        incremental = self.hash_mode == "incremental" and canon is None
        fpf = (canon.fingerprint_fn(kern) if canon is not None
               else kern.fingerprint)

        # per-action compaction capacities (adaptive; R_EXPAND_GROW
        # carries the overflowing action so only it grows)
        caps = self._expand_caps()
        total_E = sum(caps)
        edges_on = self._edges_on
        aid_q_pa = jnp.asarray(np.repeat(
            np.arange(len(caps), dtype=np.int32), caps))

        def make_body(frontier, n_front, want_deadlock, chunk_ctx=None,
                      edge_bases=None, pdepth=None):
            # pdepth is the fused commit's POR level marker; POR is a
            # resolve_por blocker under per-action commit, so it is
            # accepted here only for the shared launcher signature
            F_cap = (frontier.shape[0] if pk is not None
                     else frontier["status"].shape[0])

            def body(c):
                t = c["t"]
                base = t * T
                sidx = base + jnp.arange(T, dtype=I32)
                valid = sidx < n_front
                if pk is not None:
                    # packed at-rest frontier: gather [T, words] rows,
                    # unpack to the dense tile the kernel consumes
                    tile = jax.vmap(pk.unpack)(
                        frontier[jnp.clip(sidx, 0, F_cap - 1)])
                else:
                    tile = {k: v[jnp.clip(sidx, 0, F_cap - 1)]
                            for k, v in frontier.items()}
                if incremental:
                    parts = jax.vmap(kern.parent_parts)(tile)

                slots = c["slots"]
                nb, nbp, nba, nbprm = c["nb"], c["nbp"], c["nba"], c["nbprm"]
                N_cap = nbp.shape[0]
                nn, dist = c["nn"], c["dist"]
                reason, viol = c["reason"], c["viol"]
                en_any = jnp.zeros((T,), bool)
                gen_local = jnp.asarray(0, I32)
                act_local = []      # per-action enabled-lane counts
                grow_aid = c["grow_aid"]

                # headroom check up front: with N_cap - nn >= total_E no
                # scatter can overrun the buffer, so an insert is never
                # committed without its successors landing — which keeps
                # the pause/resume protocol idempotent with no membership
                # query pass.  Edge emission adds the parallel gate on
                # the edge append buffer (full = drain to host, not
                # grow in HBM)
                room_next = (N_cap - nn) >= total_E
                if edges_on:
                    E_cap_e = c["eb_src"].shape[0]
                    room_edge = (E_cap_e - c["edge_n"]) >= total_E
                    gids_v = c["gids"]
                    fp_segs, en_segs_e, pidx_segs_e = [], [], []
                else:
                    room_edge = jnp.asarray(True)
                commit = room_next & room_edge
                reason = jnp.where((reason == RUNNING) & ~room_next,
                                   R_NEXT_GROW, reason)
                reason = jnp.where((reason == RUNNING) & ~room_edge,
                                   R_EDGE_FLUSH, reason)
                viol_any = jnp.asarray(False)
                bag_err = jnp.asarray(False)
                slot_err = jnp.asarray(False)
                ovf_e = jnp.asarray(False)
                ovf_i = jnp.asarray(False)

                for aid, (name, fn, guard) in enumerate(
                        zip(kern.action_names, kern._action_fns(),
                            kern._guard_fns())):
                    L_a = kern._lane_count(name)
                    TL = T * L_a
                    lanes = jnp.arange(L_a, dtype=I32)
                    E_a = caps[aid]

                    # -- phase 1: cheap guard pass over every lane -----
                    en = jax.vmap(lambda st: jax.vmap(
                        lambda ln: guard(st, ln))(lanes))(tile)
                    en = en & valid[:, None]
                    en_any = en_any | en.any(axis=1)
                    en_f = en.reshape(TL)
                    n_en = en_f.sum()
                    gen_local = gen_local + n_en
                    act_local.append(n_en)
                    ovf_a = n_en > E_a
                    grow_aid = jnp.where(ovf_a & ~ovf_e, aid, grow_aid)
                    ovf_e = ovf_e | ovf_a

                    # -- phase 2: expand only the enabled lanes --------
                    (sel,) = jnp.nonzero(en_f, size=E_a, fill_value=TL)
                    sel_ok = sel < TL
                    pidx = jnp.clip(sel // L_a, 0, T - 1).astype(I32)
                    lane_sel = (sel % L_a).astype(I32)
                    st_sel = {k: v[pidx] for k, v in tile.items()}

                    if incremental:
                        parts_sel = jax.tree_util.tree_map(
                            lambda v: v[pidx], parts)

                        def one(st, parts_one, lane, fn=fn, name=name):
                            succ, en1 = fn(kern.seed_touch(st), lane)
                            ri = kern.lane_replica(name, st, lane)
                            fp = kern.fingerprint_incremental(
                                succ, ri, parts_one, st)
                            clean = {k: v for k, v in succ.items()
                                     if not k.startswith("_")}
                            return clean, fp, en1, inv(clean), clean["err"]
                        succ_f, fp, en2, iok, errv = jax.vmap(one)(
                            st_sel, parts_sel, lane_sel)
                    else:
                        def one(st, lane, fn=fn):
                            succ, en1 = fn(st, lane)
                            clean = {k: v for k, v in succ.items()
                                     if not k.startswith("_")}
                            return (clean, fpf(clean), en1,
                                    inv(clean), clean["err"])
                        succ_f, fp, en2, iok, errv = jax.vmap(one)(
                            st_sel, lane_sel)

                    en_s = en2 & sel_ok
                    errv = jnp.where(en_s, errv, 0)
                    viol_l = en_s & ~iok & (errv == 0)
                    a_bag = ((errv & ERR_BAG_OVERFLOW) != 0).any()
                    a_slot = ((errv & ~ERR_BAG_OVERFLOW) != 0).any()
                    have_v = viol_l.any()
                    vidx = jnp.argmax(viol_l)
                    vinfo = jnp.stack([(base + pidx[vidx]).astype(I32),
                                       jnp.asarray(aid, I32),
                                       lane_sel[vidx]])
                    viol = jnp.where(have_v & (viol[0] < 0), vinfo, viol)
                    viol_any = viol_any | have_v
                    bag_err = bag_err | a_bag
                    slot_err = slot_err | a_slot

                    # -- phase 3: insert + scatter, consumed in place --
                    commit_a = (commit & ~have_v & ~a_slot & ~a_bag
                                & ~ovf_a)
                    tbl, fresh, a_ovf_i = insert_core(
                        {"slots": slots}, fp, en_s & commit_a)
                    slots = tbl["slots"]
                    dest = jnp.where(fresh, nn + jnp.cumsum(fresh) - 1,
                                     N_cap).astype(I32)
                    if pk is not None:
                        # pack successors on exit: the next buffer holds
                        # [words] uint32 rows, not dense planes
                        nb = nb.at[dest].set(jax.vmap(pk.pack)(succ_f),
                                             mode="drop")
                    else:
                        for k in nb:
                            nb[k] = nb[k].at[dest].set(succ_f[k],
                                                       mode="drop")
                    nbp = nbp.at[dest].set(base + pidx, mode="drop")
                    nba = nba.at[dest].set(aid, mode="drop")
                    nbprm = nbprm.at[dest].set(lane_sel, mode="drop")
                    nfi = fresh.sum()
                    nn = nn + nfi
                    dist = dist + nfi
                    ovf_i = ovf_i | a_ovf_i
                    commit = commit_a & ~a_ovf_i
                    if edges_on:
                        # fresh gids stored UNGATED (mirrors insert
                        # persistence across a pause); triples are
                        # staged and appended once at tile end, gated
                        # on the whole tile committing — the same
                        # exactly-once discipline as `gen`
                        gids_v = store_gids(
                            slots, gids_v, fp,
                            (edge_bases[1] + dest).astype(I32), fresh)
                        fp_segs.append(fp)
                        en_segs_e.append(en_s)
                        pidx_segs_e.append(pidx)

                # failure cause priority: violation > slot error > bag
                # growth > expand-capacity > fpset growth (next-capacity
                # was folded in up front)
                new_reason = jnp.where(
                    viol_any, R_VIOLATION,
                    jnp.where(slot_err, R_SLOT_ERR,
                              jnp.where(bag_err, R_BAG_GROW,
                                        jnp.where(ovf_e, R_EXPAND_GROW,
                                                  jnp.where(ovf_i,
                                                            R_FPSET_GROW,
                                                            RUNNING)))))
                reason = jnp.where(reason == RUNNING, new_reason, reason)

                dead = valid & ~en_any
                dl = want_deadlock & commit & dead.any()
                reason = jnp.where(dl & (reason == RUNNING),
                                   R_DEADLOCK, reason)
                dead_i = jnp.where(dl, base + jnp.argmax(dead), c["dead"])
                # per-action expansion counters ride the carry as an
                # on-device accumulator (ISSUE 4 satellite) — same
                # commit gating as `gen`, so sum(act) == gen always
                act_vec = jnp.stack(act_local).astype(jnp.uint32)
                ret = {
                    "t": jnp.where(commit & (reason == RUNNING),
                                   t + 1, t),
                    "reason": reason, "viol": viol, "dead": dead_i,
                    "grow_aid": grow_aid,
                    # per-action mode sizes growth by doubling; the
                    # need vector only carries data in fused commit
                    "need": c["need"],
                    "slots": slots,
                    "nb": nb, "nbp": nbp, "nba": nba, "nbprm": nbprm,
                    "nn": nn, "dist": dist,
                    "gen": c["gen"] + jnp.where(commit, gen_local, 0),
                    "act": c["act"] + jnp.where(commit, act_vec,
                                                jnp.uint32(0)),
                }
                if edges_on:
                    # one staged emission at tile end (action-major
                    # queue order = the fused body's), gated on the
                    # final commit flag — a tile that paused or failed
                    # emits nothing and re-emits whole on re-entry
                    fp_q = jnp.concatenate(fp_segs)
                    emit = jnp.concatenate(en_segs_e) & commit
                    pidx_q = jnp.concatenate(pidx_segs_e)
                    dst_g = lookup_gids({"slots": slots}, gids_v,
                                        fp_q, emit)
                    edst = jnp.where(
                        emit, c["edge_n"] + jnp.cumsum(emit) - 1,
                        E_cap_e)
                    ret["gids"] = gids_v
                    ret["eb_src"] = c["eb_src"].at[edst].set(
                        (edge_bases[0] + base + pidx_q).astype(I32),
                        mode="drop")
                    ret["eb_aid"] = c["eb_aid"].at[edst].set(
                        aid_q_pa, mode="drop")
                    ret["eb_dst"] = c["eb_dst"].at[edst].set(
                        dst_g, mode="drop")
                    ret["edge_n"] = c["edge_n"] + emit.sum()
                return ret

            return body

        return caps, total_E, make_body

    def _fused_body_factory(self):
        """The ISSUE 10 tentpole body: one frontier tile flows through
        three stages —

        (1) **guard matrix**: every action's guard over every lane of
            the tile in one sweep (no expansion interleaved), yielding
            EXACT per-action enabled counts: they drive the generated/
            per-action counters, deadlock detection, and exact
            cap-overflow events (the ``need`` vector carries the
            observed per-action maxima so growth is sized to the real
            count, not a doubling guess);
        (2) **work-queue compaction**: each action's enabled
            (state, lane) items are packed into a dense per-action
            segment of one tile-local staging queue (action-major, so
            queue order == the per-action commit order) and ONLY those
            lanes are expanded/fingerprinted/invariant-checked;
        (3) **single-commit**: the staged segments are committed with
            ONE FPSet ``insert_core`` batch and ONE scatter set per
            tile (vs n_actions of each).  A stable first-occurrence
            dedup mask makes the intra-batch winner for duplicate
            fingerprints the earliest queue item — exactly the action
            order the per-action body commits in — and the
            failure-cause priority (violation > slot > bag >
            expand-grow > fpset-grow) plus the committed-action-prefix
            rule on a failing tile are preserved verbatim, so results
            are bit-identical to commit="per-action"."""
        kern = self.kern
        inv = self._inv
        pk = self._pk
        T = self.tile
        # canon runs hash the orbit-least image — full hash path only
        # (see _tile_body_factory)
        canon = self._canon
        incremental = self.hash_mode == "incremental" and canon is None
        fpf = (canon.fingerprint_fn(kern) if canon is not None
               else kern.fingerprint)
        n_act = len(kern.action_names)
        caps = self._expand_caps()
        total_E = sum(caps)
        caps_v = jnp.asarray(caps, I32)
        aid_q = jnp.asarray(np.repeat(np.arange(n_act, dtype=np.int32),
                                      caps))
        guard_mat = self._guard_matrix(kern)
        edges_on = self._edges_on
        # ample-set POR (ISSUE 16): amat[a, b] says "expanding only a
        # is safe given an enabled b" (por.PORFilter); qoff slices the
        # action-major staging queue back into per-action segments for
        # the kept-lane counters.  POR and edge emission are mutually
        # exclusive (resolve_por blocker), so the FPSet gids column
        # has exactly one meaning per run: graph node ids under
        # -edges, C3 level markers under -por
        por_active = self._por_active
        if por_active:
            assert not edges_on
            amat_dev = jnp.asarray(self._por.amat)
            qoff = np.concatenate(([0], np.cumsum(caps))).astype(int)

        def make_body(frontier, n_front, want_deadlock, chunk_ctx=None,
                      edge_bases=None, pdepth=None):
            F_cap = (frontier.shape[0] if pk is not None
                     else frontier["status"].shape[0])

            def body(c):
                t = c["t"]
                base = t * T
                sidx = base + jnp.arange(T, dtype=I32)
                valid = sidx < n_front
                if chunk_ctx is not None:
                    cstates, csegs, c_start = chunk_ctx
                    off = (t - c_start) * T
                    tile = {k: jax.lax.dynamic_slice_in_dim(v, off, T)
                            for k, v in cstates.items()}
                    en_segs = [jax.lax.dynamic_slice_in_dim(s, off, T)
                               for s in csegs]
                else:
                    if pk is not None:
                        tile = jax.vmap(pk.unpack)(
                            frontier[jnp.clip(sidx, 0, F_cap - 1)])
                    else:
                        tile = {k: v[jnp.clip(sidx, 0, F_cap - 1)]
                                for k, v in frontier.items()}
                    en_segs = guard_mat(tile)
                # -- stage 1: guard matrix -> exact per-action counts --
                en_segs = [e & valid[:, None] for e in en_segs]
                cnts = jnp.stack([e.sum(dtype=I32) for e in en_segs])
                en_any = jnp.zeros((T,), bool)
                for e in en_segs:
                    en_any = en_any | e.any(axis=1)
                gen_local = cnts.sum()
                ovf_vec = cnts > caps_v
                ovf_e = ovf_vec.any()
                grow_aid = jnp.where(ovf_e,
                                     jnp.argmax(ovf_vec).astype(I32),
                                     c["grow_aid"])
                need = jnp.maximum(c["need"], cnts.astype(jnp.uint32))
                if por_active:
                    # ample candidate per frontier row: one gather of
                    # the enabled bitmask against the independence
                    # matrix — row r may shortcut iff some enabled
                    # action conflicts with NO enabled action
                    # (ineligible rows of amat are all-False, so they
                    # self-veto).  Computed on the UNMASKED guard
                    # matrix, like en_any/deadlock and need/caps —
                    # the reduction only ever touches the commit
                    en_act = jnp.stack([e.any(axis=1) for e in en_segs],
                                       axis=1)               # [T, n_act]
                    conflict = (en_act.astype(I32)
                                @ (~amat_dev).astype(I32).T) > 0
                    cand = en_act & ~conflict
                    has_cand = cand.any(axis=1)
                    aid_star = jnp.argmax(cand, axis=1).astype(I32)

                slots = c["slots"]
                nb, nbp, nba, nbprm = c["nb"], c["nbp"], c["nba"], c["nbprm"]
                N_cap = nbp.shape[0]
                nn, dist = c["nn"], c["dist"]
                reason, viol = c["reason"], c["viol"]
                # same headroom gate as the per-action body: with
                # N_cap - nn >= total_E no scatter can overrun, so an
                # insert is never committed without its successors.
                # Edge emission adds the parallel gate on the edge
                # append buffer (a full one means "drain to the host
                # CSR builder", not "grow in HBM")
                room_next = (N_cap - nn) >= total_E
                if edges_on:
                    E_cap_e = c["eb_src"].shape[0]
                    room_edge = (E_cap_e - c["edge_n"]) >= total_E
                else:
                    room_edge = jnp.asarray(True)
                commit0 = room_next & room_edge
                reason = jnp.where((reason == RUNNING) & ~room_next,
                                   R_NEXT_GROW, reason)
                reason = jnp.where((reason == RUNNING) & ~room_edge,
                                   R_EDGE_FLUSH, reason)

                # -- stage 2: work-queue compaction + expansion --------
                if incremental:
                    parts = jax.vmap(kern.parent_parts)(tile)
                succ_segs, fp_segs, en_s_segs = [], [], []
                pidx_segs, lane_segs = [], []
                viol_any = jnp.asarray(False)
                bag_err = jnp.asarray(False)
                slot_err = jnp.asarray(False)
                first_bad = jnp.asarray(n_act, I32)
                for aid, (name, fn) in enumerate(
                        zip(kern.action_names, kern._action_fns())):
                    L_a = kern._lane_count(name)
                    TL = T * L_a
                    E_a = caps[aid]
                    en_f = en_segs[aid].reshape(TL)
                    (sel,) = jnp.nonzero(en_f, size=E_a, fill_value=TL)
                    sel_ok = sel < TL
                    pidx = jnp.clip(sel // L_a, 0, T - 1).astype(I32)
                    lane_sel = (sel % L_a).astype(I32)
                    st_sel = {k: v[pidx] for k, v in tile.items()}

                    if incremental:
                        parts_sel = jax.tree_util.tree_map(
                            lambda v: v[pidx], parts)

                        def one(st, parts_one, lane, fn=fn, name=name):
                            succ, en1 = fn(kern.seed_touch(st), lane)
                            ri = kern.lane_replica(name, st, lane)
                            fp = kern.fingerprint_incremental(
                                succ, ri, parts_one, st)
                            clean = {k: v for k, v in succ.items()
                                     if not k.startswith("_")}
                            return clean, fp, en1, inv(clean), clean["err"]
                        succ_f, fp, en2, iok, errv = jax.vmap(one)(
                            st_sel, parts_sel, lane_sel)
                    else:
                        def one(st, lane, fn=fn):
                            succ, en1 = fn(st, lane)
                            clean = {k: v for k, v in succ.items()
                                     if not k.startswith("_")}
                            # ISSUE 11 commit stage: the fingerprint
                            # is taken on the canonical orbit image
                            # (fpf) while the staged queue keeps the
                            # generated state — orbit-mates dedup to
                            # one committed representative
                            return (clean, fpf(clean), en1,
                                    inv(clean), clean["err"])
                        succ_f, fp, en2, iok, errv = jax.vmap(one)(
                            st_sel, lane_sel)

                    en_s = en2 & sel_ok
                    errv = jnp.where(en_s, errv, 0)
                    viol_l = en_s & ~iok & (errv == 0)
                    a_bag = ((errv & ERR_BAG_OVERFLOW) != 0).any()
                    a_slot = ((errv & ~ERR_BAG_OVERFLOW) != 0).any()
                    have_v = viol_l.any()
                    vidx = jnp.argmax(viol_l)
                    vinfo = jnp.stack([(base + pidx[vidx]).astype(I32),
                                       jnp.asarray(aid, I32),
                                       lane_sel[vidx]])
                    viol = jnp.where(have_v & (viol[0] < 0), vinfo, viol)
                    viol_any = viol_any | have_v
                    bag_err = bag_err | a_bag
                    slot_err = slot_err | a_slot
                    # committed-prefix rule: every queue item of an
                    # action at or past the FIRST failing one commits
                    # nothing (identical to the per-action body's
                    # carried commit flag going false there)
                    bad_a = have_v | a_slot | a_bag | ovf_vec[aid]
                    first_bad = jnp.minimum(
                        first_bad, jnp.where(bad_a, aid, n_act))
                    succ_segs.append(succ_f)
                    fp_segs.append(fp)
                    en_s_segs.append(en_s)
                    pidx_segs.append(pidx)
                    lane_segs.append(lane_sel)

                succ_q = {k: jnp.concatenate([s[k] for s in succ_segs])
                          for k in succ_segs[0]}
                fp_q = jnp.concatenate(fp_segs)
                en_q = jnp.concatenate(en_s_segs)
                pidx_q = jnp.concatenate(pidx_segs)
                lane_q = jnp.concatenate(lane_segs)

                # -- stage 3: ONE insert batch + ONE scatter per tile --
                keep_q = en_q
                if por_active:
                    # C3 proviso (timing-immune level markers): a row
                    # takes the ample shortcut only if its ample
                    # successor is FRESH — absent from the visited set
                    # (-1) or committed while generating THIS level
                    # (marker pdepth+1).  A marker <= pdepth means the
                    # successor closes a potential cycle at this or an
                    # earlier level: fall back to full expansion.
                    # Probed on the PRE-insert slots, so a paused
                    # tile's re-entry sees its own earlier inserts as
                    # marker pdepth+1 (= fresh) and repeats the same
                    # decision bit-identically.  Violations/deadlock/
                    # need stay on the full en_q (stages 1-2 above)
                    is_amp = (en_q & has_cand[pidx_q]
                              & (aid_q == aid_star[pidx_q]))
                    g = lookup_gids({"slots": slots}, c["gids"],
                                    fp_q, is_amp)
                    old_i = is_amp & (g >= 0) & (g <= pdepth)
                    amp_bad = jnp.zeros((T,), bool).at[pidx_q].max(old_i)
                    take = has_cand & ~amp_bad
                    keep_q = en_q & (~take[pidx_q]
                                     | (aid_q == aid_star[pidx_q]))
                mcommit = keep_q & (aid_q < first_bad) & commit0
                # stable first-occurrence dedup: the winner among equal
                # fingerprints is the earliest queue item (= earliest
                # action, matching the per-action commit order); the
                # FPSet claim column then only has to arbitrate
                # distinct fingerprints racing for one probe slot
                perm, keep = dedup_batch(fp_q, mcommit)
                canon = jnp.zeros((total_E,), bool).at[perm].set(keep)
                tbl, fresh, ovf_i = insert_core(
                    {"slots": slots}, fp_q, canon)
                slots = tbl["slots"]
                dest = jnp.where(fresh, nn + jnp.cumsum(fresh) - 1,
                                 N_cap).astype(I32)
                if pk is not None:
                    nb = nb.at[dest].set(jax.vmap(pk.pack)(succ_q),
                                         mode="drop")
                else:
                    for k in nb:
                        nb[k] = nb[k].at[dest].set(succ_q[k],
                                                   mode="drop")
                nbp = nbp.at[dest].set(base + pidx_q, mode="drop")
                nba = nba.at[dest].set(aid_q, mode="drop")
                nbprm = nbprm.at[dest].set(lane_q, mode="drop")
                nfi = fresh.sum()
                nn = nn + nfi
                dist = dist + nfi
                commit = commit0 & (first_bad >= n_act) & ~ovf_i

                # failure cause priority: violation > slot error > bag
                # growth > expand-capacity > fpset growth (same order
                # as the per-action body)
                new_reason = jnp.where(
                    viol_any, R_VIOLATION,
                    jnp.where(slot_err, R_SLOT_ERR,
                              jnp.where(bag_err, R_BAG_GROW,
                                        jnp.where(ovf_e, R_EXPAND_GROW,
                                                  jnp.where(ovf_i,
                                                            R_FPSET_GROW,
                                                            RUNNING)))))
                reason = jnp.where(reason == RUNNING, new_reason, reason)

                dead = valid & ~en_any
                dl = want_deadlock & commit & dead.any()
                reason = jnp.where(dl & (reason == RUNNING),
                                   R_DEADLOCK, reason)
                dead_i = jnp.where(dl, base + jnp.argmax(dead), c["dead"])
                ret = {
                    "t": jnp.where(commit & (reason == RUNNING),
                                   t + 1, t),
                    "reason": reason, "viol": viol, "dead": dead_i,
                    "grow_aid": grow_aid, "need": need,
                    "slots": slots,
                    "nb": nb, "nbp": nbp, "nba": nba, "nbprm": nbprm,
                    "nn": nn, "dist": dist,
                    "gen": c["gen"] + jnp.where(commit, gen_local, 0),
                    "act": c["act"] + jnp.where(
                        commit, cnts.astype(jnp.uint32), jnp.uint32(0)),
                }
                if por_active:
                    # gen/act count the KEPT expansions (they feed
                    # states_generated and action_expansions, which
                    # must describe the reduced run); gfull keeps the
                    # unreduced count for the por_cut_ratio gauge, amp
                    # counts rows where the shortcut dropped real work
                    kept_act = jnp.stack(
                        [keep_q[qoff[a]:qoff[a + 1]].sum(dtype=I32)
                         for a in range(n_act)])
                    ret["gen"] = c["gen"] + jnp.where(
                        commit, kept_act.sum(), 0)
                    ret["act"] = c["act"] + jnp.where(
                        commit, kept_act.astype(jnp.uint32),
                        jnp.uint32(0))
                    ret["gfull"] = c["gfull"] + jnp.where(
                        commit, gen_local, 0)
                    n_en_row = en_act.sum(axis=1, dtype=I32)
                    ret["amp"] = c["amp"] + jnp.where(
                        commit,
                        (take & (n_en_row > 1)).sum(dtype=I32), 0)
                    # level markers ride the insert UNGATED (mask =
                    # fresh), mirroring the edge-gid persistence rule:
                    # insert_core mutates slots even on a tile that
                    # ends up pausing, so the marker must land beside
                    # the fingerprint for re-entry to probe
                    ret["gids"] = store_gids(
                        slots, c["gids"], fp_q,
                        jnp.full((total_E,), 1, I32) * (pdepth + 1),
                        fresh)
                if edges_on:
                    # edge emission (ISSUE 15): stage 3 already holds
                    # (source row, action, successor fp) for every
                    # enabled lane, fresh and duplicate — the two
                    # things the two-pass re-expansion used to
                    # recompute.  Fresh states' gids (gid_base + next-
                    # buffer row) are stored next to their slots
                    # UNGATED, mirroring insert persistence across a
                    # pause; triples append only when the tile COMMITS
                    # (the `gen` discipline), so a paused tile's
                    # re-entry emits exactly once, with its already-
                    # committed lanes resolving as duplicates
                    src_base, gid_base = edge_bases
                    gids_v = store_gids(
                        slots, c["gids"], fp_q,
                        (gid_base + dest).astype(I32), fresh)
                    emit = en_q & commit
                    dst_g = lookup_gids({"slots": slots}, gids_v,
                                        fp_q, emit)
                    edst = jnp.where(
                        emit, c["edge_n"] + jnp.cumsum(emit) - 1,
                        E_cap_e)
                    ret["gids"] = gids_v
                    ret["eb_src"] = c["eb_src"].at[edst].set(
                        (src_base + base + pidx_q).astype(I32),
                        mode="drop")
                    ret["eb_aid"] = c["eb_aid"].at[edst].set(
                        aid_q, mode="drop")
                    ret["eb_dst"] = c["eb_dst"].at[edst].set(
                        dst_g, mode="drop")
                    ret["edge_n"] = c["edge_n"] + emit.sum()
                return ret

            return body

        return caps, total_E, make_body

    def _make_level(self):
        T = self.tile
        K = self.chunk_tiles
        _caps, _tot, make_body = self._tile_body_factory()
        fused = self.commit == "fused"
        pk = self._pk
        kern = self.kern
        guard_mat = self._guard_matrix(kern) if fused else None

        por_active = self._por_active

        def level(table, frontier, n_front, start_t,
                  nb, nbp, nba, nbprm, n_next0, want_deadlock,
                  eb, edge_meta, pdepth=None):
            # `table` bundles the FPSet slots (+ the parallel gid
            # column in edge-emission mode); `eb` is None or the
            # (src, aid, dst) edge append buffers — DONATED, they are
            # rewritten every dispatch — while `edge_meta` carries the
            # chained fill scalar `n` plus the src_base/gid_base
            # offsets and is NOT donated (the pipelined collect reads
            # the fill level back after newer dispatches consumed the
            # buffers) — ISSUE 15
            n_tiles = (n_front + T - 1) // T
            chunk_ctx = None
            need0 = jnp.zeros((len(_caps),), jnp.uint32)
            if fused:
                # chunk-wide guard matrix (ISSUE 10 stage 1): evaluate
                # every guard for the WHOLE chunk of tiles in one
                # vmapped pass before the tile loop runs — the body
                # slices its tile's rows out, and the exact per-tile
                # per-action counts make a cap-overflow pause report
                # the exact need across the whole chunk (the host
                # grows once, not once per tile)
                F_cap = (frontier.shape[0] if pk is not None
                         else frontier["status"].shape[0])
                cidx = start_t * T + jnp.arange(K * T, dtype=I32)
                cvalid = cidx < n_front
                gidx = jnp.clip(cidx, 0, F_cap - 1)
                if pk is not None:
                    cstates = jax.vmap(pk.unpack)(frontier[gidx])
                else:
                    cstates = {k: v[gidx] for k, v in frontier.items()}
                csegs = [e & cvalid[:, None] for e in guard_mat(cstates)]
                need0 = jnp.stack(
                    [e.reshape(K, -1).sum(axis=1, dtype=I32).max()
                     for e in csegs]).astype(jnp.uint32)
                chunk_ctx = (cstates, csegs, start_t)

            def cond(c):
                return ((c["t"] < n_tiles) & (c["t"] < start_t + K)
                        & (c["reason"] == RUNNING))

            edge_bases = (None if eb is None
                          else (edge_meta["src_base"],
                                edge_meta["gid_base"]))
            body = make_body(frontier, n_front, want_deadlock,
                             chunk_ctx=chunk_ctx,
                             edge_bases=edge_bases, pdepth=pdepth)
            init = {
                "t": jnp.asarray(start_t, I32),
                "reason": jnp.asarray(RUNNING, I32),
                "viol": jnp.full((3,), -1, I32),
                "dead": jnp.asarray(-1, I32),
                "grow_aid": jnp.asarray(-1, I32),
                "need": need0,
                "slots": table["slots"],
                "nb": nb, "nbp": nbp, "nba": nba, "nbprm": nbprm,
                "nn": jnp.asarray(n_next0, I32),
                "dist": jnp.asarray(0, I32),
                "gen": jnp.asarray(0, I32),
                "act": jnp.zeros((len(_caps),), jnp.uint32),
            }
            if eb is not None:
                init["gids"] = table["gids"]
                init["eb_src"], init["eb_aid"], init["eb_dst"] = eb
                init["edge_n"] = edge_meta["n"]
            if por_active:
                init["gids"] = table["gids"]
                init["gfull"] = jnp.asarray(0, I32)
                init["amp"] = jnp.asarray(0, I32)
            return jax.lax.while_loop(cond, body, init)

        return level

    def _make_multilevel(self):
        """The fused pass: an OUTER device while_loop over whole BFS
        levels (ping-pong frontier buffers, on-device trace-pointer and
        level-size accumulation), so a run to fixpoint is ONE dispatch
        with zero per-level host syncs — on a remote/tunneled TPU the
        per-level round-trips are the whole runtime (BENCH r4: 1654
        distinct/s fused vs 26.6 s ~ 1.1 s/level unfused for a 24-level
        space).  Pause protocol is unchanged: growth events exit the
        outer loop with (start_t, nn, gen_level) preserved so the host
        grows the structure and re-enters mid-level."""
        if self._edges_on:
            raise TLAError(
                "edge emission needs the host in the loop to drain "
                "the append buffer into the CSR builder; the fused/"
                "chained multilevel passes cannot stream edges — run "
                "the chunked paged engine")
        T = self.tile
        _caps, _tot, make_body = self._tile_body_factory()
        por_active = self._por_active

        def multilevel(slots, front, nb, nbp, nba, nbprm,
                       tpp, tpa, tpm, lvl_buf,
                       n_front, start_t, nn0, gen_level0, depth0,
                       level_base0, fp_count0,
                       want_deadlock, max_depth, max_states, max_lvls,
                       tiles0, tile_budget,
                       gids=None, gfull_level0=None, amp_level0=None):
            F_cap = nbp.shape[0]
            TP_CAP = tpp.shape[0]
            LVL_CAP = lvl_buf.shape[0]
            # max_lvls (traced, <= LVL_CAP) bounds levels per dispatch
            # so the host can check wall-clock budgets between
            # dispatches without recompiling.  tile_budget (traced)
            # bounds the COMMITTED TILES per dispatch instead — the
            # cross-level chaining mode (run_chained, ISSUE 9) gives
            # each dispatch a chunk-sized budget and keeps a K-deep
            # window of them in flight; the fused mode passes 2^31-1
            # so its behavior is unchanged.  A budget boundary can land
            # MID-LEVEL (start_t/nn/gen_level carry the partial level,
            # exactly like a growth pause), so the window never drains
            # at a level transition.
            idx = jnp.arange(F_cap, dtype=I32)

            def ocond(c):
                return ((c["reason"] == RUNNING) & (c["n_front"] > 0)
                        & (c["depth"] < max_depth)
                        & (c["fp_count"] < max_states)
                        & (c["lvl_cur"] < max_lvls)
                        & (c["tiles"] < tile_budget)
                        & (c["level_base"] + c["n_front"] + F_cap
                           <= TP_CAP))

            def obody(c):
                n_front_l = c["n_front"]
                n_tiles = (n_front_l + T - 1) // T
                # POR C3: the frontier being expanded sits at level
                # c["depth"], which is exactly the marker threshold
                body = make_body(c["front"], n_front_l, want_deadlock,
                                 pdepth=c["depth"] if por_active
                                 else None)
                # remaining per-dispatch tile budget, as an inner
                # bound.  Saturated: the fused mode's 2^31-1 sentinel
                # budget added to a carried start_t > 0 (a re-entry
                # after a mid-level growth pause) must not wrap int32
                # — a wrapped-negative t_stop would make the inner
                # loop a permanent no-op and hang the outer fixpoint
                t_stop = c["start_t"] + jnp.minimum(
                    tile_budget - c["tiles"], jnp.int32(1 << 30))

                def icond(ic):
                    return ((ic["t"] < n_tiles) & (ic["t"] < t_stop)
                            & (ic["reason"] == RUNNING))

                iinit = {
                    "t": c["start_t"],
                    "reason": jnp.asarray(RUNNING, I32),
                    "viol": jnp.full((3,), -1, I32),
                    "dead": jnp.asarray(-1, I32),
                    "grow_aid": jnp.asarray(-1, I32),
                    "need": c["need"],
                    "slots": c["slots"],
                    "nb": c["nb"], "nbp": c["nbp"], "nba": c["nba"],
                    "nbprm": c["nbprm"],
                    "nn": c["nn"],
                    "dist": jnp.asarray(0, I32),
                    "gen": c["gen_level"],
                    "act": c["act"],
                }
                if por_active:
                    iinit["gids"] = c["gids"]
                    iinit["gfull"] = c["gfull_level"]
                    iinit["amp"] = c["amp_level"]
                r = jax.lax.while_loop(icond, body, iinit)
                # level committed only when every tile ran; a budget
                # stop mid-level exits the outer loop with the partial
                # (start_t, nn, gen_level) carried — no swap
                committed = (r["reason"] == RUNNING) & (r["t"] >= n_tiles)
                n_next = r["nn"]
                # gids of the completed level start right after the
                # current frontier's; stable across pause/resume since
                # nn persists
                dest_base = c["level_base"] + n_front_l

                live = committed & (idx < n_next)
                sdest = jnp.where(live, dest_base + idx, TP_CAP)
                tpp = c["tpp"].at[sdest].set(
                    r["nbp"] + c["level_base"], mode="drop")
                tpa = c["tpa"].at[sdest].set(r["nba"], mode="drop")
                tpm = c["tpm"].at[sdest].set(r["nbprm"], mode="drop")
                # record only non-empty levels (run() parity: the final
                # expansion that generates nothing is counted in depth
                # but never appended to level_sizes)
                record = committed & (n_next > 0)
                lvl_buf = c["lvl_buf"].at[
                    jnp.where(record, c["lvl_cur"], LVL_CAP)
                ].set(n_next, mode="drop")

                # ping-pong: the completed level's buffer becomes the
                # frontier, the old frontier becomes scratch
                swap = committed
                front = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(swap, a, b),
                    r["nb"], c["front"])
                nb = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(swap, a, b),
                    c["front"], r["nb"])
                ext = {}
                if por_active:
                    # gfull/amp mirror gen's swap discipline: the
                    # completed level's deltas fold into the dispatch
                    # totals, a partial level rides the *_level carry
                    ext = {
                        "gids": r["gids"],
                        "gfull_level": jnp.where(swap, 0, r["gfull"]),
                        "gfull": c["gfull"] + jnp.where(
                            swap, r["gfull"], 0),
                        "amp_level": jnp.where(swap, 0, r["amp"]),
                        "amp": c["amp"] + jnp.where(swap, r["amp"], 0),
                    }
                return {
                    **ext,
                    "slots": r["slots"],
                    "front": front, "nb": nb,
                    "nbp": r["nbp"], "nba": r["nba"],
                    "nbprm": r["nbprm"],
                    "tpp": tpp, "tpa": tpa, "tpm": tpm,
                    "lvl_buf": lvl_buf,
                    "n_front": jnp.where(swap, n_next, n_front_l),
                    "start_t": jnp.where(swap, 0, r["t"]),
                    "nn": jnp.where(swap, 0, n_next),
                    "gen_level": jnp.where(swap, 0, r["gen"]),
                    "gen": c["gen"] + jnp.where(swap, r["gen"], 0),
                    "depth": c["depth"] + jnp.where(swap, 1, 0),
                    "level_base": jnp.where(swap, dest_base,
                                            c["level_base"]),
                    "fp_count": c["fp_count"] + r["dist"],
                    "lvl_cur": c["lvl_cur"] + jnp.where(record, 1, 0),
                    "tiles": c["tiles"] + (r["t"] - c["start_t"]),
                    "reason": r["reason"],
                    "viol": r["viol"], "dead": r["dead"],
                    "grow_aid": r["grow_aid"], "need": r["need"],
                    "act": r["act"],
                }

            init = {
                "slots": slots, "front": front, "nb": nb,
                "nbp": nbp, "nba": nba, "nbprm": nbprm,
                "tpp": tpp, "tpa": tpa, "tpm": tpm, "lvl_buf": lvl_buf,
                "n_front": jnp.asarray(n_front, I32),
                "start_t": jnp.asarray(start_t, I32),
                "nn": jnp.asarray(nn0, I32),
                "gen_level": jnp.asarray(gen_level0, I32),
                "gen": jnp.asarray(0, I32),
                "depth": jnp.asarray(depth0, I32),
                "level_base": jnp.asarray(level_base0, I32),
                "fp_count": jnp.asarray(fp_count0, I32),
                "lvl_cur": jnp.asarray(0, I32),
                "tiles": jnp.asarray(tiles0, I32),
                "reason": jnp.asarray(RUNNING, I32),
                "viol": jnp.full((3,), -1, I32),
                "dead": jnp.asarray(-1, I32),
                "grow_aid": jnp.asarray(-1, I32),
                "need": jnp.zeros((len(_caps),), jnp.uint32),
                "act": jnp.zeros((len(_caps),), jnp.uint32),
            }
            if por_active:
                init["gids"] = gids
                init["gfull_level"] = jnp.asarray(gfull_level0, I32)
                init["gfull"] = jnp.asarray(0, I32)
                init["amp_level"] = jnp.asarray(amp_level0, I32)
                init["amp"] = jnp.asarray(0, I32)
            return jax.lax.while_loop(ocond, obody, init)

        return multilevel

    # ------------------------------------------------------------------
    # growth handlers
    # ------------------------------------------------------------------
    def _grow_msgs(self, device_states):
        """Double MAX_MSGS in place: all-zero padding slots change no
        fingerprint (only present slots contribute to the bag hash), so
        the FPSet and every recorded trace pointer stay valid.  Pads the
        given on-device state pytrees and rebuilds the jitted passes.

        Packed buffers round-trip through the OLD pack spec to dense,
        pad, and re-pack under the rebuilt spec (MAX_MSGS changes both
        the lane count and the spec version); unused zero rows are
        stable under the round trip, so the whole buffer converts."""
        old = self.codec.shape.MAX_MSGS
        old_pk = self._pk
        if old_pk is not None:
            dense = [old_pk.unpack_np(np.asarray(d))
                     for d in device_states]
            self._build(old * 2)
            dense = [self.codec.pad_msgs(d, old) for d in dense]
            return [jnp.asarray(self._pk.pack_np(d)) for d in dense]
        self._build(old * 2)
        return [self.codec.pad_msgs(d, old) for d in device_states]

    @staticmethod
    def _pad_rows(buf, add):
        """Append `add` zero rows to a frontier-format buffer (dense
        plane dict or packed [cap, words] array)."""
        def padv(v):
            shape = (add,) + v.shape[1:]
            return jnp.concatenate([v, jnp.zeros(shape, v.dtype)])
        if isinstance(buf, dict):
            return {k: padv(v) for k, v in buf.items()}
        return padv(buf)

    @classmethod
    def _grow_next(cls, bufs, factor=4):
        """Enlarge the next-frontier buffer set, preserving contents."""
        nb, nbp, nba, nbprm = bufs
        cap = nbp.shape[0]
        add = cap * (factor - 1)
        return (cls._pad_rows(nb, add), cls._pad_rows(nbp, add),
                cls._pad_rows(nba, add), cls._pad_rows(nbprm, add))

    # ------------------------------------------------------------------
    # exact-count expansion caps (ISSUE 10)
    # ------------------------------------------------------------------
    def _fold_need(self, need):
        """Fold one dispatch's chunk-wide per-action enabled maxima
        into the run-scoped observation (the exact-growth and
        calibration source)."""
        if self.commit == "fused" and self._need_seen is not None:
            self._need_seen = np.maximum(
                self._need_seen, np.asarray(need, np.int64))

    def _grow_expand(self, aid, obs, emit):
        """R_EXPAND_GROW handler shared by the chunked/fused/chained
        (and paged) loops.  Fused commit: grow EVERY action whose
        observed exact need exceeds its cap — the chunk-wide guard
        matrix already measured the true maxima, so one recompile
        covers the whole chunk instead of one doubling guess per tile.
        Per-action commit: the historical doubling of the overflowing
        action's tile multiplier."""
        kern = self.kern
        if self.commit == "fused":
            caps = self._expand_caps()
            grown = []
            for a, name in enumerate(kern.action_names):
                need = int(self._need_seen[a])
                if need > caps[a]:
                    self.expand_caps[a] = min(
                        self.tile * kern._lane_count(name),
                        _align8(need))
                    grown.append((name, self.expand_caps[a]))
            if not grown:
                # defensive: a pause whose need never reached the host
                # (should not happen — the paused ticket carries it)
                self.expand_caps[aid] = min(
                    self.tile * kern._lane_count(kern.action_names[aid]),
                    _align8(caps[aid] * 2))
                grown = [(kern.action_names[aid], self.expand_caps[aid])]
            for _name, cap in grown:
                obs.grow("expand_buffer", cap)
            emit("expand caps grown to exact chunk need: "
                 + ", ".join(f"{n}={c}" for n, c in grown)
                 + " (recompiling)")
        else:
            self.expand_mults[aid] *= 2
            obs.grow("expand_buffer", self.expand_mults[aid])
            emit(f"expand buffer for {kern.action_names[aid]} grown "
                 f"to tile x {self.expand_mults[aid]} (recompiling)")
        self._level = jax.jit(self._make_level(),
                              donate_argnums=(0, 4, 5, 6, 7, 10))
        self._ml = None
        self._wl = None
        self._fresh_jit = True

    def _calibrate_caps(self, obs, emit, level_states):
        """Level-boundary cap calibration (fused commit): shrink the
        per-action expansion caps onto the observed exact per-tile
        maxima once a representative level has been measured.  Only
        ever fires when it saves >= 20% of the dispatched expand lanes
        (each calibration is a recompile); caps can only shrink onto
        real observations, so a later bigger tile simply triggers an
        exact growth event.  Cap changes never affect results — only
        which lanes are padding (the occupancy gauge's denominator)."""
        if self.commit != "fused" or level_states < 4 * self.tile:
            return False
        kern, T = self.kern, self.tile
        tgt = [min(T * kern._lane_count(n),
                   max(8, _align8(max(int(s), 1))))
               for n, s in zip(kern.action_names, self._need_seen)]
        cur = self._expand_caps()
        if sum(tgt) * 5 > sum(cur) * 4:
            return False
        self.expand_caps = tgt
        self._level = jax.jit(self._make_level(),
                              donate_argnums=(0, 4, 5, 6, 7, 10))
        self._ml = None
        self._wl = None
        self._fresh_jit = True
        obs.grow("expand_calibrate", sum(tgt))
        emit(f"expand caps calibrated to exact chunk maxima "
             f"({sum(cur)} -> {sum(tgt)} lanes/tile; recompiling)")
        return True

    def _account_tiles(self, n_tiles):
        """Occupancy accounting: `n_tiles` frontier tiles were
        dispatched under the current cap set."""
        self._tiles_done += int(n_tiles)
        self._lanes_disp += int(n_tiles) * sum(self._expand_caps())

    # ------------------------------------------------------------------
    def _alloc_bufs(self, cap):
        if self._pk is not None:
            nb = jnp.zeros((cap, self._pk.words), jnp.uint32)
        else:
            zero = self.codec.zero_state()
            nb = {k: jnp.zeros((cap,) + np.shape(v), np.int32)
                  for k, v in zero.items()}
        return (nb, jnp.zeros((cap,), I32), jnp.zeros((cap,), I32),
                jnp.zeros((cap,), I32))

    def _set_rows(self, buf, batch, n):
        """Write the first `n` rows of a dense host batch into a
        frontier-format buffer (packing them when the buffer is
        packed)."""
        if self._pk is not None:
            return buf.at[:n].set(jnp.asarray(self._pk.pack_np(batch)))
        return {k: buf[k].at[:n].set(jnp.asarray(batch[k]))
                for k in buf}

    def _dense_rows(self, buf, n):
        """First `n` rows of a frontier-format buffer as a dense host
        plane dict (the checkpoint interchange format: snapshots always
        store DENSE planes so any engine/pack configuration can resume
        them)."""
        if self._pk is not None:
            return self._pk.unpack_np(np.asarray(buf[:n]))
        return {k: np.asarray(v[:n]) for k, v in buf.items()}

    def _pack_manifest(self):
        return self._pk.manifest() if self._pk is not None else None

    def _fp_batch(self, batch):
        """Fingerprint a dense batch through the canonical seam (the
        host-side twin of the in-kernel fpf closure: init
        registration, resume re-routing)."""
        if self._canon is None:
            return self.kern.fingerprint_batch(batch)
        arr = {k: jnp.asarray(v) for k, v in batch.items()}
        return jax.vmap(self._canon.fingerprint_fn(self.kern))(arr)

    def _canon_manifest(self):
        return (self._canon.manifest() if self._canon is not None
                else None)

    def _symmetry_on(self):
        """True when this run's fingerprints are orbit-reduced —
        through the canon seam OR a factory-supplied folded kernel."""
        return self._canon is not None or (
            bool(self.spec.symmetry_perms) and self._sym_fold > 1)

    def _check_canon_manifest(self, ck, path):
        """Resume-seam policy (ISSUE 11 satellite): a snapshot records
        the canonicalization spec it was fingerprinted under; resuming
        a symmetry-on snapshot with -symmetry off (or vice versa, or
        under a changed group/orbit table) is a loud policy error —
        the FPSet slots hold fingerprints of a different space, so the
        resumed run would silently re-admit or drop states.  (A
        changed SYMMETRY *definition* already fails the spec-digest
        check; this guards the engine-level switch.)  Mirrors the
        pack-spec mismatch rule."""
        ckc = ck.get("canon")
        mine = self._canon.version if self._canon is not None else None
        theirs = (ckc or {}).get("version")
        if theirs != mine:
            raise TLAError(
                f"checkpoint {path} was written with symmetry "
                f"canonicalization {theirs or 'off'} but this engine "
                f"runs {mine or 'off'}; the stored fingerprints are "
                f"not comparable — resume with the matching "
                f"-symmetry setting/group")

    def _check_pack_manifest(self, ck, path):
        """Resume-seam policy (ISSUE 9 satellite): a snapshot records
        the packing-spec version it was written under; resuming with a
        MISMATCHED widths table is a loud policy error, not a silent
        re-encode — a drifted widths table means the run would pack
        fields into different budgets than the ones speclint verified
        for the snapshot's trajectory.  pack=off on either side is
        compatible by construction (snapshots store dense planes)."""
        ckpk = ck.get("pack")
        if ckpk and self._pk is not None and \
                ckpk.get("version") != self._pk.version:
            raise TLAError(
                f"checkpoint {path} was written under packing spec "
                f"{ckpk.get('version')} but this engine derives "
                f"{self._pk.version} from its widths table; refusing "
                f"to resume (rebuild with the matching spec/.cfg or "
                f"pass pack=False)")

    def _pack_gauges(self, obs):
        """frontier_bytes_per_state / pack_ratio (ISSUE 9 satellite):
        the at-rest bytes one frontier row costs this run, and the
        dense/packed ratio (1.0 when packing is off)."""
        zero = self.codec.zero_state()
        dense = sum(int(np.prod(np.shape(v)) or 1) * 4
                    for v in zero.values())
        packed = self._pk.packed_bytes if self._pk is not None else dense
        obs.gauge("frontier_bytes_per_state", int(packed))
        obs.gauge("pack_ratio", round(dense / packed, 3))

    # -- bounds pre-pass consumption (ISSUE 13) ------------------------
    def _bounds_doc(self):
        """The run_start journal `bounds` object (None = off)."""
        return (self._facts.journal_doc()
                if self._facts is not None else None)

    def _bounds_manifest(self):
        """Checkpoint manifest record of the consumed facts (None =
        bounds off): the digest resume compatibility is judged by."""
        if self._facts is None:
            return None
        return {"digest": self._facts.digest,
                "tightened": self._facts.tightened}

    def _check_bounds_manifest(self, ck, path):
        """Resume-seam policy (ISSUE 13 satellite): a snapshot records
        the bounds facts it consumed (tightened packing + pruned lane
        ids both depend on them); resuming under a flipped ``-bounds``
        or changed facts is a loud policy error, mirroring the
        pack/canon rules.  (Changed cfg constants already fail the
        spec-digest check; this guards the engine-level switch.)"""
        theirs = (ck.get("bounds") or {}).get("digest")
        mine = (self._facts.digest if self._facts is not None
                else None)
        if theirs != mine:
            raise TLAError(
                f"checkpoint {path} was written under bounds facts "
                f"{theirs or 'off'} but this engine consumes "
                f"{mine or 'off'}; the tightened packing and pruned "
                f"action ids are not comparable — resume with the "
                f"matching -bounds setting (and the same cfg "
                f"constants)")

    def _bounds_gauges(self, obs):
        """state_bound / dead_actions / bound_tightening_ratio
        (ISSUE 13): what the static pre-pass proved and how many
        pack bits it saved (declared bits / tightened bits; 1.0 when
        untightened or bounds off)."""
        if self._facts is None:
            return
        f = self._facts
        if f.state_bound is not None:
            obs.gauge("state_bound", int(f.state_bound))
        obs.gauge("dead_actions", len(self._pruned))
        ratio = 1.0
        if self._pk is not None and self._pk_decl is not None and \
                self._pk.total_bits:
            ratio = self._pk_decl.total_bits / self._pk.total_bits
        obs.gauge("bound_tightening_ratio", round(ratio, 4))

    # -- ample-set POR consumption (ISSUE 16) --------------------------
    def _por_doc(self):
        """The run_start journal `por` object (None = off) — key-set
        parity across all engines (obs/SCHEMA.md)."""
        return (self._por.journal_doc()
                if self._por is not None else None)

    def _por_manifest(self):
        """Checkpoint manifest record of the consumed independence
        facts (None = POR off): flip-on-resume policy anchor."""
        return self._por.manifest() if self._por is not None else None

    def _check_por_manifest(self, ck, path):
        """Resume-seam policy (ISSUE 16 satellite): a snapshot records
        the independence facts its reduced exploration trusted;
        resuming under a flipped ``-por`` or changed facts is a loud
        policy error, mirroring the pack/canon/bounds rules — the
        stored frontier/visited set cover a DIFFERENT (reduced or
        full) slice of the space, so the resumed run would silently
        drop or re-admit interleavings."""
        theirs = (ck.get("por") or {}).get("digest")
        mine = self._por.digest if self._por is not None else None
        if theirs != mine:
            raise TLAError(
                f"checkpoint {path} was written under POR facts "
                f"{theirs or 'off'} but this engine consumes "
                f"{mine or 'off'}; the explored state sets are not "
                f"comparable — resume with the matching -por setting "
                f"(and the same spec/cfg)")

    def _por_gauges(self, obs):
        """por_cut_ratio / ample_states (ISSUE 16): generated kept /
        generated full under the ample filter (1.0 when POR off or
        inert), and how many expanded states took the shortcut with
        real work elided."""
        if self._por is None:
            return
        full = int(self._por_full)
        kept = int(self._por_kept)
        obs.gauge("por_cut_ratio",
                  round(kept / full, 4) if full else 1.0)
        obs.gauge("ample_states", int(self._por_amp))
        obs.gauge("por_eligible_actions", self._por.n_eligible)

    def _register_init(self, res):
        """Encode, dedup, and FPSet-register the initial states; seed
        the host pointer store and check invariants on them (shared by
        run() and run_fused() — the two must stay observationally
        identical).  Returns (table, init_batch, n0, viol_index);
        viol_index is non-None when an init state violates, with
        res.trace already built."""
        spec, codec = self.spec, self.codec
        table = empty_table(self.fpset_capacity)
        init_states = list(spec.init_states())
        init_dense = [codec.encode(st) for st in init_states]
        init_batch = {k: np.stack([d[k] for d in init_dense])
                      for k in init_dense[0]}
        fps = np.asarray(self._fp_batch(init_batch))
        keep, seen = [], set()
        for i in range(len(init_dense)):
            key = tuple(fps[i])
            if key not in seen:
                seen.add(key)
                keep.append(i)
        init_batch = {k: v[keep] for k, v in init_batch.items()}
        self._init_states = [init_states[i] for i in keep]
        self._init_dense = [init_dense[i] for i in keep]
        n0 = len(keep)
        table, _, _ = insert_batch(
            table, jnp.asarray(fps[keep]), jnp.ones((n0,), bool))
        if self._edges_on:
            # gid column (ISSUE 15): graph node ids ARE commit order,
            # so the deduped init states take gids 0..n0-1
            table["gids"] = store_gids(
                table["slots"],
                jnp.full((self.fpset_capacity,), -1, jnp.int32),
                jnp.asarray(fps[keep]),
                jnp.arange(n0, dtype=jnp.int32),
                jnp.ones((n0,), bool))
        if self._por_active:
            # C3 level-marker column (ISSUE 16): init states are level
            # 0, and a zeros column gives every one of them marker 0
            # without a store pass; empty-slot values are never read
            # (lookup_gids returns -1 for absent fingerprints)
            table["gids"] = jnp.zeros((self.fpset_capacity,),
                                      jnp.int32)
        # host trace store: gid -> (parent gid, action, param)
        self._h_parent = [np.full(n0, -1, np.int64)]
        self._h_action = [np.full(n0, -1, np.int32)]
        self._h_param = [np.zeros(n0, np.int32)]
        for i in range(n0):
            bad = spec.check_invariants(self._init_states[i])
            if bad:
                res.ok = False
                res.violated_invariant = bad
                res.trace = self._trace(i)
                return table, init_batch, n0, i
        res.states_generated += len(init_dense)
        return table, init_batch, n0, None

    @closes_observer
    def run(self, max_states=None, max_depth=None, max_seconds=None,
            check_deadlock=False, log=None, progress_every=10.0,
            checkpoint_path=None, checkpoint_every=None,
            resume_from=None, obs=None) -> CheckResult:
        from ..analysis import preflight
        preflight(self.spec, log=log)   # fail fast, before any dispatch
        obs = RunObserver.ensure(obs, "device", self.spec, log=log,
                                 progress_every=progress_every)
        obs.pipeline = self.pipe_window
        obs.pack = self._pk is not None
        obs.commit = self.commit
        obs.symmetry = self._symmetry_on()
        obs.bounds = self._bounds_doc()
        obs.edges = self._edges_on
        obs.por = self._por_doc()
        self._obs_active = obs          # closes_observer finalizes it
        spec, codec = self.spec, self.codec  # codec only for init encode
        # per-action expansion counters (on-device accumulator, pulled
        # with the control scalars; run-scoped, not checkpointed) +
        # occupancy accounting (ISSUE 10)
        self._act_counts = np.zeros(len(self.kern.action_names),
                                    np.int64)
        self._tiles_done = 0
        self._lanes_disp = 0
        self._por_kept = self._por_full = self._por_amp = 0
        res = CheckResult()
        t0 = time.time()
        obs.start(t0, backend=jax.default_backend(),
                  resumed=resume_from is not None)
        emit = obs.log

        if resume_from is not None:
            # --- resume from a level-boundary snapshot ----------------
            from .checkpoint import load_checkpoint, spec_digest
            ck = load_checkpoint(resume_from,
                                 expect_digest=spec_digest(spec),
                                 log=emit)
            if (ck.get("extra") or {}).get("sharded"):
                raise TLAError("checkpoint was written by the sharded "
                               "engine; resume it there")
            # an EMPTY expand_mults means the snapshot carries no
            # per-action multipliers (written by the sharded engine,
            # then converted single-device for the supervisor's paged
            # fallback) — keep this engine's own defaults
            if ck["max_msgs"] != self.codec.shape.MAX_MSGS or \
                    (ck["expand_mults"] and list(ck["expand_mults"])
                     != list(self.expand_mults)):
                if ck["expand_mults"]:
                    self.expand_mults = list(ck["expand_mults"])
                self._build(ck["max_msgs"])
                codec = self.codec
            self._check_bounds_manifest(ck, resume_from)
            self._check_pack_manifest(ck, resume_from)
            self._check_canon_manifest(ck, resume_from)
            table = {"slots": jnp.asarray(ck["slots"])}
            fp_cap = int(ck["slots"].shape[0])
            if self._por_active:
                self._check_por_manifest(ck, resume_from)
                # markers are NOT snapshotted: at a level boundary
                # every stored fingerprint belongs to the frontier's
                # level or earlier, so an all-zeros column (marker 0
                # <= any pdepth = old) reproduces every C3 decision
                table["gids"] = jnp.zeros((fp_cap,), jnp.int32)
            elif ck.get("por"):
                self._check_por_manifest(ck, resume_from)
            self._init_dense = ck["init_dense"]
            self._init_states = [codec.decode(d)
                                 for d in ck["init_dense"]]
            self._h_parent = [ck["h_parent"]]
            self._h_action = [ck["h_action"]]
            self._h_param = [ck["h_param"]]
            self.level_sizes = list(ck["level_sizes"])
            depth = ck["depth"]
            fp_count = ck["fp_count"]
            res.states_generated = ck["states_generated"]
            t0 -= ck["elapsed"]            # keep cumulative wall clock
            obs.set_epoch(t0)
            n_front = ck["n_front"]
            f_cap = max(self.next_cap, n_front)
            front, fpar, fact, fprm = self._alloc_bufs(f_cap)
            front = self._set_rows(front, ck["frontier"], n_front)
            bufs = self._alloc_bufs(self.next_cap)
            level_base = sum(self.level_sizes[:-1])
            emit(f"resumed from {resume_from}: depth {depth}, "
                 f"{fp_count} distinct, frontier {n_front}")
        else:
            fp_cap = self.fpset_capacity
            # reset BEFORE registration: a reused engine instance must
            # not leak the previous run's trajectory into an
            # init-violation result
            self.level_sizes = []
            table, init_batch, n0, viol = self._register_init(res)
            fp_count = n0
            if viol is not None:
                return self._finish(res, obs, fp_count,
                                    table=table, fp_cap=fp_cap)

            # --- device frontier + next buffers -----------------------
            f_cap = max(self.next_cap, n0)
            front, fpar, fact, fprm = self._alloc_bufs(f_cap)
            front = self._set_rows(front, init_batch, n0)
            bufs = self._alloc_bufs(self.next_cap)
            n_front = n0
            level_base = 0          # gid of frontier[0]
            depth = 0
            self.level_sizes = [n0]
        last_checkpoint = time.time()
        return self._run_loop(
            res, obs, table=table, front=front, bufs=bufs, fpar=fpar,
            fact=fact, fprm=fprm, n_front=n_front,
            level_base=level_base, depth=depth, fp_count=fp_count,
            fp_cap=fp_cap, t0=t0, max_states=max_states,
            max_depth=max_depth, max_seconds=max_seconds,
            check_deadlock=check_deadlock,
            checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every,
            last_checkpoint=last_checkpoint)

    def _run_loop(self, res, obs, *, table, front, bufs, fpar, fact,
                  fprm, n_front, level_base, depth, fp_count, fp_cap,
                  t0, max_states, max_depth, max_seconds,
                  check_deadlock, checkpoint_path, checkpoint_every,
                  last_checkpoint):
        # keyword-only: the loop state is a pile of same-typed ints and
        # identically shaped buffers — a transposed positional arg
        # would type-check and silently corrupt traces/metrics
        from .pipeline import DispatchPipeline
        pipe = DispatchPipeline(self.pipe_window, obs,
                                ready=lambda o: o["reason"])

        def pull(o):
            # ONE host round-trip for all control scalars — separate
            # int() pulls cost one tunnel RTT each on a remote TPU
            vals = [o["reason"], o["t"], o["nn"], o["gen"], o["dist"],
                    o["act"], o["need"]]
            if self._por_active:
                vals += [o["gfull"], o["amp"]]
            return jax.device_get(vals)
        return self._chunk_loop(
            res, obs, pipe, pull, table=table, front=front,
            bufs=bufs, fpar=fpar, fact=fact, fprm=fprm,
            n_front=n_front, level_base=level_base, depth=depth,
            fp_count=fp_count, fp_cap=fp_cap, t0=t0,
            max_states=max_states, max_depth=max_depth,
            max_seconds=max_seconds, check_deadlock=check_deadlock,
            checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every,
            last_checkpoint=last_checkpoint)

    def _chunk_loop(self, res, obs, pipe, pull, *, table, front, bufs,
                    fpar, fact, fprm, n_front, level_base, depth,
                    fp_count, fp_cap, t0, max_states, max_depth,
                    max_seconds, check_deadlock, checkpoint_path,
                    checkpoint_every, last_checkpoint):
        spec = self.spec
        emit = obs.log
        while n_front > 0:
            if max_depth is not None and depth >= max_depth:
                res.error = f"depth limit {max_depth} reached"
                break
            depth += 1
            fault_point("level", depth=depth, obs=obs)
            start_t = 0
            n_next = 0
            n_tiles = (n_front + self.tile - 1) // self.tile
            stop = None
            # device-side chain: the next dispatch's (start_t, nn)
            # come straight off the previous dispatch's outputs, so
            # filling the window costs zero host syncs
            pend_t = jnp.asarray(0, I32)
            pend_nn = jnp.asarray(0, I32)
            while True:
                # keep the window full (speculation past a pause or the
                # level end is safe: such dispatches commit nothing and
                # pipe.drain() discards their deltas — pipeline.py)
                while pipe.has_room():
                    nb, nbp, nba, nbprm = bufs
                    out = pipe.launch(
                        self._level, table, front,
                        jnp.asarray(n_front, I32), pend_t,
                        nb, nbp, nba, nbprm, pend_nn,
                        jnp.asarray(bool(check_deadlock)), None, None,
                        jnp.asarray(depth - 1, I32),
                        fresh=self._fresh_jit,
                        label=f"level {depth} dispatch")
                    self._fresh_jit = False
                    table = {"slots": out["slots"]}
                    if self._por_active:
                        table["gids"] = out["gids"]
                    bufs = (out["nb"], out["nbp"], out["nba"],
                            out["nbprm"])
                    pend_t, pend_nn = out["t"], out["nn"]
                out, sc = pipe.collect(pull)
                reason, start_t, n_next, gen_add, dist_add = (
                    int(x) for x in sc[:5])
                res.states_generated += gen_add
                fp_count += dist_add
                self._act_counts += np.asarray(sc[5], np.int64)
                self._fold_need(sc[6])
                if self._por_active:
                    self._por_kept += gen_add
                    self._por_full += int(sc[7])
                    self._por_amp += int(sc[8])

                if reason == RUNNING:
                    obs.progress(depth=depth, distinct=fp_count,
                                 generated=res.states_generated)
                    if max_seconds and time.time() - t0 > max_seconds:
                        stop = f"time budget {max_seconds}s reached"
                        pipe.drain()
                        break
                    if start_t >= n_tiles:
                        pipe.drain()     # in-flight tickets are no-ops
                        break            # level complete
                    continue
                # pause or terminal reason: everything still in flight
                # is a replay of the same paused tile — drop it, then
                # handle the reason on the chain-tip table/buffers
                # (identical to the consumed ticket's: replays commit
                # nothing)
                pipe.drain()
                if reason == R_VIOLATION:
                    vp, va, vprm = (int(v) for v in np.asarray(out["viol"]))
                    gid = level_base + vp
                    parent_dense = self._fetch_row(front, vp)
                    vstate = self._materialize_one(parent_dense, va, vprm)
                    bad = spec.check_invariants(
                        self.codec.decode(vstate))
                    if bad is None:
                        # device said violated, interpreter disagrees:
                        # engine bug — fail loudly, don't fabricate a
                        # counterexample (see device_sim for rationale)
                        raise TLAError(
                            "device/interpreter divergence: device "
                            "invariant kernel reported a violation the "
                            "interpreter accepts (parent gid "
                            f"{gid}, action {self.kern.action_names[va]})")
                    res.ok = False
                    res.violated_invariant = bad
                    res.trace = self._trace(gid, extra=(va, vprm))
                    res.diameter = depth
                    return self._finish(res, obs, fp_count,
                                        table=table, fp_cap=fp_cap)
                elif reason == R_BAG_GROW:
                    front, nb = self._grow_msgs([front, bufs[0]])
                    bufs = (nb,) + bufs[1:]
                    obs.grow("message_table", self.codec.shape.MAX_MSGS)
                    emit(f"message table grown to "
                         f"{self.codec.shape.MAX_MSGS} slots (recompiling)")
                elif reason == R_FPSET_GROW:
                    table = grow(table)
                    fp_cap *= 4
                    # shape change -> the next dispatch retraces and
                    # recompiles; charge it to "compile", not
                    # "dispatch" (same for every growth below)
                    self._fresh_jit = True
                    obs.grow("fpset", fp_cap)
                    emit(f"FPSet grown to {fp_cap} slots")
                elif reason == R_NEXT_GROW:
                    bufs = self._grow_next(bufs)
                    self._fresh_jit = True
                    obs.grow("next_buffer", bufs[1].shape[0])
                    emit(f"next-frontier buffer grown to "
                         f"{bufs[1].shape[0]}")
                elif reason == R_EXPAND_GROW:
                    self._grow_expand(int(out["grow_aid"]), obs, emit)
                elif reason == R_SLOT_ERR:
                    raise TLAError(
                        "dense-layout slot collision (a second DVC or "
                        "recovery response from one source in one view): "
                        "this restart-era interleaving needs the "
                        "multi-slot layout (vsr.py docstring)")
                elif reason == R_DEADLOCK:
                    di = int(out["dead"])
                    gid = level_base + di
                    res.ok = False
                    res.error = "deadlock"
                    res.deadlock_state = self.codec.decode(
                        self._fetch_row(front, di))
                    res.trace = self._trace(gid)
                    res.diameter = depth
                    return self._finish(res, obs, fp_count,
                                        table=table, fp_cap=fp_cap)
                # growth pauses fall through here; terminal reasons
                # returned above
                obs.progress(depth=depth, distinct=fp_count,
                             generated=res.states_generated)
                if max_seconds and time.time() - t0 > max_seconds:
                    stop = f"time budget {max_seconds}s reached"
                    break

            # ---- level complete: pull trace pointers, swap buffers ---
            obs.level_done(depth, frontier=n_front, distinct=fp_count,
                           generated=res.states_generated)
            self._account_tiles(min(start_t, n_tiles))
            nb, nbp, nba, nbprm = bufs
            if n_next:
                # async pointer fetch: the copies overlap the next
                # level's compute and are only materialized on demand
                # (_flush_pointers) — a blocking device_get here costs
                # a full tunnel RTT per level on a remote TPU
                par, act, prm = nbp[:n_next], nba[:n_next], nbprm[:n_next]
                for a in (par, act, prm):
                    a.copy_to_host_async()
                self._h_parent.append((par, level_base))
                self._h_action.append(act)
                self._h_param.append(prm)
                self.level_sizes.append(n_next)
            level_base += n_front
            # the old frontier set becomes the next scratch buffer set
            front, bufs = nb, (front, fpar, fact, fprm)
            fpar, fact, fprm = nbp, nba, nbprm
            n_front = n_next
            if self.debug_checks and n_next:
                self._debug_assert_widths(front, n_next, depth)
            # fused commit: shrink the expansion caps onto the exact
            # observed maxima (the window is drained here, so the
            # recompile never races an in-flight dispatch)
            if n_next and stop is None:
                self._calibrate_caps(obs, emit, n_front)
            # a pending SIGTERM/SIGINT (supervisor's PreemptionGuard)
            # forces a rescue snapshot at this boundary regardless of
            # cadence; at fixpoint (n_next == 0) the run finishes anyway
            rescue = preempt_signal() if n_next else None
            if checkpoint_path and n_next and (
                    rescue is not None
                    or checkpoint_every is None
                    or time.time() - last_checkpoint >= checkpoint_every):
                from .checkpoint import save_checkpoint, spec_digest
                with obs.timer("checkpoint"):
                    self._flush_pointers()
                    save_checkpoint(
                        checkpoint_path,
                        slots=table["slots"],
                        frontier=self._dense_rows(front, n_next),
                        n_front=n_next,
                        h_parent=np.concatenate(self._h_parent),
                        h_action=np.concatenate(self._h_action),
                        h_param=np.concatenate(self._h_param),
                        init_dense=self._init_dense,
                        level_sizes=self.level_sizes, depth=depth,
                        fp_count=fp_count,
                        states_generated=res.states_generated,
                        max_msgs=self.codec.shape.MAX_MSGS,
                        expand_mults=self.expand_mults,
                        elapsed=time.time() - t0,
                        digest=spec_digest(spec),
                        pack=self._pack_manifest(),
                        canon=self._canon_manifest(),
                        bounds=self._bounds_manifest(),
                        por=self._por_manifest(), obs=obs)
                last_checkpoint = time.time()
                obs.checkpoint(checkpoint_path, depth, fp_count)
                emit(f"checkpoint written to {checkpoint_path} "
                     f"(depth {depth}, {fp_count} distinct)")
            if rescue is not None:
                obs.rescue(checkpoint_path or "", depth, fp_count,
                           rescue)
                emit(f"preempted by {rescue}: rescue snapshot at depth "
                     f"{depth} ({checkpoint_path}); exiting resumable")
                raise Preempted(checkpoint_path, depth, fp_count,
                                rescue)
            if stop:
                res.error = stop
                break
            if n_next == 0:
                break
            if max_states and fp_count >= max_states:
                res.error = f"state limit {max_states} reached"
                break
            # proactive FPSet growth between levels keeps probe chains
            # short and the in-level overflow pause rare
            if fp_count > 0.5 * fp_cap:
                table = grow(table)
                fp_cap *= 4
                self._fresh_jit = True
                obs.grow("fpset", fp_cap)
                emit(f"FPSet grown to {fp_cap} slots")

        res.diameter = depth
        return self._finish(res, obs, fp_count,
                            table=table, fp_cap=fp_cap)

    def _debug_assert_widths(self, front, n_front, depth):
        """TPUVSR_DEBUG_NANS=1 overflow guard: after each level, pull
        the view/op planes of the committed frontier and assert they
        stay inside the statically derived ranges (the widths lint
        pass).  Catches packed-field wrap the moment it happens instead
        of as a fingerprint anomaly millions of states later."""
        if self._pk is not None:
            front = self._pk.unpack_np(np.asarray(front[:n_front]))
        if not hasattr(self, "_debug_bounds"):
            from ..analysis.passes.widths import derive_ranges
            rng = derive_ranges(self.spec)
            self._debug_bounds = {
                k: rng[q] for k, q in (("view", "view_number"),
                                       ("op", "op_number"))
                if q in rng and k in front}
        for plane, (lo, hi) in self._debug_bounds.items():
            vals = np.asarray(front[plane][:n_front])
            if vals.size and (vals.min() < lo or vals.max() > hi):
                raise TLAError(
                    f"debug overflow guard: plane {plane!r} reached "
                    f"[{int(vals.min())}, {int(vals.max())}] at depth "
                    f"{depth}, outside the derived range [{lo}, {hi}] "
                    f"(TPUVSR_DEBUG_NANS width assertion)")

    # ------------------------------------------------------------------
    # fused run: whole fixpoint in O(1) dispatches
    # ------------------------------------------------------------------
    @closes_observer
    def run_fused(self, max_states=None, max_depth=None,
                  max_seconds=None, check_deadlock=False, log=None,
                  levels_per_dispatch=256, checkpoint_path=None,
                  checkpoint_every=None, rescue_quantum=8,
                  obs=None) -> CheckResult:
        """Like run(), but through the fused multi-level pass
        (_make_multilevel): the whole reachable space is explored in a
        handful of dispatches (one, absent growth pauses), eliminating
        the per-level host round-trips that dominate on a remote TPU.
        Trace pointers and level sizes accumulate on device and are
        pulled once at the end.

        With ``checkpoint_path`` (the supervised mode, ISSUE 4
        satellite) each dispatch is bounded to a ``rescue_quantum``
        level quantum so the host regains control at level boundaries:
        run()-format snapshots are written there (every boundary, or on
        the ``checkpoint_every`` cadence), and a pending SIGTERM/SIGINT
        (PreemptionGuard) turns into a rescue snapshot + ``Preempted``
        exactly like the chunked engine.  The snapshot resumes through
        ``run()`` — the fused pass itself has no resume path."""
        from ..analysis import preflight
        preflight(self.spec, log=log)   # fail fast, before any dispatch
        obs = RunObserver.ensure(obs, "device-fused", self.spec, log=log)
        obs.pipeline = 1                # one fused dispatch in flight
        obs.pack = self._pk is not None
        obs.commit = self.commit
        obs.symmetry = self._symmetry_on()
        obs.bounds = self._bounds_doc()
        obs.edges = self._edges_on
        obs.por = self._por_doc()
        obs.gauge("pipeline_depth", 1)
        self._obs_active = obs          # closes_observer finalizes it
        spec, codec = self.spec, self.codec
        self._act_counts = np.zeros(len(self.kern.action_names),
                                    np.int64)
        self._tiles_done = 0
        self._lanes_disp = 0
        self._por_kept = self._por_full = self._por_amp = 0
        res = CheckResult()
        t0 = time.time()
        obs.start(t0, backend=jax.default_backend())
        emit = obs.log

        fp_cap = self.fpset_capacity
        self.level_sizes = []      # no stale trajectory on init-viol
        table, init_batch, n0, viol = self._register_init(res)
        if viol is not None:
            return self._finish(res, obs, n0, table=table, fp_cap=fp_cap)

        # ping-pong buffers share one capacity in fused mode
        f_cap = max(self.next_cap, n0)
        front, nbp, nba, nbprm = self._alloc_bufs(f_cap)
        front = self._set_rows(front, init_batch, n0)
        nb, _, _, _ = self._alloc_bufs(f_cap)
        tp_cap = max(4 * f_cap, 1 << 16)
        tpp = jnp.full((tp_cap,), -1, I32)
        tpa = jnp.full((tp_cap,), -1, I32)
        tpm = jnp.zeros((tp_cap,), I32)
        lvl_buf = jnp.zeros((levels_per_dispatch,), I32)

        # run() parity on the limit conventions: max_depth=0 is a real
        # limit there (`is not None` — stops before the first level)
        # while max_states=0 means unlimited (`if max_states and ...`);
        # md/ms must encode the SAME semantics the host checks below
        # use, or a md=0 run explores a whole dispatch quantum before
        # the host notices (ADVICE r4)
        md = 2**31 - 1 if max_depth is None else int(max_depth)
        ms = int(max_states) if max_states else 2**31 - 1
        n_front, start_t, nn, gen_level = n0, 0, 0, 0
        depth, level_base, fp_count = 0, 0, n0
        por_on = self._por_active
        gfull_level, amp_level = 0, 0
        self.level_sizes = [n0]
        last_checkpoint = time.time()
        # adaptive dispatch quantum: small first dispatches give the
        # host early wall-clock checkpoints for max_seconds, growing
        # toward levels_per_dispatch so steady state stays O(1)
        # dispatches (on a remote TPU the extra early syncs are noise).
        # A checkpointing (supervised) run stays bounded at
        # rescue_quantum so a preemption is never more than that many
        # levels away from a rescue boundary.
        q_cap = (min(levels_per_dispatch, max(1, int(rescue_quantum)))
                 if checkpoint_path else levels_per_dispatch)
        quantum = min(4, q_cap) if (max_seconds or checkpoint_path) \
            else levels_per_dispatch

        def set_pointers(n):
            self._h_parent = [np.asarray(tpp[:n]).astype(np.int64)]
            self._h_action = [np.asarray(tpa[:n])]
            self._h_param = [np.asarray(tpm[:n])]

        while True:
            fresh = self._fresh_jit or self._ml is None
            if self._ml is None:
                self._ml = jax.jit(self._make_multilevel(),
                                   donate_argnums=tuple(range(10)))
            with obs.timer("compile" if fresh else "dispatch"), \
                    obs.annotate(f"fused dispatch (depth {depth}+)"):
                out = self._ml(
                    table["slots"], front, nb, nbp, nba, nbprm,
                    tpp, tpa, tpm, lvl_buf,
                    jnp.asarray(n_front, I32), jnp.asarray(start_t, I32),
                    jnp.asarray(nn, I32), jnp.asarray(gen_level, I32),
                    jnp.asarray(depth, I32), jnp.asarray(level_base, I32),
                    jnp.asarray(fp_count, I32),
                    jnp.asarray(bool(check_deadlock)),
                    jnp.asarray(md, I32), jnp.asarray(ms, I32),
                    jnp.asarray(min(quantum, levels_per_dispatch), I32),
                    jnp.asarray(0, I32),
                    jnp.asarray(2**31 - 1, I32),
                    *((table["gids"], jnp.asarray(gfull_level, I32),
                       jnp.asarray(amp_level, I32)) if por_on else ()))
                out["reason"].block_until_ready()
            self._fresh_jit = False
            obs.count("dispatches")
            quantum = min(quantum * 4, q_cap)
            table = {"slots": out["slots"]}
            if por_on:
                table["gids"] = out["gids"]
            front, nb = out["front"], out["nb"]
            nbp, nba, nbprm = out["nbp"], out["nba"], out["nbprm"]
            tpp, tpa, tpm = out["tpp"], out["tpa"], out["tpm"]
            lvl_buf = out["lvl_buf"]
            with obs.timer("host_sync"):
                sc = jax.device_get(
                    [out[k] for k in ("reason", "n_front", "start_t",
                                      "nn", "gen_level", "gen", "depth",
                                      "level_base", "fp_count",
                                      "lvl_cur", "act", "tiles",
                                      "need")]
                    + ([out[k] for k in ("gfull", "gfull_level",
                                         "amp", "amp_level")]
                       if por_on else []))
            (reason, n_front, start_t, nn, gen_level, gen_add, depth,
             level_base, fp_count, lvl_cur) = (int(x) for x in sc[:10])
            self._act_counts += np.asarray(sc[10], np.int64)
            self._account_tiles(int(sc[11]))
            self._fold_need(sc[12])
            if por_on:
                self._por_kept += gen_add
                self._por_full += int(sc[13])
                self._por_amp += int(sc[15])
                gfull_level, amp_level = int(sc[14]), int(sc[16])
            res.states_generated += gen_add
            if lvl_cur:
                # level boundaries inside one dispatch share its
                # host-side timestamp and generated count (the device
                # never synced mid-dispatch) — documented in SCHEMA.md
                cum = sum(self.level_sizes)
                for x in np.asarray(lvl_buf[:lvl_cur]):
                    prev = self.level_sizes[-1]
                    self.level_sizes.append(int(x))
                    cum += int(x)
                    obs.level_done(len(self.level_sizes) - 1,
                                   frontier=prev, distinct=cum,
                                   generated=res.states_generated)
            obs.progress(depth=depth, distinct=fp_count,
                         generated=res.states_generated, force=True)

            if reason == RUNNING:
                if n_front == 0:
                    break                           # fixpoint
                if max_depth is not None and depth >= max_depth:
                    res.error = f"depth limit {max_depth} reached"
                    break
                if max_states and fp_count >= max_states:
                    res.error = f"state limit {max_states} reached"
                    break
                if max_seconds and time.time() - t0 > max_seconds:
                    res.error = f"time budget {max_seconds}s reached"
                    break
                # quantum boundary == level boundary (ocond only exits
                # between levels): rescue/cadence checkpoint first
                # (ISSUE 4 satellite — the fused fixpoint is
                # preemption-safe under -supervise), then the level
                # fault hook for the next quantum's first level —
                # mirroring the chunked engine's checkpoint-then-
                # fault chronology so a fault always finds the
                # freshest snapshot behind it.  The preemption flag is
                # polled regardless of checkpoint_path (chunked-run
                # parity: a guard-caught SIGTERM must never be
                # silently swallowed — Preempted's message reports the
                # missing snapshot)
                rescue = preempt_signal()
                if checkpoint_path and (
                        rescue is not None
                        or checkpoint_every is None
                        or time.time() - last_checkpoint
                        >= checkpoint_every):
                    from .checkpoint import save_checkpoint, spec_digest
                    with obs.timer("checkpoint"):
                        set_pointers(level_base + n_front)
                        save_checkpoint(
                            checkpoint_path,
                            slots=table["slots"],
                            frontier=self._dense_rows(front, n_front),
                            n_front=n_front,
                            h_parent=np.concatenate(self._h_parent),
                            h_action=np.concatenate(self._h_action),
                            h_param=np.concatenate(self._h_param),
                            init_dense=self._init_dense,
                            level_sizes=self.level_sizes, depth=depth,
                            fp_count=fp_count,
                            states_generated=res.states_generated,
                            max_msgs=self.codec.shape.MAX_MSGS,
                            expand_mults=self.expand_mults,
                            elapsed=time.time() - t0,
                            digest=spec_digest(spec),
                            pack=self._pack_manifest(),
                            canon=self._canon_manifest(),
                            bounds=self._bounds_manifest(),
                            por=self._por_manifest(), obs=obs)
                    last_checkpoint = time.time()
                    obs.checkpoint(checkpoint_path, depth, fp_count)
                    emit(f"checkpoint written to {checkpoint_path} "
                         f"(depth {depth}, {fp_count} distinct; "
                         f"resume via the chunked engine)")
                if rescue is not None:
                    obs.rescue(checkpoint_path or "", depth, fp_count,
                               rescue)
                    emit(f"preempted by {rescue}: rescue snapshot at "
                         f"depth {depth} ({checkpoint_path}); exiting "
                         f"resumable")
                    raise Preempted(checkpoint_path, depth, fp_count,
                                    rescue)
                # quantum boundaries are level boundaries: safe spot
                # to shrink the fused expansion caps onto the exact
                # observed maxima (no dispatch in flight)
                self._calibrate_caps(obs, emit, n_front)
                # the next quantum starts with level depth+1 — same
                # depth convention as the chunked engine's per-level
                # hook.  The host only sees quantum boundaries, so a
                # level-pinned fault fires iff its level is the first
                # of a quantum (pin rescue_quantum accordingly in
                # injection tests)
                fault_point("level", depth=depth + 1, obs=obs)
                if level_base + n_front + f_cap > tp_cap:
                    add = tp_cap                     # double
                    tpp = jnp.concatenate(
                        [tpp, jnp.full((add,), -1, I32)])
                    tpa = jnp.concatenate(
                        [tpa, jnp.full((add,), -1, I32)])
                    tpm = jnp.concatenate(
                        [tpm, jnp.zeros((add,), I32)])
                    tp_cap += add
                    self._fresh_jit = True   # shape change: retrace
                    obs.grow("trace_pointer_store", tp_cap)
                    emit(f"trace-pointer store grown to {tp_cap}")
                # else: level counter full — drained above, re-enter
                continue
            if reason == R_VIOLATION:
                # committed tiles of the in-flight level count (run()
                # adds per-chunk gen on every call incl. the last)
                res.states_generated += gen_level
                if por_on:
                    self._por_kept += gen_level
                    self._por_full += gfull_level
                    self._por_amp += amp_level
                vp, va, vprm = (int(v) for v in np.asarray(out["viol"]))
                gid = level_base + vp
                parent_dense = self._fetch_row(front, vp)
                vstate = self._materialize_one(parent_dense, va, vprm)
                bad = spec.check_invariants(self.codec.decode(vstate))
                if bad is None:
                    raise TLAError(
                        "device/interpreter divergence: device "
                        "invariant kernel reported a violation the "
                        "interpreter accepts (parent gid "
                        f"{gid}, action {self.kern.action_names[va]})")
                set_pointers(level_base + n_front)
                res.ok = False
                res.violated_invariant = bad
                res.trace = self._trace(gid, extra=(va, vprm))
                # depth counts committed levels; the violation is in
                # the in-progress one (chunked run() parity)
                res.diameter = depth + 1
                return self._finish(res, obs, fp_count,
                                    table=table, fp_cap=fp_cap)
            if reason == R_DEADLOCK:
                res.states_generated += gen_level
                if por_on:
                    self._por_kept += gen_level
                    self._por_full += gfull_level
                    self._por_amp += amp_level
                di = int(out["dead"])
                set_pointers(level_base + n_front)
                res.ok = False
                res.error = "deadlock"
                res.deadlock_state = self.codec.decode(
                    self._fetch_row(front, di))
                res.trace = self._trace(level_base + di)
                res.diameter = depth + 1
                return self._finish(res, obs, fp_count,
                                    table=table, fp_cap=fp_cap)
            if reason == R_BAG_GROW:
                front, nb = self._grow_msgs([front, nb])
                obs.grow("message_table", self.codec.shape.MAX_MSGS)
                emit(f"message table grown to "
                     f"{self.codec.shape.MAX_MSGS} slots (recompiling)")
            elif reason == R_FPSET_GROW:
                table = grow(table)
                fp_cap *= 4
                self._fresh_jit = True       # shape change: retrace
                obs.grow("fpset", fp_cap)
                emit(f"FPSet grown to {fp_cap} slots")
            elif reason == R_NEXT_GROW:
                old_cap = nbp.shape[0]
                front, nbp, nba, nbprm = self._grow_next(
                    (front, nbp, nba, nbprm))
                f_cap = nbp.shape[0]
                nb = self._pad_rows(nb, f_cap - old_cap)
                self._fresh_jit = True       # shape change: retrace
                obs.grow("next_buffer", f_cap)
                emit(f"frontier buffers grown to {f_cap}")
            elif reason == R_EXPAND_GROW:
                self._grow_expand(int(out["grow_aid"]), obs, emit)
            elif reason == R_SLOT_ERR:
                raise TLAError(
                    "dense-layout slot collision (a second DVC or "
                    "recovery response from one source in one view): "
                    "this restart-era interleaving needs the "
                    "multi-slot layout (vsr.py docstring)")

        # a limit break straight after a growth pause still carries an
        # in-flight level's committed-tile gen (run() adds per chunk)
        res.states_generated += gen_level
        if por_on:
            self._por_kept += gen_level
            self._por_full += gfull_level
            self._por_amp += amp_level
        set_pointers(fp_count if reason == RUNNING and n_front == 0
                     else level_base + n_front)
        res.diameter = depth
        return self._finish(res, obs, fp_count,
                            table=table, fp_cap=fp_cap)

    # ------------------------------------------------------------------
    # chained run: a pipelined window that survives level boundaries
    # ------------------------------------------------------------------
    @closes_observer
    def run_chained(self, max_states=None, max_depth=None,
                    max_seconds=None, check_deadlock=False, log=None,
                    progress_every=10.0, levels_cap=1024,
                    checkpoint_path=None, checkpoint_every=None,
                    obs=None) -> CheckResult:
        """Like run() with ``-pipeline K``, but the dispatch window
        SURVIVES level transitions (ISSUE 9 tentpole lever 3): run()
        must drain its window at every level boundary — the host swaps
        the frontier buffers and resets the chain scalars — so on a
        level-heavy space the device idles through one host round-trip
        per level no matter how deep the window is.  Here each dispatch
        is the fused multi-level pass (_make_multilevel) bounded to a
        ``chunk_tiles`` TILE budget: a budget boundary can land
        mid-level (the partial (start_t, nn, gen_level) ride the carry,
        exactly like a growth pause), the on-device ping-pong swap
        carries the frontier across level ends, and the next dispatch
        chains on the previous one's device-side carry — so the K-deep
        window stays full through level transitions with zero host
        syncs to refill it.

        Pause discipline is unchanged: dispatches chained behind a
        pause re-attempt the same tile, commit nothing, and re-fail
        identically, so drained tickets carry no deltas and counts /
        level sizes / violation traces are BIT-IDENTICAL to run() for
        every K (tests/test_pack.py asserts it).  Trace pointers and
        level sizes accumulate on device fused-style and are pulled per
        collected ticket (level sizes) / at the end (pointers).

        Rescue seam (ISSUE 10 satellite): with ``checkpoint_path`` the
        chained run is checkpointable — when the cadence fires (or a
        PreemptionGuard signal is pending) the window stops refilling,
        drains through normal collects (trailing tickets hold REAL
        work), and if the chain sits mid-level ONE level-bounded
        dispatch (``max_lvls=1``, unbounded tile budget) completes the
        current level exactly; a run()-format snapshot is then written
        at the boundary, so a checkpointed run no longer has to fall
        back to run().  The snapshot resumes through ``run()`` (the
        supervisor journals that as a mode degrade, like fused)."""
        from ..analysis import preflight
        preflight(self.spec, log=log)
        obs = RunObserver.ensure(obs, "device-chained", self.spec,
                                 log=log, progress_every=progress_every)
        obs.pipeline = self.pipe_window
        obs.pack = self._pk is not None
        obs.commit = self.commit
        obs.symmetry = self._symmetry_on()
        obs.bounds = self._bounds_doc()
        obs.edges = self._edges_on
        obs.por = self._por_doc()
        self._obs_active = obs          # closes_observer finalizes it
        spec = self.spec
        self._act_counts = np.zeros(len(self.kern.action_names),
                                    np.int64)
        self._tiles_done = 0
        self._lanes_disp = 0
        self._por_kept = self._por_full = self._por_amp = 0
        res = CheckResult()
        t0 = time.time()
        obs.start(t0, backend=jax.default_backend())

        fp_cap = self.fpset_capacity
        self.level_sizes = []      # no stale trajectory on init-viol
        table, init_batch, n0, viol = self._register_init(res)
        if viol is not None:
            return self._finish(res, obs, n0, table=table, fp_cap=fp_cap)
        f_cap = max(self.next_cap, n0)
        front, nbp, nba, nbprm = self._alloc_bufs(f_cap)
        front = self._set_rows(front, init_batch, n0)
        nb, _, _, _ = self._alloc_bufs(f_cap)
        tp_cap = max(4 * f_cap, 1 << 16)
        tpp = jnp.full((tp_cap,), -1, I32)
        tpa = jnp.full((tp_cap,), -1, I32)
        tpm = jnp.zeros((tp_cap,), I32)
        lvl_buf = jnp.zeros((levels_cap,), I32)
        md = 2**31 - 1 if max_depth is None else int(max_depth)
        ms = int(max_states) if max_states else 2**31 - 1

        # device-side chain scalars: rebound from every launch's output
        # so filling the window costs zero host syncs (run()'s chain is
        # just (start_t, nn); here the whole fused carry chains)
        d_n_front = jnp.asarray(n0, I32)
        d_start = jnp.asarray(0, I32)
        d_nn = jnp.asarray(0, I32)
        d_gen_level = jnp.asarray(0, I32)
        d_depth = jnp.asarray(0, I32)
        d_level_base = jnp.asarray(0, I32)
        d_fp = jnp.asarray(n0, I32)
        por_on = self._por_active
        d_gfull_level = jnp.asarray(0, I32)
        d_amp_level = jnp.asarray(0, I32)
        gfull_level, amp_level = 0, 0
        self.level_sizes = [n0]
        depth, fp_count, n_front = 0, n0, n0
        level_base, gen_level = 0, 0
        h_start, h_nn = 0, 0      # collected chain position (seam)

        from .pipeline import DispatchPipeline
        pipe = DispatchPipeline(self.pipe_window, obs,
                                ready=lambda o: o["reason"])

        def pull(o):
            vals = [o["reason"], o["n_front"], o["depth"],
                    o["fp_count"], o["level_base"], o["lvl_cur"],
                    o["gen"], o["gen_level"], o["act"], o["start_t"],
                    o["nn"], o["tiles"], o["need"]]
            if por_on:
                vals += [o["gfull"], o["gfull_level"],
                         o["amp"], o["amp_level"]]
            return jax.device_get(vals)

        def set_pointers(n):
            self._h_parent = [np.asarray(tpp[:n]).astype(np.int64)]
            self._h_action = [np.asarray(tpa[:n])]
            self._h_param = [np.asarray(tpm[:n])]

        def collect_one():
            """Collect the oldest ticket, fold its deltas into the
            host-side totals, and emit its committed levels."""
            nonlocal depth, fp_count, n_front, level_base, gen_level
            nonlocal h_start, h_nn, levels_unck
            nonlocal gfull_level, amp_level
            out, sc = pipe.collect(pull)
            (reason, n_front, depth, fp_count, level_base, lvl_cur,
             gen_add, gen_level) = (int(x) for x in sc[:8])
            res.states_generated += gen_add
            self._act_counts += np.asarray(sc[8], np.int64)
            h_start, h_nn = int(sc[9]), int(sc[10])
            levels_unck += lvl_cur
            self._account_tiles(int(sc[11]))
            self._fold_need(sc[12])
            if por_on:
                self._por_kept += gen_add
                self._por_full += int(sc[13])
                self._por_amp += int(sc[15])
                gfull_level, amp_level = int(sc[14]), int(sc[16])
            if lvl_cur:
                # each dispatch records its own committed levels from
                # slot 0 of ITS lvl_buf output (which is why lvl_buf is
                # excluded from donation: this read can race a newer
                # in-flight dispatch)
                with obs.timer("host_sync"):
                    sizes = np.asarray(out["lvl_buf"][:lvl_cur])
                cum = sum(self.level_sizes)
                for x in sizes:
                    prev = self.level_sizes[-1]
                    self.level_sizes.append(int(x))
                    cum += int(x)
                    obs.level_done(len(self.level_sizes) - 1,
                                   frontier=prev, distinct=cum,
                                   generated=res.states_generated)
            return out, reason

        emit = obs.log
        stop = None
        ckpt_due = False
        levels_unck = 0     # levels committed since the last snapshot
        last_checkpoint = time.time()

        def launch_next(tile_budget, max_lvls):
            nonlocal table, front, nb, nbp, nba, nbprm, tpp, tpa, tpm
            nonlocal lvl_buf, d_n_front, d_start, d_nn, d_gen_level
            nonlocal d_depth, d_level_base, d_fp
            nonlocal d_gfull_level, d_amp_level
            fresh = self._fresh_jit or self._wl is None
            if self._wl is None:
                # the SAME pass run_fused jits, minus the lvl_buf
                # donation (argnum 9): collected tickets read their
                # level counters back while newer dispatches are
                # already consuming the other buffers
                self._wl = jax.jit(self._make_multilevel(),
                                   donate_argnums=tuple(range(9)))
            out = pipe.launch(
                self._wl, table["slots"], front, nb, nbp, nba,
                nbprm, tpp, tpa, tpm, lvl_buf,
                d_n_front, d_start, d_nn, d_gen_level, d_depth,
                d_level_base, d_fp,
                jnp.asarray(bool(check_deadlock)),
                jnp.asarray(md, I32), jnp.asarray(ms, I32),
                jnp.asarray(max_lvls, I32),
                jnp.asarray(0, I32),
                jnp.asarray(tile_budget, I32),
                *((table["gids"], d_gfull_level, d_amp_level)
                  if por_on else ()),
                fresh=fresh, label=f"window (depth {depth}+)")
            self._fresh_jit = False
            table = {"slots": out["slots"]}
            if por_on:
                table["gids"] = out["gids"]
                d_gfull_level = out["gfull_level"]
                d_amp_level = out["amp_level"]
            front, nb = out["front"], out["nb"]
            nbp, nba, nbprm = out["nbp"], out["nba"], out["nbprm"]
            tpp, tpa, tpm = out["tpp"], out["tpa"], out["tpm"]
            lvl_buf = out["lvl_buf"]
            d_n_front, d_start = out["n_front"], out["start_t"]
            d_nn, d_gen_level = out["nn"], out["gen_level"]
            d_depth, d_level_base = out["depth"], out["level_base"]
            d_fp = out["fp_count"]

        while True:
            if not ckpt_due:
                while pipe.has_room():
                    launch_next(self.chunk_tiles, levels_cap)
            if pipe.in_flight:
                out, reason = collect_one()
            else:
                # rescue seam: the window drained while a checkpoint
                # was pending — fall through to the seam below
                out, reason = None, RUNNING
            obs.progress(depth=depth, distinct=fp_count,
                         generated=res.states_generated)

            if reason == RUNNING:
                if n_front == 0:
                    pipe.drain()            # trailing no-op tickets
                    break
                if max_depth is not None and depth >= max_depth:
                    stop = f"depth limit {max_depth} reached"
                elif max_states and fp_count >= max_states:
                    stop = f"state limit {max_states} reached"
                elif max_seconds and time.time() - t0 > max_seconds:
                    stop = f"time budget {max_seconds}s reached"
                if stop:
                    # trailing tickets hold REAL committed work (unlike
                    # a pause, whose replays commit nothing): consume
                    # them so the reported counts reflect what ran
                    while pipe.in_flight:
                        out, reason = collect_one()
                    break
                if level_base + n_front + nbp.shape[0] > tp_cap:
                    # trace-pointer store pressure paused the kernel
                    # (trailing tickets hit the same guard: no-ops)
                    pipe.drain()
                    add = tp_cap
                    tpp = jnp.concatenate(
                        [tpp, jnp.full((add,), -1, I32)])
                    tpa = jnp.concatenate(
                        [tpa, jnp.full((add,), -1, I32)])
                    tpm = jnp.concatenate([tpm, jnp.zeros((add,), I32)])
                    tp_cap += add
                    self._fresh_jit = True   # shape change: retrace
                    obs.grow("trace_pointer_store", tp_cap)
                    emit(f"trace-pointer store grown to {tp_cap}")
                    continue
                # ---- level-boundary rescue seam (ISSUE 10 satellite):
                # stop refilling, drain through normal collects
                # (trailing tickets hold real work), complete the
                # current level with ONE level-bounded dispatch when
                # the chain sits mid-level, then snapshot in run()
                # format at the boundary
                rescue = preempt_signal()
                # checkpoint_every=None means "every level boundary"
                # (run() parity) — gated on a NEW committed level so
                # the seam never drains the window without fresh work
                # to snapshot
                if rescue is not None or (checkpoint_path and (
                        (checkpoint_every is None and levels_unck > 0)
                        or (checkpoint_every is not None
                            and time.time() - last_checkpoint
                            >= checkpoint_every))):
                    ckpt_due = True
                if ckpt_due:
                    if pipe.in_flight:
                        continue
                    if h_start or h_nn:
                        launch_next(2**31 - 1, 1)
                        continue
                    ckpt_due = False
                    levels_unck = 0
                    if checkpoint_path:
                        from .checkpoint import (save_checkpoint,
                                                 spec_digest)
                        with obs.timer("checkpoint"):
                            set_pointers(level_base + n_front)
                            save_checkpoint(
                                checkpoint_path,
                                slots=table["slots"],
                                frontier=self._dense_rows(front,
                                                          n_front),
                                n_front=n_front,
                                h_parent=np.concatenate(self._h_parent),
                                h_action=np.concatenate(self._h_action),
                                h_param=np.concatenate(self._h_param),
                                init_dense=self._init_dense,
                                level_sizes=self.level_sizes,
                                depth=depth, fp_count=fp_count,
                                states_generated=res.states_generated,
                                max_msgs=self.codec.shape.MAX_MSGS,
                                expand_mults=self.expand_mults,
                                elapsed=time.time() - t0,
                                digest=spec_digest(spec),
                                pack=self._pack_manifest(),
                                canon=self._canon_manifest(),
                                bounds=self._bounds_manifest(),
                                por=self._por_manifest(), obs=obs)
                        last_checkpoint = time.time()
                        obs.checkpoint(checkpoint_path, depth, fp_count)
                        emit(f"checkpoint written to {checkpoint_path} "
                             f"(depth {depth}, {fp_count} distinct; "
                             f"resume via the chunked engine)")
                    if rescue is not None:
                        obs.rescue(checkpoint_path or "", depth,
                                   fp_count, rescue)
                        emit(f"preempted by {rescue}: "
                             + (f"rescue snapshot at depth {depth} "
                                f"({checkpoint_path}); exiting "
                                f"resumable" if checkpoint_path else
                                f"no checkpoint path — exiting at the "
                                f"depth-{depth} boundary with no "
                                f"snapshot"))
                        raise Preempted(checkpoint_path, depth,
                                        fp_count, rescue)
                # else: tile budget (the normal windowed cadence) or a
                # full per-dispatch level counter (next dispatch resets
                # it) — just keep the window full
                continue
            # pause or terminal: trailing tickets are commit-nothing
            # replays; handle the reason on the chain-tip buffers
            pipe.drain()
            if reason == R_VIOLATION:
                res.states_generated += gen_level
                if por_on:
                    self._por_kept += gen_level
                    self._por_full += gfull_level
                    self._por_amp += amp_level
                vp, va, vprm = (int(v) for v in np.asarray(out["viol"]))
                gid = level_base + vp
                parent_dense = self._fetch_row(front, vp)
                vstate = self._materialize_one(parent_dense, va, vprm)
                bad = spec.check_invariants(self.codec.decode(vstate))
                if bad is None:
                    raise TLAError(
                        "device/interpreter divergence: device "
                        "invariant kernel reported a violation the "
                        "interpreter accepts (parent gid "
                        f"{gid}, action {self.kern.action_names[va]})")
                set_pointers(level_base + n_front)
                res.ok = False
                res.violated_invariant = bad
                res.trace = self._trace(gid, extra=(va, vprm))
                res.diameter = depth + 1
                return self._finish(res, obs, fp_count,
                                    table=table, fp_cap=fp_cap)
            if reason == R_DEADLOCK:
                res.states_generated += gen_level
                if por_on:
                    self._por_kept += gen_level
                    self._por_full += gfull_level
                    self._por_amp += amp_level
                di = int(out["dead"])
                set_pointers(level_base + n_front)
                res.ok = False
                res.error = "deadlock"
                res.deadlock_state = self.codec.decode(
                    self._fetch_row(front, di))
                res.trace = self._trace(level_base + di)
                res.diameter = depth + 1
                return self._finish(res, obs, fp_count,
                                    table=table, fp_cap=fp_cap)
            if reason == R_BAG_GROW:
                front, nb = self._grow_msgs([front, nb])
                obs.grow("message_table", self.codec.shape.MAX_MSGS)
                emit(f"message table grown to "
                     f"{self.codec.shape.MAX_MSGS} slots (recompiling)")
            elif reason == R_FPSET_GROW:
                table = grow(table)
                fp_cap *= 4
                self._fresh_jit = True
                obs.grow("fpset", fp_cap)
                emit(f"FPSet grown to {fp_cap} slots")
            elif reason == R_NEXT_GROW:
                old_cap = nbp.shape[0]
                front, nbp, nba, nbprm = self._grow_next(
                    (front, nbp, nba, nbprm))
                nb = self._pad_rows(nb, nbp.shape[0] - old_cap)
                f_cap = nbp.shape[0]
                self._fresh_jit = True
                obs.grow("next_buffer", f_cap)
                emit(f"frontier buffers grown to {f_cap}")
            elif reason == R_EXPAND_GROW:
                self._grow_expand(int(out["grow_aid"]), obs, emit)
            elif reason == R_SLOT_ERR:
                raise TLAError(
                    "dense-layout slot collision (a second DVC or "
                    "recovery response from one source in one view): "
                    "this restart-era interleaving needs the "
                    "multi-slot layout (vsr.py docstring)")

        res.states_generated += gen_level
        if por_on:
            self._por_kept += gen_level
            self._por_full += gfull_level
            self._por_amp += amp_level
        set_pointers(fp_count if (stop is None and n_front == 0)
                     else level_base + n_front)
        if stop:
            res.error = stop
        res.diameter = depth
        return self._finish(res, obs, fp_count,
                            table=table, fp_cap=fp_cap)

    # ------------------------------------------------------------------
    def _flush_pointers(self):
        """Materialize any still-on-device trace-pointer levels (the
        per-level fetches are issued async)."""
        for i, v in enumerate(self._h_parent):
            if isinstance(v, tuple):
                arr, off = v
                self._h_parent[i] = np.asarray(arr).astype(np.int64) + off
        for lst in (self._h_action, self._h_param):
            for i, v in enumerate(lst):
                if not isinstance(v, np.ndarray):
                    lst[i] = np.asarray(v, np.int32)

    def _fetch_row(self, batch, i):
        """One dense state row from a frontier-format buffer (packed
        rows are unpacked host-side)."""
        if not isinstance(batch, dict):
            return self._pk.unpack_row_np(np.asarray(batch[i]))
        return {k: np.asarray(v[i]) for k, v in batch.items()}

    def _materialize_one(self, st, aid, param):
        """Apply one recorded (action, lane param) to a single dense
        state — the trace-replay step."""
        fn = self._mat.get(aid)
        if fn is None:
            fn = jax.jit(jax.vmap(self.kern._action_fns()[aid],
                                  in_axes=(0, 0)))
            self._mat[aid] = fn
        batch = {k: np.asarray(v)[None] for k, v in st.items()}
        succ, en = fn(batch, jnp.asarray([param], jnp.int32))
        assert bool(np.asarray(en)[0]), "trace replay chose a disabled lane"
        return {k: np.asarray(v)[0] for k, v in succ.items()
                if not k.startswith("_")}

    def _finish(self, res, obs, fp_count, table=None, fp_cap=None):
        """Uniform result finalization: the collector (not the engine)
        stamps elapsed/states_per_sec/levels/metrics (ISSUE 2
        satellite — no more post-hoc res.elapsed patching)."""
        res.distinct_states = fp_count
        self._pack_gauges(obs)
        self._bounds_gauges(obs)
        self._por_gauges(obs)
        # symmetry canonicalization gauges (ISSUE 11): group order
        # this run reduced by (1 = off), and the headline
        # generated/distinct-after-canon ratio — on a symmetry-on run
        # it folds the orbit factor on top of ordinary dedup, so the
        # on-vs-off A/B reads the orbit cut straight off the journal
        obs.gauge("symmetry_perms",
                  self._canon.perms if self._canon is not None
                  else self._sym_fold)
        if res.states_generated and fp_count:
            obs.gauge("orbit_ratio",
                      round(res.states_generated / fp_count, 4))
        if fp_cap:
            obs.gauge("fpset_capacity", int(fp_cap))
            obs.gauge("fpset_occupancy", fp_count / fp_cap)
        acts = getattr(self, "_act_counts", None)
        if acts is not None:
            # per-action expansion counters from the on-device
            # accumulator (ISSUE 4 satellite); sums to generated minus
            # the init states on a clean run
            obs.gauge("action_expansions",
                      {n: int(c) for n, c in
                       zip(self.kern.action_names, acts)})
        # occupancy = real work items / expand lanes dispatched, and
        # the structural insert_core batches per frontier tile
        # (ISSUE 10: 1 fused vs n_actions per-action)
        lanes = getattr(self, "_lanes_disp", 0)
        if lanes and acts is not None:
            obs.gauge("occupancy",
                      round(float(acts.sum()) / lanes, 4))
        obs.gauge("inserts_per_tile",
                  1 if self.commit == "fused"
                  else len(self.kern.action_names))
        obs.gauge("commit_mode", self.commit)
        if table is not None and obs.detailed:
            from .fpset import table_stats
            st = table_stats(table["slots"])
            obs.gauge("fpset_occupancy", st["occupancy"])
            obs.gauge("fpset_collision_rate", st["collision_rate"])
        return obs.finish(res, levels=getattr(self, "level_sizes", None))

    def _trace(self, gid, extra=None):
        """Walk the host pointer table back to an init state, then
        replay the recorded (action, param) chain through the kernel to
        materialize each state, emitting TRACE-format entries."""
        self._flush_pointers()
        parent = np.concatenate(self._h_parent)
        action = np.concatenate(self._h_action)
        param = np.concatenate(self._h_param)
        steps = []
        cur = gid
        while action[cur] >= 0:
            steps.append((int(action[cur]), int(param[cur])))
            cur = int(parent[cur])
        steps.reverse()
        if extra is not None:
            steps.append(extra)
        loc = {a.name: a.location for a in self.spec.actions}
        st = self.codec.encode(self._init_states[cur])
        out = [TraceEntry(position=1, action_name=None, location=None,
                          state=self.codec.decode(st))]
        for pos, (aid, prm) in enumerate(steps):
            st = self._materialize_one(st, aid, prm)
            name = self.kern.action_names[aid]
            out.append(TraceEntry(position=pos + 2, action_name=name,
                                  location=loc.get(name),
                                  state=self.codec.decode(st)))
        return out


def device_bfs_check(spec: SpecModel, max_states=None, max_depth=None,
                     check_deadlock=False, tile_size=128, max_msgs=None,
                     log=None, obs=None) -> CheckResult:
    """Run the device BFS (message-table growth happens in place)."""
    eng = DeviceBFS(spec, max_msgs=max_msgs, tile_size=tile_size)
    return eng.run(max_states=max_states, max_depth=max_depth,
                   check_deadlock=check_deadlock, log=log, obs=obs)
