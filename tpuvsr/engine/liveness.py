"""Liveness checking: behavior graph x property automaton, fair-SCC
search under weak fairness (SURVEY.md §3.4; exercised by the 01-series
cfgs: SPECIFICATION LivenessSpec + PROPERTY ConvergenceToView /
OpEventuallyAllOrNothing, A01:770-809).

Property shapes supported (the corpus's inventory):
  []<>P                  — violated by a fair lasso whose cycle is
                           everywhere ~P
  P ~> Q                 — violated by a fair lasso with a P-state at or
                           before the cycle and no later Q
  \\A x \\in S : ...      — constant-set quantification over either shape

Both negations are one-jump Büchi automata (guess the point after which
the bad condition holds forever), so the product graph is at most twice
the behavior graph.  A cycle C is weakly fair for WF_vars(A) iff C takes
a real (state-changing) A-step or some state of C has <<A>>_vars
disabled; infinite stuttering at a state is a (trivially) fair cycle for
every WF whose action is disabled there — TLC's temporal semantics for
[][Next]_vars specs.

The graph is built with the interpreter (liveness configs are the small
ones; symmetry must be off, as the reference cfg comments insist —
A01 cfg:22-24).  States are identified by their VIEW value, matching
TLC's behavior-graph construction under a VIEW.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..core.values import TLAError
from .spec import SpecModel
from .trace import TraceEntry


@dataclass
class LivenessResult:
    ok: bool = True
    property_name: str = None
    distinct_states: int = 0
    elapsed: float = 0.0
    trace: list = field(default_factory=list)   # prefix + cycle
    cycle_start: int = 0                        # index into trace
    error: str = None
    metrics: dict = None      # tpuvsr-metrics/1 document for this run


def _build_graph(spec: SpecModel, max_states=None):
    """Reachable behavior graph: states, edges (sid, action, tid)."""
    if spec.symmetry_perms:
        raise TLAError("liveness checking requires SYMMETRY off "
                       "(reference cfg guidance, A01 cfg:22-24)")
    ids = {}
    states = []
    edges = []          # list of lists: sid -> [(action_name, tid)]
    order = []

    def intern(st):
        k = spec.view_value(st)
        sid = ids.get(k)
        if sid is None:
            sid = len(states)
            ids[k] = sid
            states.append(st)
            edges.append([])
            order.append(sid)
        return sid

    frontier = [intern(st) for st in spec.init_states()]
    inits = list(frontier)
    seen_depth = 0
    while frontier:
        seen_depth += 1
        nxt = []
        for sid in frontier:
            if edges[sid]:
                continue
            st = states[sid]
            for action, succ in spec.successors(st):
                known = len(states)
                tid = intern(succ)
                edges[sid].append((action.name, tid))
                if tid >= known:
                    nxt.append(tid)
            if max_states and len(states) > max_states:
                raise TLAError(
                    f"liveness graph exceeds {max_states} states")
        frontier = nxt
    return states, edges, inits


def _collect_props(spec: SpecModel, name):
    """Expand a PROPERTY definition into (kind, P_expr, Q_expr, env)
    leaves; kind in {"gf", "leadsto"}."""
    from ..interp.evalr import EMPTY_ENV, EvalCtx
    d = spec.module.defs.get(name)
    if d is None:
        raise TLAError(f"PROPERTY {name} not defined")
    leaves = []

    def walk(e, env):
        tag = e[0]
        if tag == "box" and e[1][0] == "diamond":
            leaves.append(("gf", e[1][1], None, env))
        elif tag == "binop" and e[1] == "leadsto":
            leaves.append(("leadsto", e[2], e[3], env))
        elif tag == "forall":
            for binding in spec.ev._group_bindings(e[1], env, EvalCtx({})):
                walk(e[2], env.extend(binding))
        elif tag == "and":
            for x in e[1]:
                walk(x, env)
        elif tag == "id" and e[1] in spec.module.defs:
            walk(spec.module.defs[e[1]].body, env)
        else:
            raise TLAError(f"unsupported temporal property shape: {tag}")
    walk(d.body, EMPTY_ENV)
    return leaves


def _eval_pred(spec, expr, env, st):
    from ..interp.evalr import EvalCtx
    return spec.ev.eval(expr, env, EvalCtx(st)) is True


def _fairness_groups(spec):
    """WF action groups from the decomposed SPECIFICATION.

    The corpus uses two WF shapes: per-action ``WF_vars(SendDVC)``
    lists (A01:793-806) and a single disjunction ``WF_vars(WFActions)``
    with ``WFActions == A1 \\/ A2 \\/ ...`` (ST03:922-943, AL05, CP06)
    — and VSR's ``WF_vars(Next)``.  WF of a disjunction is fair iff
    some disjunct is taken infinitely often or the whole disjunction is
    disabled infinitely often, so each WF formula becomes a *group* of
    action names."""
    action_names = {a.name for a in spec.actions}
    groups = []
    for kind, _sub, act in spec.fairness:
        if kind != "wf":
            raise TLAError("only weak fairness appears in the corpus")
        if act[0] != "id":
            raise TLAError(f"unsupported fairness action: {act!r}")
        name = act[1]
        if name in action_names:
            groups.append((name, frozenset([name])))
            continue
        d = spec.module.defs.get(name)
        if d is None:
            raise TLAError(f"WF action {name} not defined")
        members = set()

        def flat(e):
            if e[0] == "or":
                for x in e[1]:
                    flat(x)
            elif e[0] == "id" and e[1] in action_names:
                members.add(e[1])
            elif e[0] == "id" and e[1] in spec.module.defs:
                flat(spec.module.defs[e[1]].body)
            else:
                raise TLAError(
                    f"WF action {name} is not a disjunction of actions")
        flat(d.body)
        groups.append((name, frozenset(members)))
    return groups


def _tarjan_sccs(n_nodes, succ):
    """Iterative Tarjan over node ids 0..n-1 with succ(u) -> iterable."""
    index = [-1] * n_nodes
    low = [0] * n_nodes
    onstack = [False] * n_nodes
    stack = []
    sccs = []
    counter = [0]
    for root in range(n_nodes):
        if index[root] != -1:
            continue
        work = [(root, 0, list(succ(root)))]
        while work:
            u, pi, children = work[-1]
            if pi == 0:
                index[u] = low[u] = counter[0]
                counter[0] += 1
                stack.append(u)
                onstack[u] = True
            advanced = False
            for ci in range(pi, len(children)):
                v = children[ci]
                if index[v] == -1:
                    work[-1] = (u, ci + 1, children)
                    work.append((v, 0, list(succ(v))))
                    advanced = True
                    break
                elif onstack[v]:
                    low[u] = min(low[u], index[v])
            if advanced:
                continue
            if low[u] == index[u]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack[w] = False
                    comp.append(w)
                    if w == u:
                        break
                sccs.append(comp)
            work.pop()
            if work:
                p = work[-1][0]
                low[p] = min(low[p], low[u])
    return sccs


def build_graph(spec: SpecModel, max_states=None):
    """Public: the reachable behavior graph (states, edges, inits).
    Reusable across property runs — e.g. checking a spec with and
    without its liveness shields shares one graph, since shield
    predicates appear only in properties, never in Next."""
    return _build_graph(spec, max_states)


def liveness_check(spec: SpecModel, max_states=None,
                   log=None, graph=None, obs=None) -> LivenessResult:
    """`graph` may be the interpreter-built (states, edges, inits)
    triple from build_graph, or a device-built
    engine.device_liveness.DeviceGraph (same attributes, lazy state
    decode, batched predicate evaluation)."""
    from ..obs import RunObserver
    obs = RunObserver.ensure(obs, "liveness", spec, log=log)
    res = LivenessResult()
    t0 = time.time()
    obs.start(t0, backend="host")
    dev_graph = None
    try:
        with obs.timer("graph_build"):
            if graph is None:
                states, edges, inits = _build_graph(spec, max_states)
            elif hasattr(graph, "batch_predicate"):
                dev_graph = graph
                states, inits = graph.states, graph.inits
                # don't touch .edges when CSR arrays exist —
                # materializing the list-of-lists view defeats the
                # array representation
                edges = None if hasattr(graph, "csr") else graph.edges
            else:
                states, edges, inits = graph
    except TLAError as e:
        res.ok = False
        res.error = str(e)
        return obs.finish(res)
    import numpy as np

    res.distinct_states = len(states)
    n = len(states)
    wf_groups = _fairness_groups(spec)

    # edge access: CSR arrays when the device graph provides them
    # (shipped-constant graphs are far too large for list-of-lists),
    # else the interpreter's list form
    csr = getattr(dev_graph, "csr", None) if dev_graph else None
    if csr is not None:
        indptr, aidv, tidv = csr
        names = list(dev_graph.kern.action_names)
        srcv = np.repeat(np.arange(n, dtype=np.int64),
                         np.diff(indptr))
        n_edges = int(tidv.shape[0])

        def edges_of(u):
            return [(names[int(aidv[j])], int(tidv[j]))
                    for j in range(indptr[u], indptr[u + 1])]

        def succ_tids(u):
            return tidv[indptr[u]:indptr[u + 1]]

        # vectorized per-group "has a real member step" arrays
        real = tidv != srcv
        name_to_aid = {nm: i for i, nm in enumerate(names)}
        genab = []
        for _gname, members in wf_groups:
            maids = np.asarray([name_to_aid[m] for m in members
                                if m in name_to_aid], np.int32)
            sel = real & np.isin(aidv, maids)
            g = np.zeros(n, bool)
            np.logical_or.at(g, srcv[sel], True)
            genab.append(g)

        def group_enabled(u, gi):
            return bool(genab[gi][u])
    else:
        n_edges = sum(len(e) for e in edges)

        def edges_of(u):
            return edges[u]

        def succ_tids(u):
            return np.asarray([t for _a, t in edges[u]], np.int64)

        enabled = [set() for _ in range(n)]
        for sid in range(n):
            for aname, tid in edges[sid]:
                if tid != sid:
                    enabled[sid].add(aname)

        def group_enabled(u, gi):
            return bool(enabled[u] & wf_groups[gi][1])

    if log:
        log(f"behavior graph: {n} states, {n_edges} edges")

    def batch_values(expr, env):
        """[n] device-batched bools, or None when the leaf cannot be
        evaluated on device (no kernel and no AST lowerer)."""
        if dev_graph is None:
            return None
        if expr[0] == "id" and env.is_empty():
            vals = dev_graph.batch_predicate(expr[1])
            if vals is not None:
                return np.asarray(vals, bool)
        if hasattr(dev_graph, "batch_expr"):
            vals = dev_graph.batch_expr(expr, _flatten_env(env))
            if vals is not None:
                return np.asarray(vals, bool)
        return None

    def pred_values(expr, env):
        vals = batch_values(expr, env)
        if vals is not None:
            return vals
        return np.fromiter(
            (_eval_pred(spec, expr, env, states[sid])
             for sid in range(n)), bool, n)

    obs.gauge("graph_states", n)
    obs.gauge("graph_edges", int(n_edges))
    if dev_graph is not None:
        # streamed-graph health (ISSUE 15): how the device graph was
        # built, what the construction cost beyond the safety BFS
        # was, and the edge emission rate — the liveness acceptance
        # gauges the bench round and compare_bench's gate read
        if getattr(dev_graph, "mode", None):
            obs.gauge("graph_mode", dev_graph.mode)
        if getattr(dev_graph, "graph_overhead_ratio", None) is not None:
            obs.gauge("graph_overhead_ratio",
                      dev_graph.graph_overhead_ratio)
        if getattr(dev_graph, "edges_per_s", None) is not None:
            obs.gauge("edges_per_s", dev_graph.edges_per_s)
    for prop_name in spec.temporal_props:
        for kind, p_expr, q_expr, env in _collect_props(spec, prop_name):
            if kind == "gf":
                # violation automaton: jump to phase 1 on ~P, stay on ~P
                bad = ~pred_values(p_expr, env)
                seed = bad
            else:
                # P ~> Q: phase-1 condition is ~Q; the jump additionally
                # requires P at the jump state — P is evaluated only
                # where ~Q holds unless a device batch is available
                bad = ~pred_values(q_expr, env)
                pv = batch_values(p_expr, env)
                if pv is not None:
                    seed = bad & pv
                else:
                    seed = np.asarray(
                        [bool(bad[sid])
                         and _eval_pred(spec, p_expr, env, states[sid])
                         for sid in range(n)], bool)

            # phase-1 subgraph: states with bad=True, edges bad->bad
            # (+ implicit stutter self-loops).  A fair cycle inside it
            # reachable from a seed state violates the property.
            def p1_succ(u):
                tt = succ_tids(u)
                return tt[bad[tt]] if csr is not None else \
                    [t for t in tt if bad[t]]

            sccs = _tarjan_sccs(n, lambda u: p1_succ(u) if bad[u] else ())

            def cycle_fair(comp):
                """A fair cycle exists within this (all-bad) SCC iff for
                every WF group: some internal state-changing edge takes
                a member, or some SCC state has the whole group disabled
                — strong connectivity then stitches one cycle through
                all the witnesses.  A singleton SCC is the stuttering
                lasso, fair iff every WF group is disabled there."""
                comp_set = set(comp)
                taken = {a for u in comp for (a, t) in edges_of(u)
                         if t in comp_set and t != u}
                for gi, (_gname, members) in enumerate(wf_groups):
                    if taken & members:
                        continue
                    if all(group_enabled(u, gi) for u in comp):
                        return False    # group always enabled, no
                                        # member ever taken: unfair
                return True

            # a violation needs BOTH a fair all-bad SCC and a lasso
            # reaching it (init -> seed -> bad-only path) — try every
            # candidate SCC, not just the first
            for comp in sccs:
                if not all(bad[u] for u in comp):
                    continue
                if not cycle_fair(comp):
                    continue
                path = _find_lasso(spec, states, edges_of, inits, seed,
                                   bad, set(comp))
                if path is not None:
                    res.ok = False
                    res.property_name = prop_name
                    res.trace, res.cycle_start = path
                    return obs.finish(res)
    return obs.finish(res)


def _flatten_env(env):
    """interp Env chain -> {name: value} with inner bindings winning."""
    chain = []
    while env is not None:
        chain.append(env.mapping)
        env = env.parent
    out = {}
    for m in reversed(chain):
        out.update(m)
    return out


def _find_lasso(spec, states, edges_of, inits, seed, bad, comp):
    """BFS init -> seed state s, then bad-only path s -> comp; returns
    (trace_entries, cycle_start_index) or None."""
    from collections import deque

    # phase A: shortest path from any init to a seed state
    prev = {}
    dq = deque()
    for i in inits:
        if i not in prev:
            prev[i] = (None, None)
            dq.append(i)
    target = None
    while dq:
        u = dq.popleft()
        if seed[u]:
            # phase B must reach comp from u via bad states
            pb = _bad_path(edges_of, bad, u, comp)
            if pb is not None:
                target = (u, pb)
                break
        for aname, t in edges_of(u):
            if t not in prev:
                prev[t] = (u, aname)
                dq.append(t)
    if target is None:
        return None
    u, pb = target
    # reconstruct prefix
    pre = []
    cur = u
    while cur is not None:
        p, a = prev[cur]
        pre.append((cur, a))
        cur = p
    pre.reverse()
    full = pre + pb[1:] if pb else pre
    loc = {a.name: a.location for a in spec.actions}
    entries = []
    for i, (sid, aname) in enumerate(full):
        entries.append(TraceEntry(
            position=i + 1, action_name=aname,
            location=loc.get(aname) if aname else None,
            state=states[sid]))
    cycle_start = len(pre) - 1 if not pb or len(pb) <= 1 else len(pre)
    return entries, max(0, cycle_start)


def _bad_path(edges_of, bad, start, comp):
    """BFS through bad-states from start into comp; [(sid, action)]."""
    from collections import deque
    if start in comp:
        return [(start, None)]
    prev = {start: (None, None)}
    dq = deque([start])
    while dq:
        u = dq.popleft()
        for aname, t in edges_of(u):
            if bad[t] and t not in prev:
                prev[t] = (u, aname)
                if t in comp:
                    out = []
                    cur = t
                    while cur is not None:
                        p, a = prev[cur]
                        out.append((cur, a))
                        cur = p
                    out.reverse()
                    return out
                dq.append(t)
    return None
