"""Device-resident fingerprint set (the TLC FPSet rebuilt for HBM).

The reference workload drove TLC's disk-spilling FPSet to 500 GB
(README:20); the TPU engine instead keeps 128-bit fingerprints in an
HBM-resident open-addressing hash table and batch-inserts an entire
frontier expansion per call (SURVEY.md §2.5).

Layout: a claim array ``tags[CAP]`` holding word 0 of each fingerprint
(0 = empty; fingerprints with word 0 == 0 are remapped to 1) and a
payload array ``rows[CAP, 3]`` holding words 1..3.  Insertion is
claim-then-verify linear probing, fully vectorized over the batch:

  1. gather the tag at each lane's probe slot;
  2. lanes seeing their own tag compare the payload — equal means
     duplicate (resolved, not fresh);
  3. lanes seeing empty scatter-claim the tag and payload, then re-read;
     a lane that reads back its own tag AND payload won (resolved,
     fresh) — losers and tag-collision victims probe the next slot.

Batches must be intra-batch deduplicated first (two lanes carrying the
same fingerprint would both win), which `dedup_batch` does with a
lexicographic sort.  Like TLC's 64-bit fingerprinting, set membership is
probabilistic: a 128-bit collision (or a same-slot claim-tag collision
at ~2^-32 per probing pair, which can ghost one entry) silently merges
two states; both are vanishingly unlikely at reachable-set sizes.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

U32 = jnp.uint32
MAX_PROBES = 64


def empty_table(capacity: int):
    """capacity must be a power of two."""
    assert capacity & (capacity - 1) == 0
    return {"tags": jnp.zeros((capacity,), U32),
            "rows": jnp.zeros((capacity, 3), U32)}


def _slot_hash(fps):
    """[B, 4] -> [B] uint32 probe-start; decorrelated from the claim tag
    (word 0) so clustered tags don't cluster slots."""
    h = fps[:, 0] ^ (fps[:, 1] * jnp.uint32(0x9E3779B1))
    h = h ^ (fps[:, 2] * jnp.uint32(0x85EBCA6B)) ^ (fps[:, 3] >> 5)
    h = h ^ (h >> 15)
    return h * jnp.uint32(0x27D4EB2F)


def dedup_batch(fps, mask):
    """Keep the first occurrence of each distinct fingerprint.

    Returns (perm, keep): `perm` sorts the batch so equal fingerprints
    are adjacent (masked-out lanes sort to the end), `keep[i]` marks
    lanes of fps[perm] that are valid first occurrences.
    """
    key = [jnp.where(mask, fps[:, i], jnp.uint32(0xFFFFFFFF))
           for i in range(4)]
    perm = jnp.lexsort((key[3], key[2], key[1], key[0]))
    sfps = fps[perm]
    smask = mask[perm]
    neq = (sfps[1:] != sfps[:-1]).any(axis=1)
    first = jnp.concatenate([jnp.ones((1,), bool), neq])
    return perm, first & smask


def insert_core(table, fps, mask):
    """Insert fps[mask] into the table; fps must be intra-batch unique
    among masked lanes.  Returns (table, fresh, overflow) where fresh
    marks lanes whose fingerprint was not previously in the table.
    Plain traceable function — compose inside a jit (insert_batch is the
    standalone jitted form)."""
    cap = table["tags"].shape[0]
    capm = jnp.uint32(cap - 1)
    tag = jnp.where(fps[:, 0] == 0, jnp.uint32(1), fps[:, 0])
    row = fps[:, 1:]
    # probe chain is derived from the *canonical* key (word 0 after the
    # 0->1 claim remap) so a table rebuilt by grow() from stored
    # (tag, row) pairs probes identically to future lookups
    h0 = _slot_hash(jnp.concatenate([tag[:, None], row], axis=1))

    def body(t, carry):
        tags, rows, unresolved, fresh = carry
        idx = (h0 + jnp.uint32(t)) & capm
        cur_tag = tags[idx]
        cur_row = rows[idx]
        mine = (cur_tag == tag) & (cur_row == row).all(axis=1)
        dup = unresolved & mine
        empty = unresolved & (cur_tag == 0)
        # claim: only lanes seeing empty scatter; conflicting claims are
        # resolved by the read-back
        cidx = jnp.where(empty, idx, jnp.uint32(cap))  # OOB drops the write
        tags = tags.at[cidx].set(tag, mode="drop")
        rows = rows.at[cidx].set(row, mode="drop")
        won = empty & (tags[idx] == tag) & (rows[idx] == row).all(axis=1)
        fresh = fresh | won
        unresolved = unresolved & ~dup & ~won
        return tags, rows, unresolved, fresh

    tags, rows, unresolved, fresh = jax.lax.fori_loop(
        0, MAX_PROBES, body,
        (table["tags"], table["rows"], mask, jnp.zeros_like(mask)))
    return ({"tags": tags, "rows": rows}, fresh, unresolved.any())


insert_batch = partial(jax.jit, donate_argnums=(0,))(insert_core)


def grow(table, factor=4):
    """Host-side rebuild into a larger table (on probe overflow or high
    load).  Rare; chunked re-insertion of all occupied slots."""
    cap = int(table["tags"].shape[0])
    tags = np.asarray(table["tags"])
    rows = np.asarray(table["rows"])
    occ = tags != 0
    fps = np.concatenate([tags[occ, None], rows[occ]], axis=1)
    new = empty_table(cap * factor)
    chunk = 1 << 16
    for off in range(0, fps.shape[0], chunk):
        part = fps[off:off + chunk]
        pad = np.zeros((chunk - part.shape[0], 4), np.uint32)
        batch = jnp.asarray(np.concatenate([part, pad]))
        m = jnp.asarray(np.arange(chunk) < part.shape[0])
        new, _, ovf = insert_batch(new, batch, m)
        if bool(ovf):
            return grow(table, factor * 2)
    return new
