"""Device-resident fingerprint set (the TLC FPSet rebuilt for HBM).

The reference workload drove TLC's disk-spilling FPSet to 500 GB
(README:20); the TPU engine instead keeps 128-bit fingerprints in an
HBM-resident open-addressing hash table and batch-inserts an entire
frontier expansion per call (SURVEY.md §2.5).

Layout: one ``slots[CAP, 5]`` uint32 array per table; columns are
(tag, row0, row1, row2, claim) where tag is word 0 of the fingerprint
(0 = empty slot; fingerprints with word 0 == 0 are remapped to 1),
row0..2 are words 1..3, and claim transiently holds the batch lane id
that claimed the slot.  Insertion is claim-then-verify linear probing,
fully vectorized over the batch:

  1. gather each lane's probe slot;
  2. lanes seeing their own (tag, row) are duplicates (resolved);
  3. lanes seeing empty scatter their full (tag, row, lane-id) payload
     in ONE scatter, then re-read; the lane that reads back its own
     payload — including the lane id — won (resolved, fresh); losers
     probe on.

Because the claim column disambiguates same-fingerprint writers within
one scatter, batches may contain duplicate fingerprints: exactly one
lane per distinct new fingerprint resolves fresh, and its duplicates
resolve as duplicates on the next probe iteration.  (This is what lets
the BFS level kernel skip sort-based intra-batch dedup entirely.)

Like TLC's 64-bit fingerprinting, set membership is probabilistic: a
128-bit collision silently merges two states — vanishingly unlikely at
reachable-set sizes.
"""

from __future__ import annotations

import os
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

U32 = jnp.uint32
MAX_PROBES = 64

# Experimental hedge for the tile-1024 axon mis-exploration
# (scripts/tile_sweep.json): if the claim-then-verify scatter->gather
# pair is being fused/reordered by the TPU lowering, an optimization
# barrier between the claim write and the verify read forces the
# ordering.  Off by default; scripts/tpu_miscompile_repro.py flips it
# in a subprocess to test the hypothesis on hardware.
_CLAIM_BARRIER = os.environ.get("TPUVSR_FPSET_BARRIER", "0") == "1"


def empty_table(capacity: int):
    """capacity must be a power of two."""
    assert capacity & (capacity - 1) == 0
    return {"slots": jnp.zeros((capacity, 5), U32)}


def _slot_hash(fps):
    """[B, 4] -> [B] uint32 probe-start; decorrelated from the claim tag
    (word 0) so clustered tags don't cluster slots."""
    h = fps[:, 0] ^ (fps[:, 1] * jnp.uint32(0x9E3779B1))
    h = h ^ (fps[:, 2] * jnp.uint32(0x85EBCA6B)) ^ (fps[:, 3] >> 5)
    h = h ^ (h >> 15)
    return h * jnp.uint32(0x27D4EB2F)


def dedup_batch(fps, mask, tie=None):
    """Keep the first occurrence of each distinct fingerprint.

    Returns (perm, keep): `perm` sorts the batch so equal fingerprints
    are adjacent (masked-out lanes sort to the end), `keep[i]` marks
    lanes of fps[perm] that are valid first occurrences.  With `tie`
    (an int array, one priority per lane) the winner among equal
    fingerprints is the lane with the SMALLEST tie value instead of
    the smallest batch position — the sharded fused-commit step passes
    the canonical state-major flat index so a compacted (reordered)
    batch picks the same winner the dense batch would (ISSUE 10).
    (The single-device BFS engine's fused commit relies on the default
    batch-position tie; the sharded exchange uses both forms.)

    With symmetry canonicalization on (ISSUE 11, engine/canon.py) the
    fps in a batch are orbit-least images, so ORBIT-MATES carry equal
    keys here: the stable first-occurrence winner is what decides
    which generated representative a whole orbit commits to the
    frontier — the same earliest-queue-item rule, now doing the
    orbit-level dedup too.
    """
    key = [jnp.where(mask, fps[:, i], jnp.uint32(0xFFFFFFFF))
           for i in range(4)]
    minor = (key[3],) if tie is None else (tie, key[3])
    perm = jnp.lexsort(minor + (key[2], key[1], key[0]))
    sfps = fps[perm]
    smask = mask[perm]
    neq = (sfps[1:] != sfps[:-1]).any(axis=1)
    first = jnp.concatenate([jnp.ones((1,), bool), neq])
    return perm, first & smask


def _keyed(fps):
    """Canonical (tag, row) encoding: word 0 remapped 0 -> 1 so 0 can
    mark empty slots; the probe chain hashes the canonical key so a
    table rebuilt by grow() probes identically to future lookups."""
    tag = jnp.where(fps[:, 0] == 0, jnp.uint32(1), fps[:, 0])
    keyed = jnp.concatenate([tag[:, None], fps[:, 1:]], axis=1)
    return keyed, _slot_hash(keyed)


def insert_core(table, fps, mask):
    """Insert fps[mask] into the table.  Duplicate fingerprints within
    the batch are allowed: exactly one lane per distinct new fingerprint
    returns fresh.  Returns (table, fresh, overflow); overflow means
    some lanes were still unresolved after MAX_PROBES (their inserts
    did not happen — grow the table and retry).  Plain traceable
    function — compose inside a jit (insert_batch is the standalone
    jitted form)."""
    slots = table["slots"]
    cap = slots.shape[0]
    capm = jnp.uint32(cap - 1)
    keyed, h0 = _keyed(fps)
    n = fps.shape[0]
    lane_id = jnp.arange(n, dtype=U32)
    payload = jnp.concatenate([keyed, lane_id[:, None]], axis=1)  # [n, 5]

    def cond(carry):
        t, _slots, unresolved, _fresh = carry
        return (t < MAX_PROBES) & unresolved.any()

    def body(carry):
        t, slots, unresolved, fresh = carry
        idx = (h0 + jnp.uint32(t)) & capm
        cur = slots[idx]
        mine = (cur[:, :4] == keyed).all(axis=1)
        dup = unresolved & mine
        empty = unresolved & (cur[:, 0] == 0)
        # claim: one scatter writes tag+row+lane-id atomically, so the
        # read-back names a single winner even among equal fingerprints
        cidx = jnp.where(empty, idx, jnp.uint32(cap))  # OOB drops the write
        slots = slots.at[cidx].set(payload, mode="drop")
        if _CLAIM_BARRIER:
            slots = jax.lax.optimization_barrier(slots)
        post = slots[idx]
        won = empty & (post == payload).all(axis=1)
        # a lane that saw empty but reads back its own (tag, row) under
        # someone else's claim lost the race to an EQUAL fingerprint —
        # resolve it as a duplicate now; advancing the probe would
        # wrongly insert the fingerprint a second time at the next slot
        lost_dup = empty & ~won & (post[:, :4] == keyed).all(axis=1)
        fresh = fresh | won
        unresolved = unresolved & ~dup & ~won & ~lost_dup
        return t + 1, slots, unresolved, fresh

    _, slots, unresolved, fresh = jax.lax.while_loop(
        cond, body,
        (jnp.int32(0), slots, mask, jnp.zeros_like(mask)))
    return {**table, "slots": slots}, fresh, unresolved.any()


insert_batch = partial(jax.jit, donate_argnums=(0,))(insert_core)


def table_stats(slots):
    """Host-side occupancy/collision stats of a table's ``slots``
    array (device or numpy).  "Displaced" slots are occupied slots not
    sitting at their probe-chain start — the linear-probing collision
    measure the obs layer reports as ``fpset_collision_rate``.  Costs
    one table pull; callers gate it on metrics being requested."""
    s = np.asarray(slots)
    cap = int(s.shape[0])
    occ = s[:, 0] != 0
    n = int(occ.sum())
    out = {"capacity": cap, "occupied": n,
           "occupancy": n / cap if cap else 0.0,
           "displaced": 0, "collision_rate": 0.0}
    if n == 0:
        return out
    keyed = s[occ, :4].astype(np.uint32)
    with np.errstate(over="ignore"):
        # numpy replica of _slot_hash (stored words are already keyed)
        h = keyed[:, 0] ^ (keyed[:, 1] * np.uint32(0x9E3779B1))
        h = h ^ (keyed[:, 2] * np.uint32(0x85EBCA6B)) ^ (keyed[:, 3] >> 5)
        h = h ^ (h >> 15)
        home = (h * np.uint32(0x27D4EB2F)) & np.uint32(cap - 1)
    idx = np.nonzero(occ)[0].astype(np.uint32)
    displaced = int((home != idx).sum())
    out["displaced"] = displaced
    out["collision_rate"] = displaced / n
    return out


def query_core(table, fps, mask):
    """Read-only membership probe: returns (fresh, overflow).  `fresh`
    marks masked lanes whose fingerprint is NOT in the table (duplicate
    lanes within the batch all read fresh — callers using the count for
    capacity checks get a conservative overcount); lanes unresolved
    after MAX_PROBES raise `overflow` and are not fresh."""
    slots = table["slots"]
    cap = slots.shape[0]
    capm = jnp.uint32(cap - 1)
    keyed, h0 = _keyed(fps)

    def cond(carry):
        t, unresolved, _fresh = carry
        return (t < MAX_PROBES) & unresolved.any()

    def body(carry):
        t, unresolved, fresh = carry
        idx = (h0 + jnp.uint32(t)) & capm
        cur = slots[idx]
        mine = (cur[:, :4] == keyed).all(axis=1)
        empty = unresolved & (cur[:, 0] == 0)
        fresh = fresh | empty
        unresolved = unresolved & ~mine & ~empty
        return t + 1, unresolved, fresh

    _, unresolved, fresh = jax.lax.while_loop(
        cond, body, (jnp.int32(0), mask, jnp.zeros_like(mask)))
    return fresh, unresolved.any()


def store_gids(slots, vals, fps, gids, mask):
    """Write ``gids[mask]`` into the parallel ``vals[CAP]`` array at
    each masked lane's resolved probe slot.  Every masked fingerprint
    must already be PRESENT in ``slots`` (insert first, then store) —
    the lane re-probes its chain to find the slot it resolved to.
    Plain traceable function; the streamed edge-emission commit
    (ISSUE 15) composes it with ``insert_core`` inside the level
    kernel so every fresh state's graph node id lands next to its
    fingerprint, and ``lookup_gids`` then resolves successor
    fingerprints — fresh AND duplicate — to gids on device."""
    cap = slots.shape[0]
    capm = jnp.uint32(cap - 1)
    keyed, h0 = _keyed(fps)

    def cond(carry):
        t, unresolved, _v = carry
        return (t < MAX_PROBES) & unresolved.any()

    def body(carry):
        t, unresolved, vals = carry
        idx = (h0 + jnp.uint32(t)) & capm
        cur = slots[idx]
        mine = unresolved & (cur[:, :4] == keyed).all(axis=1)
        vidx = jnp.where(mine, idx, jnp.uint32(cap))
        vals = vals.at[vidx].set(gids, mode="drop")
        unresolved = unresolved & ~mine
        return t + 1, unresolved, vals

    _, _, vals = jax.lax.while_loop(
        cond, body, (jnp.int32(0), mask, vals))
    return vals


def insert_gids(table, vals, fps, gids, mask):
    """insert_core that also records a 32-bit value (a graph node id)
    per fingerprint in the parallel ``vals[CAP]`` array — the device
    side of the liveness graph's fingerprint->gid index
    (engine/device_liveness.py).  Batches must not contain duplicate
    fingerprints (graph nodes are distinct by construction).  Returns
    (table, vals, overflow, fresh_count)."""
    table, fresh, ovf = insert_core(table, fps, mask)
    # each fresh lane re-probes its own chain to find the slot it won
    # and writes its gid there
    vals = store_gids(table["slots"], vals, fps, gids, mask & fresh)
    return table, vals, ovf, fresh.sum(dtype=jnp.int32)


def lookup_gids(table, vals, fps, mask):
    """fps -> stored gid (or -1 when absent/unresolved).  Read-only."""
    slots = table["slots"]
    cap = slots.shape[0]
    capm = jnp.uint32(cap - 1)
    keyed, h0 = _keyed(fps)
    n = fps.shape[0]

    def cond(carry):
        t, unresolved, _o = carry
        return (t < MAX_PROBES) & unresolved.any()

    def body(carry):
        t, unresolved, out = carry
        idx = (h0 + jnp.uint32(t)) & capm
        cur = slots[idx]
        mine = unresolved & (cur[:, :4] == keyed).all(axis=1)
        out = jnp.where(mine, vals[idx].astype(jnp.int32), out)
        empty = cur[:, 0] == 0
        unresolved = unresolved & ~mine & ~empty
        return t + 1, unresolved, out

    _, _, out = jax.lax.while_loop(
        cond, body, (jnp.int32(0), mask,
                     jnp.full((n,), -1, jnp.int32)))
    return out


def grow(table, factor=4):
    """Host-side rebuild into a larger table (on probe overflow or high
    load).  Rare; chunked re-insertion of all occupied slots.  A table
    carrying a ``gids`` value column (the streamed edge-emission mode,
    ISSUE 15) is rebuilt WITH it: each occupied slot's stored gid
    follows its fingerprint to the new probe chain."""
    slots = np.asarray(table["slots"])
    occ = slots[:, 0] != 0
    fps = slots[occ, :4]
    cap = int(slots.shape[0])
    old_gids = (np.asarray(table["gids"])[occ]
                if "gids" in table else None)
    new = empty_table(cap * factor)
    new_gids = (jnp.full((cap * factor,), -1, jnp.int32)
                if old_gids is not None else None)
    chunk = 1 << 16
    ins_g = jax.jit(insert_gids, donate_argnums=(0, 1)) \
        if old_gids is not None else None
    for off in range(0, fps.shape[0], chunk):
        part = fps[off:off + chunk]
        pad = np.zeros((chunk - part.shape[0], 4), np.uint32)
        batch = jnp.asarray(np.concatenate([part, pad]))
        m = jnp.asarray(np.arange(chunk) < part.shape[0])
        if old_gids is not None:
            gpart = old_gids[off:off + chunk].astype(np.int32)
            gpad = np.zeros((chunk - gpart.shape[0],), np.int32)
            new, new_gids, ovf, _ = ins_g(
                new, new_gids, batch,
                jnp.asarray(np.concatenate([gpart, gpad])), m)
        else:
            new, _, ovf = insert_batch(new, batch, m)
        if bool(ovf):
            return grow(table, factor * 2)
    if new_gids is not None:
        new["gids"] = new_gids
    return new
