"""Host-paged BFS engine: the frontier spill tier for defect-scale runs.

The reference's flagship run — exhaustive BFS of VSR.tla at the
defect-repro constants — drove TLC to >=500 GB of disk, nearly all of
it queue/state storage, not fingerprints
(/root/reference/README.md:20; CAPACITY.md).  On a TPU the same wall
hits sooner: at ~7 KiB per dense state one chip's spare HBM holds
under a million frontier states, while a defect-scale BFS level can
exceed that by orders of magnitude.  This engine keeps ONLY the
fingerprint set resident in device memory (not the binding constraint:
16 GB of HBM holds ~800 M fingerprint slots) and pages the frontier
through the device in fixed-size chunks:

  host frontier (numpy; the 125 GB host holds ~17 M dense states)
      --chunk in-->  device chunk buffer [chunk_tiles x tile states]
      --level kernel (DeviceBFS._make_level, unchanged)-->
      next-frontier buffer fills --> DRAIN to host, reset, continue

The drain reuses the level kernel's existing pause protocol: the
headroom check that raised R_NEXT_GROW in the resident engine (grow
the buffer in HBM) here means "spill what you have" — the paused tile
has committed nothing, so the host copies the nn valid rows out,
zeroes the counter, and re-enters at the same tile.  Transfers are
sequential block copies proportional to bytes/state x generated/s
(CAPACITY.md mitigation 1).

Everything else — fingerprinting, invariant evaluation, growth of the
message table / FPSet / per-action expand buffers, violation handling,
deadlock detection, trace replay — is inherited from DeviceBFS; the
two engines run the SAME jitted level pass, so paged results match
resident results exactly (asserted in tests/test_paged.py).

Checkpoint/resume reuses the level-boundary snapshot format of
engine/checkpoint.py (the frontier is already host-side here, making
snapshots cheap).
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from ..core.values import TLAError
from ..obs import RunObserver, closes_observer
from ..resilience.faults import fault_point
from ..resilience.supervisor import Preempted, preempt_signal
from .bfs import CheckResult
from .device_bfs import (DeviceBFS, I32, R_BAG_GROW, R_DEADLOCK,
                         R_EDGE_FLUSH, R_EXPAND_GROW, R_FPSET_GROW,
                         R_NEXT_GROW, R_SLOT_ERR, R_VIOLATION, RUNNING)
from .fpset import grow
from .spill import EdgeCSR


class PagedBFS(DeviceBFS):
    """DeviceBFS with a host-RAM frontier paged through the device.

    With ``retain_levels=True`` every expanded frontier level's host
    block is kept on ``self.level_blocks`` (gid order) — the state
    enumeration pass the device liveness graph builder reuses
    (engine/device_liveness.py)."""

    def __init__(self, *args, retain_levels=False, spill_dir=None,
                 spill_ram_rows=None, edges=False, edge_capacity=None,
                 edge_spill_dir=None, edge_ram_rows=None, **kwargs):
        self.retain_levels = retain_levels
        self.level_blocks = []
        # streamed edge emission (ISSUE 15): the fused commit's stage 3
        # resolves every enabled lane's successor fingerprint to a gid
        # on device (gid-valued FPSet) and appends (src gid, action,
        # dst gid) triples to a device append buffer, drained into the
        # incremental host CSR builder (engine/spill.EdgeCSR) at chunk
        # boundaries — the behavior graph streams OUT of the safety
        # BFS instead of being re-derived by a second expansion pass.
        # `edge_spill_dir` tiers the drained triples to disk for
        # graphs past the RAM budget.  Must be set BEFORE the parent
        # constructor runs (the tile bodies close over it)
        self._edges_on = bool(edges)
        self._edge_capacity = edge_capacity
        self._edge_spill_dir = edge_spill_dir
        self._edge_ram_rows = edge_ram_rows
        self.edge_sink = None
        self._edge_rows_total = 0
        self._edge_hw = 0
        self._run_t0 = None
        # disk spill tier (ISSUE 11, CAPACITY.md mitigation 2): with a
        # spill directory, each level's host pages live in a SpillTier
        # — at most `spill_ram_rows` rows resident, the rest in
        # append-only level files re-read sequentially when the level
        # pages through the device.  The host-RAM frontier ceiling
        # becomes a disk-priced one; results are bit-identical (the
        # tier only changes WHERE at-rest rows live)
        self._spill_dir = spill_dir
        self._spill_ram_rows = int(spill_ram_rows or (1 << 20))
        self._tiers = []
        if spill_dir and retain_levels:
            raise TLAError(
                "retain_levels (the liveness graph enumeration) needs "
                "the whole level resident; it cannot be combined with "
                "the disk spill tier")
        super().__init__(*args, **kwargs)

    # -- disk-tier helpers (no-ops when spill_dir is None) -------------
    def _tier(self, level, block, obs):
        from .spill import SpillTier
        t = SpillTier(self._spill_dir, level, self._spill_ram_rows,
                      obs=obs, depth=level)
        self._tiers.append(t)
        if block is not None:
            t.append(block)
        return t

    def _front_block(self, host_front, start, n):
        """Rows [start, start+n) of the (possibly disk-tiered) host
        frontier, in the at-rest row format."""
        from .spill import SpillTier
        if isinstance(host_front, SpillTier):
            return host_front.block(start, n)
        if self._pk is not None:
            return host_front[start:start + n]
        return {k: v[start:start + n] for k, v in host_front.items()}

    def _front_dense(self, host_front, n):
        """First `n` rows as dense planes (the checkpoint interchange
        format)."""
        from .spill import SpillTier
        if isinstance(host_front, SpillTier):
            host_front = host_front.all_rows()
        if self._pk is not None:
            return self._pk.unpack_np(np.asarray(host_front)[:n])
        return {k: np.asarray(v)[:n] for k, v in host_front.items()}

    def _front_dense_blocks(self, tier, n):
        """Generator of dense plane-dict blocks over a disk-tiered
        frontier, page by page — the streaming checkpoint writer's
        input (ISSUE 13 satellite: the PR 11 save_checkpoint residual).
        Peak residency is ONE page, tracked run-wide on
        ``_ckpt_peak_rows`` / ``_ckpt_blocks`` (the test assertion
        hooks)."""
        self._ckpt_peak_rows = getattr(self, "_ckpt_peak_rows", 0)
        self._ckpt_blocks = max(getattr(self, "_ckpt_blocks", 0), 0)
        done = 0
        for _pos, rows, load in tier._iter_pages():
            if done >= n:
                break
            take = min(rows, n - done)
            block = load()
            if take < rows:
                from .spill import _slice
                block = _slice(block, 0, take)
            dense = (self._pk.unpack_np(np.asarray(block))
                     if self._pk is not None else
                     {k: np.asarray(v) for k, v in block.items()})
            done += take
            self._ckpt_peak_rows = max(self._ckpt_peak_rows, take)
            self._ckpt_blocks += 1
            yield dense

    # -- host-side helpers ---------------------------------------------
    def _host_zero(self, n):
        if self._pk is not None:
            # packed host frontier: spill pages and the at-rest host
            # store move [words] uint32 rows, not dense planes
            # (ISSUE 9 — 4-8x fewer bytes over the chunk-in/drain-out
            # transfers that bound this engine)
            return np.zeros((n, self._pk.words), np.uint32)
        zero = self.codec.zero_state()
        return {k: np.zeros((n,) + np.shape(v), np.int32)
                for k, v in zero.items()}

    def _host_row(self, host_front, i):
        """One dense state row of the (possibly packed, possibly
        disk-tiered) host frontier."""
        from .spill import SpillTier
        if isinstance(host_front, SpillTier):
            block = host_front.row(i)
            if self._pk is not None:
                return self._pk.unpack_row_np(np.asarray(block)[0])
            return {k: v[0] for k, v in block.items()}
        if self._pk is not None:
            return self._pk.unpack_row_np(host_front[i])
        return {k: host_front[k][i] for k in host_front}

    def _chunk_cap(self):
        return self.chunk_tiles * self.tile

    def _total_E(self):
        # same caps the level kernel compacts with (fused commit: the
        # exact-count caps; per-action: the tile-multiple formula) —
        # the next-buffer headroom floor must track whichever is live
        return sum(self._expand_caps())

    def _pad_init_dense(self, old):
        for i, d in enumerate(self._init_dense):
            padded = self.codec.pad_msgs(
                {k: np.asarray(v)[None] for k, v in d.items()}, old)
            self._init_dense[i] = {k: v[0] for k, v in padded.items()}

    def _state_row_bytes(self):
        """Bytes of one frontier row as the paged tier actually moves
        it: packed words when the pack spec is bound, dense otherwise
        (the spill `bytes` journal field and gauges report REAL
        transfer volume)."""
        if self._pk is not None:
            return self._pk.packed_bytes
        zero = self.codec.zero_state()
        return sum(int(np.prod(np.shape(v)) or 1) * 4
                   for v in zero.values())

    @closes_observer
    def run(self, max_states=None, max_depth=None, max_seconds=None,
            check_deadlock=False, log=None, progress_every=10.0,
            checkpoint_path=None, checkpoint_every=None,
            resume_from=None, obs=None) -> CheckResult:
        from ..analysis import preflight
        preflight(self.spec, log=log)   # fail fast, before any dispatch
        obs = RunObserver.ensure(obs, "paged", self.spec, log=log,
                                 progress_every=progress_every)
        obs.pipeline = self.pipe_window
        obs.pack = self._pk is not None
        obs.commit = self.commit
        obs.symmetry = self._symmetry_on()
        obs.bounds = self._bounds_doc()
        obs.edges = self._edges_on
        obs.por = self._por_doc()
        self._obs_active = obs          # closes_observer finalizes it
        spec = self.spec
        self._act_counts = np.zeros(len(self.kern.action_names),
                                    np.int64)
        self._tiles_done = 0
        self._lanes_disp = 0
        self._por_kept = self._por_full = self._por_amp = 0
        res = CheckResult()
        t0 = time.time()
        self._run_t0 = t0
        obs.start(t0, backend=jax.default_backend(),
                  resumed=resume_from is not None)
        emit = obs.log

        self.spill_count = 0     # drains triggered by a full buffer
        self.spill_rows = 0      # total rows paged out to host
        self.level_blocks = []   # fresh per run (retain_levels)
        if self._edges_on:
            # incremental host CSR builder the edge drains feed
            # (ISSUE 15); fresh per run like the level blocks
            self.edge_sink = EdgeCSR(spill_dir=self._edge_spill_dir,
                                     ram_rows=self._edge_ram_rows,
                                     obs=obs)
            self._edge_rows_total = 0
            self._edge_hw = 0

        if resume_from is not None:
            from .checkpoint import load_checkpoint, spec_digest
            ck = load_checkpoint(resume_from,
                                 expect_digest=spec_digest(spec),
                                 log=emit)
            if (ck.get("extra") or {}).get("sharded"):
                raise TLAError("checkpoint was written by the sharded "
                               "engine; resume it there")
            # empty expand_mults (a converted sharded snapshot, see
            # parallel.sharded_bfs.convert_sharded_snapshot): keep
            # this engine's own per-action defaults
            if ck["max_msgs"] != self.codec.shape.MAX_MSGS or \
                    (ck["expand_mults"] and list(ck["expand_mults"])
                     != list(self.expand_mults)):
                if ck["expand_mults"]:
                    self.expand_mults = list(ck["expand_mults"])
                self._build(ck["max_msgs"])
            self._check_bounds_manifest(ck, resume_from)
            self._check_pack_manifest(ck, resume_from)
            self._check_canon_manifest(ck, resume_from)
            table = {"slots": jnp.asarray(ck["slots"])}
            fp_cap = int(ck["slots"].shape[0])
            # POR manifest policy (ISSUE 16): resuming under a flipped
            # -por or changed independence facts is a loud error; on a
            # matching resume the C3 level markers are rebuilt as
            # zeros — at a level boundary every stored fingerprint is
            # old, which reproduces the writer's decisions exactly
            if self._por_active:
                self._check_por_manifest(ck, resume_from)
                table["gids"] = jnp.zeros((fp_cap,), jnp.int32)
            elif ck.get("por"):
                self._check_por_manifest(ck, resume_from)
            if self._edges_on:
                # edge-stream resume seam (ISSUE 15): the snapshot
                # must carry the gid column and the drained edge rows
                # up to its committed level — resuming a plain-BFS
                # snapshot with edges on would leave every pre-resume
                # state gid-less, so it is a policy error
                if ck.get("gids") is None:
                    raise TLAError(
                        f"checkpoint {resume_from} was written "
                        f"without the edge stream (no gid column); "
                        f"resume with edges off, or restart the "
                        f"temporal run from scratch")
                table["gids"] = jnp.asarray(ck["gids"])
                if ck.get("edges") is not None:
                    self.edge_sink.seed(ck["edges"])
                    self._edge_rows_total = self.edge_sink.rows
                if self.retain_levels:
                    g = ck.get("graph")
                    sizes = [int(x) for x in ck["level_sizes"][:-1]]
                    have = (0 if g is None
                            else int(next(iter(g.values())).shape[0]))
                    if have != sum(sizes):
                        raise TLAError(
                            f"checkpoint {resume_from} retains "
                            f"{have} graph rows, the committed "
                            f"levels hold {sum(sizes)} — snapshot "
                            f"not written by a retain_levels run")
                    off = 0
                    for s in sizes:
                        self.level_blocks.append(
                            {k: v[off:off + s] for k, v in g.items()})
                        off += s
            self._init_dense = ck["init_dense"]
            self._init_states = [self.codec.decode(d)
                                 for d in ck["init_dense"]]
            self._h_parent = [ck["h_parent"]]
            self._h_action = [ck["h_action"]]
            self._h_param = [ck["h_param"]]
            self.level_sizes = list(ck["level_sizes"])
            depth = ck["depth"]
            fp_count = ck["fp_count"]
            res.states_generated = ck["states_generated"]
            t0 -= ck["elapsed"]
            obs.set_epoch(t0)
            n_front = ck["n_front"]
            # snapshots store dense planes (the engine-agnostic
            # interchange format); pack them on load when packing is on
            host_front = (self._pk.pack_np(
                {k: np.asarray(v) for k, v in ck["frontier"].items()})
                if self._pk is not None else
                {k: np.asarray(v) for k, v in ck["frontier"].items()})
            if self._spill_dir is not None:
                # reload through the tier: a resumed frontier larger
                # than the RAM budget spills right back to disk
                host_front = self._tier(depth, host_front, obs)
            level_base = sum(self.level_sizes[:-1])
            emit(f"resumed from {resume_from}: depth {depth}, "
                 f"{fp_count} distinct, frontier {n_front}")
        else:
            fp_cap = self.fpset_capacity
            self.level_sizes = []  # no stale trajectory on init-viol
            table, init_batch, n0, viol = self._register_init(res)
            fp_count = n0
            if viol is not None:
                return self._finish(res, obs, fp_count,
                                    table=table, fp_cap=fp_cap)
            init_rows = {k: init_batch[k][:n0].astype(np.int32)
                         for k in init_batch}
            host_front = (self._pk.pack_np(init_rows)
                          if self._pk is not None else init_rows)
            if self._spill_dir is not None:
                host_front = self._tier(0, host_front, obs)
            n_front = n0
            level_base = 0
            depth = 0
            self.level_sizes = [n0]

        last_checkpoint = time.time()
        dev_chunk = None        # allocated lazily; realloc on bag growth
        # the level kernel refuses to commit a tile unless the next
        # buffer has total_E rows of headroom, so total_E + one tile's
        # worth is the functional floor; size it larger (the default
        # next_capacity) to keep drains block-sized rather than
        # per-tile.  Floored AFTER any resume rebuild (expand_mults /
        # max_msgs from the checkpoint can enlarge total_E) and
        # re-floored on every in-run rebuild — a stale floor live-locks
        # the drain loop (commit never true with an empty buffer).
        self.next_cap = max(self.next_cap, self._total_E() + self.tile)
        bufs = self._alloc_bufs(self.next_cap)
        # edge append buffer (ISSUE 15): same total_E + one-tile floor
        # as the next buffer (the kernel refuses to commit a tile
        # without total_E triples of headroom); default sized 4x the
        # next buffer so R_EDGE_FLUSH drains stay block-sized
        ebufs = None
        n_edge = 0
        if self._edges_on:
            self.edge_cap = max(int(self._edge_capacity
                                    or 4 * self.next_cap),
                                self._total_E() + self.tile)
            ebufs = tuple(jnp.zeros((self.edge_cap,), I32)
                          for _ in range(3))
        stop = None

        # pipelined dispatch window (ISSUE 4): chained on device-side
        # (start_t, nn) scalars; host-side spill compaction and
        # journal/metrics work overlap the in-flight dispatches.  The
        # window drains at every pause/spill/chunk boundary — dropped
        # tickets are replays that committed nothing (engine/pipeline.py)
        from .pipeline import DispatchPipeline
        pipe = DispatchPipeline(self.pipe_window, obs,
                                ready=lambda o: o["reason"])

        def pull(o):
            keys = [o["reason"], o["t"], o["nn"], o["gen"],
                    o["dist"], o["act"], o["need"]]
            if self._edges_on:
                keys.append(o["edge_n"])
            if self._por_active:
                keys += [o["gfull"], o["amp"]]
            return jax.device_get(keys)

        while n_front > 0 and stop is None:
            if max_depth is not None and depth >= max_depth:
                res.error = f"depth limit {max_depth} reached"
                break
            if self.retain_levels:
                # level blocks stay DENSE: the device liveness graph
                # builder enumerates them as plane dicts
                self.level_blocks.append(
                    self._pk.unpack_np(host_front)
                    if self._pk is not None else host_front)
            depth += 1
            fault_point("level", depth=depth, obs=obs)
            # per-level host accumulators for drained next states and
            # their (level-relative) trace pointers.  Disk tier:
            # `drained` is a SpillTier — same .append seam, but pages
            # beyond the RAM budget flush to level files
            drained = (self._tier(depth, None, obs)
                       if self._spill_dir is not None else [])
            d_par, d_act, d_prm = [], [], []
            n_next_total = 0
            chunk_start = 0
            n_c = 0
            n_next = 0

            def spill():
                """Page the first n_next rows of the next buffers out to
                host RAM and reset the counter.  Reads the chain-tip
                buffers: identical to the paused dispatch's (replays
                commit nothing)."""
                nonlocal n_next_total, n_next
                if n_next == 0:
                    return
                nb, nbp, nba, nbprm = bufs
                with obs.timer("host_sync"):
                    rows, par, act, prm = jax.device_get(
                        (nb[:n_next] if self._pk is not None
                         else {k: v[:n_next] for k, v in nb.items()},
                         nbp[:n_next], nba[:n_next], nbprm[:n_next]))
                drained.append(np.asarray(rows)
                               if self._pk is not None else
                               {k: np.asarray(v) for k, v in rows.items()})
                # par is chunk-relative; lift to level-relative now
                d_par.append(np.asarray(par, np.int64) + chunk_start)
                d_act.append(np.asarray(act))
                d_prm.append(np.asarray(prm))
                n_next_total += n_next
                self.spill_rows += n_next
                obs.spill(depth, n_next,
                          n_next * self._state_row_bytes())
                n_next = 0

            def refloor_edges():
                """Kernel rebuilt with (possibly) wider caps: drain
                the plain-int triples, re-floor the append buffer
                against the new total_E headroom requirement, and
                re-zero it (a stale floor live-locks the commit gate,
                exactly like the next_cap floor above)."""
                nonlocal ebufs, pend_en
                drain_edges()
                self.edge_cap = max(self.edge_cap,
                                    self._total_E() + self.tile)
                ebufs = tuple(jnp.zeros((self.edge_cap,), I32)
                              for _ in range(3))
                pend_en = jnp.asarray(0, I32)

            def drain_edges():
                """Drain the committed edge triples off the device
                append buffer into the CSR builder (ISSUE 15).  Reads
                the chain-tip edge buffers — identical to the
                collected ticket's, since replays commit nothing."""
                nonlocal n_edge
                if not self._edges_on or n_edge == 0:
                    return
                es, ea, ed = ebufs
                with obs.timer("host_sync"):
                    s, a, d = jax.device_get(
                        (es[:n_edge], ea[:n_edge], ed[:n_edge]))
                self.edge_sink.append(np.asarray(s), np.asarray(a),
                                      np.asarray(d))
                self._edge_rows_total += n_edge
                self._edge_hw = max(self._edge_hw, n_edge)
                obs.edge_flush(depth, n_edge,
                               n_edge * EdgeCSR.ROW_BYTES)
                n_edge = 0

            def put_chunk():
                nonlocal dev_chunk
                cc = self._chunk_cap()
                block = self._front_block(host_front, chunk_start,
                                          n_c)
                if self._pk is not None:
                    if dev_chunk is None:
                        dev_chunk = jnp.zeros((cc, self._pk.words),
                                              jnp.uint32)
                    dev_chunk = dev_chunk.at[:n_c].set(block)
                    return
                if dev_chunk is None:
                    dev_chunk = {
                        k: jnp.zeros((cc,) + np.shape(v), np.int32)
                        for k, v in self.codec.zero_state().items()}
                dev_chunk = {
                    k: dev_chunk[k].at[:n_c].set(block[k])
                    for k in dev_chunk}

            while chunk_start < n_front and stop is None:
                n_c = min(self._chunk_cap(), n_front - chunk_start)
                put_chunk()
                n_tiles_c = (n_c + self.tile - 1) // self.tile
                start_t = 0
                pend_t = jnp.asarray(0, I32)
                pend_nn = jnp.asarray(n_next, I32)
                pend_en = jnp.asarray(n_edge, I32)
                while True:
                    while pipe.has_room():
                        nb, nbp, nba, nbprm = bufs
                        eb_arg, emeta_arg = None, None
                        if self._edges_on:
                            # gid_base maps a next-buffer row to its
                            # global gid (spilled rows precede the
                            # buffer); src_base lifts a chunk row to
                            # its frontier gid.  Both are constant
                            # within a pipelined burst: spills only
                            # happen behind a drained pause
                            eb_arg = ebufs
                            emeta_arg = {
                                "n": pend_en,
                                "src_base": jnp.asarray(
                                    level_base + chunk_start, I32),
                                "gid_base": jnp.asarray(
                                    level_base + n_front
                                    + n_next_total, I32)}
                        out = pipe.launch(
                            self._level, table, dev_chunk,
                            jnp.asarray(n_c, I32), pend_t,
                            nb, nbp, nba, nbprm, pend_nn,
                            jnp.asarray(bool(check_deadlock)),
                            eb_arg, emeta_arg,
                            jnp.asarray(depth - 1, I32),
                            fresh=self._fresh_jit,
                            label=f"level {depth} dispatch")
                        self._fresh_jit = False
                        table = {"slots": out["slots"]}
                        if self._por_active:
                            table["gids"] = out["gids"]
                        bufs = (out["nb"], out["nbp"], out["nba"],
                                out["nbprm"])
                        pend_t, pend_nn = out["t"], out["nn"]
                        if self._edges_on:
                            table["gids"] = out["gids"]
                            ebufs = (out["eb_src"], out["eb_aid"],
                                     out["eb_dst"])
                            pend_en = out["edge_n"]
                    out, sc = pipe.collect(pull)
                    reason, start_t, n_next, gen_add, dist_add = (
                        int(x) for x in sc[:5])
                    res.states_generated += gen_add
                    fp_count += dist_add
                    self._act_counts += np.asarray(sc[5], np.int64)
                    self._fold_need(sc[6])
                    if self._edges_on:
                        n_edge = int(sc[7])
                    if self._por_active:
                        self._por_kept += gen_add
                        self._por_full += int(sc[7])
                        self._por_amp += int(sc[8])

                    if reason == RUNNING:
                        obs.progress(depth=depth, distinct=fp_count,
                                     generated=res.states_generated,
                                     frontier=n_front,
                                     extra="host-paged")
                        if max_seconds and time.time() - t0 > max_seconds:
                            stop = f"time budget {max_seconds}s reached"
                            pipe.drain()
                            break
                        if start_t >= n_tiles_c:
                            pipe.drain()     # no-op tickets past the end
                            break            # chunk complete
                        continue
                    # pause/terminal: in-flight tickets are replays of
                    # the same paused tile — drop, then handle on the
                    # chain-tip table/buffers
                    pipe.drain()
                    if reason == R_VIOLATION:
                        vp, va, vprm = (int(v)
                                        for v in np.asarray(out["viol"]))
                        gid = level_base + chunk_start + vp
                        parent_dense = self._host_row(
                            host_front, chunk_start + vp)
                        vstate = self._materialize_one(
                            parent_dense, va, vprm)
                        bad = spec.check_invariants(
                            self.codec.decode(vstate))
                        if bad is None:
                            raise TLAError(
                                "device/interpreter divergence: device "
                                "invariant kernel reported a violation "
                                "the interpreter accepts (parent gid "
                                f"{gid}, action "
                                f"{self.kern.action_names[va]})")
                        res.ok = False
                        res.violated_invariant = bad
                        res.trace = self._trace(gid, extra=(va, vprm))
                        res.diameter = depth
                        return self._finish(res, obs, fp_count,
                                            table=table, fp_cap=fp_cap)
                    elif reason == R_NEXT_GROW:
                        # the spill tier: page the filled buffer out to
                        # host RAM instead of growing it in HBM; the
                        # refilled window then overlaps the host-side
                        # compaction below with device compute
                        self.spill_count += 1
                        spill()
                        pend_nn = jnp.asarray(0, I32)
                    elif reason == R_EDGE_FLUSH:
                        # edge append buffer full (ISSUE 15): drain the
                        # committed triples into the CSR builder and
                        # re-enter — the edge analog of the spill above
                        drain_edges()
                        pend_en = jnp.asarray(0, I32)
                    elif reason == R_BAG_GROW:
                        old = self.codec.shape.MAX_MSGS
                        spill()
                        old_pk = self._pk
                        self._build(old * 2)
                        obs.grow("message_table",
                                 self.codec.shape.MAX_MSGS)
                        if old_pk is not None:
                            # packed pages: round-trip through the OLD
                            # spec to dense, pad, re-pack under the
                            # rebuilt one (see DeviceBFS._grow_msgs)
                            def regrow(rows):
                                d = self.codec.pad_msgs(
                                    old_pk.unpack_np(rows), old)
                                return self._pk.pack_np(d)
                        else:
                            def regrow(rows):
                                return self.codec.pad_msgs(rows, old)
                        if self._spill_dir is not None:
                            host_front.map_pages(regrow)
                            drained.map_pages(regrow)
                        else:
                            host_front = regrow(host_front)
                            drained = [regrow(d) for d in drained]
                        self.level_blocks = [
                            self.codec.pad_msgs(b, old)
                            for b in self.level_blocks]
                        self._pad_init_dense(old)
                        dev_chunk = None
                        self.next_cap = max(
                            self.next_cap, self._total_E() + self.tile)
                        bufs = self._alloc_bufs(self.next_cap)
                        if self._edges_on:
                            refloor_edges()
                        put_chunk()     # same chunk, re-enter at start_t
                        pend_t = jnp.asarray(start_t, I32)
                        pend_nn = jnp.asarray(0, I32)
                        emit(f"message table grown to "
                             f"{self.codec.shape.MAX_MSGS} slots "
                             f"(recompiling)")
                    elif reason == R_FPSET_GROW:
                        table = grow(table)
                        fp_cap *= 4
                        self._fresh_jit = True   # shape change
                        obs.grow("fpset", fp_cap)
                        emit(f"FPSet grown to {fp_cap} slots")
                    elif reason == R_EXPAND_GROW:
                        self._grow_expand(int(out["grow_aid"]), obs,
                                          emit)
                        if self.next_cap < self._total_E() + self.tile:
                            spill()
                            self.next_cap = self._total_E() + self.tile
                            bufs = self._alloc_bufs(self.next_cap)
                            pend_nn = jnp.asarray(0, I32)
                        if self._edges_on and self.edge_cap < \
                                self._total_E() + self.tile:
                            refloor_edges()
                    elif reason == R_SLOT_ERR:
                        raise TLAError(
                            "dense-layout slot collision (a second DVC "
                            "or recovery response from one source in "
                            "one view): this restart-era interleaving "
                            "needs the multi-slot layout (vsr.py "
                            "docstring)")
                    elif reason == R_DEADLOCK:
                        di = int(out["dead"])
                        gid = level_base + chunk_start + di
                        res.ok = False
                        res.error = "deadlock"
                        res.deadlock_state = self.codec.decode(
                            self._host_row(host_front, chunk_start + di))
                        res.trace = self._trace(gid)
                        res.diameter = depth
                        return self._finish(res, obs, fp_count,
                                            table=table, fp_cap=fp_cap)
                    # growth pauses fall through here; terminal reasons
                    # returned above
                    obs.progress(depth=depth, distinct=fp_count,
                                 generated=res.states_generated,
                                 frontier=n_front, extra="host-paged")
                    if max_seconds and time.time() - t0 > max_seconds:
                        stop = f"time budget {max_seconds}s reached"
                        break
                # chunk done (or stopped): spill whatever accumulated,
                # and drain the chunk's committed edge triples (so the
                # CSR builder sees whole chunks in commit order and a
                # level boundary always finds the buffer empty)
                self._account_tiles(min(start_t, n_tiles_c))
                spill()
                drain_edges()
                chunk_start += n_c

            # ---- level complete: assemble next frontier on host ------
            obs.level_done(depth, frontier=n_front, distinct=fp_count,
                           generated=res.states_generated)
            if n_next_total:
                if self._spill_dir is not None:
                    host_next = drained       # the tier holds the rows
                elif self._pk is not None:
                    host_next = np.concatenate(drained)
                else:
                    host_next = {k: np.concatenate(
                        [d[k] for d in drained]) for k in host_front}
                self._h_parent.append(
                    np.concatenate(d_par) + level_base)
                self._h_action.append(np.concatenate(d_act))
                self._h_param.append(np.concatenate(d_prm))
                self.level_sizes.append(n_next_total)
            else:
                host_next = (drained if self._spill_dir is not None
                             else self._host_zero(0))
            level_base += n_front
            if self._spill_dir is not None:
                # the consumed level's files are dead weight now:
                # steady-state disk holds two levels' worth of rows
                host_front.drop()
            host_front = host_next
            n_front = n_next_total

            if stop:
                res.error = stop
                break
            # fused commit: shrink the expansion caps onto the exact
            # observed maxima (window drained at the level boundary)
            self._calibrate_caps(obs, emit, n_front)
            # pending preemption forces a rescue snapshot at this
            # boundary regardless of cadence (see device_bfs)
            rescue = preempt_signal() if n_front else None
            if checkpoint_path and n_front and (
                    rescue is not None
                    or checkpoint_every is None
                    or time.time() - last_checkpoint >= checkpoint_every):
                from .checkpoint import save_checkpoint, spec_digest
                from .spill import SpillTier
                # disk-tiered frontier: STREAM pages into the staged
                # npz (peak residency = one page) instead of
                # materializing n_front dense rows (ISSUE 13 satellite
                # — the PR 11 save_checkpoint residual)
                fr_kw = (
                    {"frontier_blocks":
                     self._front_dense_blocks(host_front, n_front)}
                    if isinstance(host_front, SpillTier) else
                    {"frontier": self._front_dense(host_front,
                                                   n_front)})
                if self._edges_on:
                    # edge-stream seam (ISSUE 15): the gid column,
                    # the drained edge rows up to this committed
                    # level, and — on a retain_levels (temporal) run
                    # — the retained level blocks, so a SIGTERM'd
                    # temporal run resumes to a bit-identical CSR
                    fr_kw["gids"] = np.asarray(table["gids"])
                    fr_kw["edge_blocks"] = self.edge_sink.blocks()
                    if self.retain_levels:
                        fr_kw["graph_blocks"] = iter(
                            self.level_blocks)
                with obs.timer("checkpoint"):
                    save_checkpoint(
                        checkpoint_path,
                        slots=table["slots"],
                        n_front=n_front,
                        **fr_kw,
                        h_parent=np.concatenate(self._h_parent),
                        h_action=np.concatenate(self._h_action),
                        h_param=np.concatenate(self._h_param),
                        init_dense=self._init_dense,
                        level_sizes=self.level_sizes, depth=depth,
                        fp_count=fp_count,
                        states_generated=res.states_generated,
                        max_msgs=self.codec.shape.MAX_MSGS,
                        expand_mults=self.expand_mults,
                        elapsed=time.time() - t0,
                        digest=spec_digest(spec),
                        pack=self._pack_manifest(),
                        canon=self._canon_manifest(),
                        bounds=self._bounds_manifest(),
                        por=self._por_manifest(), obs=obs)
                last_checkpoint = time.time()
                obs.checkpoint(checkpoint_path, depth, fp_count)
                emit(f"checkpoint written to {checkpoint_path} "
                     f"(depth {depth}, {fp_count} distinct)")
            if rescue is not None:
                obs.rescue(checkpoint_path or "", depth, fp_count,
                           rescue)
                emit(f"preempted by {rescue}: rescue snapshot at depth "
                     f"{depth} ({checkpoint_path}); exiting resumable")
                raise Preempted(checkpoint_path, depth, fp_count,
                                rescue)
            if n_front == 0:
                break
            if max_states and fp_count >= max_states:
                res.error = f"state limit {max_states} reached"
                break
            if fp_count > 0.5 * fp_cap:
                table = grow(table)
                fp_cap *= 4
                self._fresh_jit = True       # shape change
                obs.grow("fpset", fp_cap)
                emit(f"FPSet grown to {fp_cap} slots")

        res.diameter = depth
        return self._finish(res, obs, fp_count,
                            table=table, fp_cap=fp_cap)


    def _finish(self, res, obs, fp_count, table=None, fp_cap=None):
        if self._spill_dir is not None:
            # cumulative bytes the run wrote to the disk tier (files
            # of consumed levels included), then release what is left
            obs.gauge("spill_tier_bytes",
                      int(sum(t.disk_bytes for t in self._tiers)))
            for t in self._tiers:
                t.drop()
            self._tiers = []
        if self._edges_on:
            # edge-stream gauges (ISSUE 15): cumulative drained bytes,
            # the append buffer's observed high water, and the
            # headline emission rate over the run's wall clock
            from .spill import EdgeCSR as _E
            obs.gauge("edge_bytes",
                      int(self._edge_rows_total) * _E.ROW_BYTES)
            obs.gauge("edge_buf_high_water", int(self._edge_hw))
            el = max(time.time() - (self._run_t0 or time.time()),
                     1e-9)
            obs.gauge("edges_per_s",
                      round(self._edge_rows_total / el, 1))
        return super()._finish(res, obs, fp_count, table=table,
                               fp_cap=fp_cap)


def paged_bfs_check(spec, max_states=None, max_depth=None,
                    check_deadlock=False, tile_size=128, max_msgs=None,
                    chunk_tiles=64, log=None, obs=None) -> CheckResult:
    eng = PagedBFS(spec, max_msgs=max_msgs, tile_size=tile_size,
                   chunk_tiles=chunk_tiles)
    return eng.run(max_states=max_states, max_depth=max_depth,
                   check_deadlock=check_deadlock, log=log, obs=obs)
