"""Packed bit-planed frontier encoding (ISSUE 9 tentpole).

The dense state layout (models/*.py ``zero_state``) spends a full
int32 lane on every field, but the speclint ``widths`` pass
(analysis/passes/widths.py) proves most fields fit a handful of bits:
at the defect constants a view number needs 3 bits, a log-entry code
4, a replica id 2 — yet the at-rest frontier, the host spill pages and
the sharded all-to-all all move 32 bits per field.  CAPACITY.md shows
the dense frontier (7.2 KB/state at MAX_MSGS=48), not fingerprints, is
the binding HBM constraint of the defect-scale BFS — so shrinking
bytes/state multiplies both frontier capacity and exchange bandwidth
(the Lazy-TSO-Reachability move, arxiv 1501.02683: pay only for what
the reachability front actually needs).

This module turns the per-field bit budgets into a first-class
interchange format:

* ``build_pack_spec(codec, spec)`` derives a :class:`PackSpec` from the
  codec's ``plane_bounds`` (per-plane — or per-column, for
  heterogeneous planes like ``m_hdr`` — value ranges computed from the
  SAME shape attributes and ``widths.derive_ranges`` table the lint
  pass verifies) — the widths table is the single source of truth for
  field widths (ISSUE 9 satellite; the drift pass cross-checks the
  codec constants against it);
* ``pack``/``unpack`` convert one int32 struct-of-arrays state row to
  and from a ``[words]`` uint32 plane: every lane is biased by its
  lower bound and laid into a contiguous bit stream (a lane may
  straddle two words), so a row costs ``ceil(total_bits / 32)`` words
  instead of one word per lane.  Both directions are pure jnp integer
  ops — jit- and vmap-friendly — and ``pack_np``/``unpack_np`` are
  bit-identical numpy twins for host-side work (paged spill
  compaction, checkpoint conversion);
* the round trip is EXACT for every in-range value (the pack property
  tests drive edge values at each field's width boundary), so the
  engines' distinct/generated/level_sizes/traces stay bit-identical
  with packing on or off — the PR 4 drain-and-replay discipline
  extended to the state representation;
* ``manifest()``/``from_manifest`` serialize the spec into checkpoint
  manifests: a snapshot records the packing-spec ``version`` (a digest
  of the plane table), resume under a mismatched widths table is a
  policy error (TLAError), and a pack=off engine can still read a
  packed snapshot through the manifest's own table (and vice versa).

Planes without a provable bound (e.g. the message-bag ``m_count``
column — TLC bag counts have no static bound) keep their full 32 bits;
the format degrades gracefully to ratio 1.0 for codecs that declare no
bounds at all (``build_pack_spec`` returns None and the engines run
dense unless packing is forced).
"""

from __future__ import annotations

import hashlib
import json

import numpy as np

from ..core.values import TLAError

WORD_BITS = 32
_FULL = np.uint32(0xFFFFFFFF)


def _bits_for(lo, hi):
    """Bits needed to store values lo..hi (biased by -lo); >= 32 falls
    back to a raw 32-bit lane (lo forced to 0 so negative int32 values
    round-trip through the uint32 reinterpretation)."""
    span = int(hi) - int(lo)
    if span < 0:
        raise TLAError(f"packing bound ({lo}, {hi}) is empty")
    bits = max(1, span.bit_length())
    if bits >= WORD_BITS:
        return 0, WORD_BITS
    return int(lo), bits


def _normalize_bounds(key, shape, bound):
    """One plane's declared bound -> per-lane (lo, bits) numpy vectors.

    ``bound`` is ``(lo, hi)`` (uniform) or a sequence of per-column
    ``(lo, hi)`` pairs applying along the plane's LAST axis (the
    column axis of heterogeneous planes like ``m_hdr``/``log``);
    ``None`` keeps raw 32-bit lanes."""
    lanes = int(np.prod(shape) or 1)
    if bound is None:
        return (np.zeros(lanes, np.int64),
                np.full(lanes, WORD_BITS, np.int64), None)
    if isinstance(bound, tuple) and len(bound) == 2 and \
            not isinstance(bound[0], (tuple, list)):
        lo, bits = _bits_for(*bound)
        return (np.full(lanes, lo, np.int64),
                np.full(lanes, bits, np.int64), (lo, bits))
    cols = list(bound)
    if not shape or shape[-1] != len(cols):
        raise TLAError(
            f"plane {key!r}: per-column bounds ({len(cols)} entries) "
            f"do not match the last axis of shape {shape}")
    per = [_bits_for(*b) for b in cols]
    reps = lanes // len(cols)
    lo = np.tile(np.asarray([p[0] for p in per], np.int64), reps)
    bits = np.tile(np.asarray([p[1] for p in per], np.int64), reps)
    return lo, bits, [list(p) for p in per]


class PackSpec:
    """Static layout of the packed row format for one codec binding.

    ``entries`` is a list of ``(key, shape, lo_norm, bits_norm)`` in
    the codec's ``zero_state`` plane order; lo/bits are normalized to
    either an ``(lo, bits)`` pair or a per-column list."""

    def __init__(self, entries):
        self.entries = entries
        self.keys = [e[0] for e in entries]
        self.shapes = {e[0]: tuple(e[1]) for e in entries}
        lo_parts, bit_parts, self._splits = [], [], []
        pos = 0
        for key, shape, _norm, (lo_vec, bits_vec) in (
                (e[0], e[1], e[2], e[3]) for e in entries):
            lanes = lo_vec.shape[0]
            self._splits.append((key, tuple(shape), pos, pos + lanes))
            pos += lanes
            lo_parts.append(lo_vec)
            bit_parts.append(bits_vec)
        self.lanes = pos
        lo = np.concatenate(lo_parts)
        bits = np.concatenate(bit_parts)
        start = np.concatenate([[0], np.cumsum(bits)[:-1]])
        self.total_bits = int(bits.sum())
        self.words = max(1, -(-self.total_bits // WORD_BITS))
        # static per-lane tables (numpy; closed over by the jnp fns)
        self._lo = lo.astype(np.int32)
        self._bits = bits
        self._mask = np.where(
            bits >= WORD_BITS, _FULL,
            (np.uint64(1) << bits.astype(np.uint64)) - 1
        ).astype(np.uint32)
        self._widx = (start // WORD_BITS).astype(np.int32)
        self._off = (start % WORD_BITS).astype(np.uint32)
        self._hishift = (WORD_BITS - 1 - self._off).astype(np.uint32)
        canon = [[k, list(s), n] for k, s, n, _v in entries]
        self.version = hashlib.sha256(
            json.dumps(canon, sort_keys=True).encode()).hexdigest()[:12]

    # -- sizing --------------------------------------------------------
    @property
    def dense_bytes(self):
        """Bytes of one dense int32 row (the format packing replaces)."""
        return self.lanes * 4

    @property
    def packed_bytes(self):
        return self.words * 4

    @property
    def ratio(self):
        return self.dense_bytes / self.packed_bytes

    # -- manifest ------------------------------------------------------
    def manifest(self):
        """JSON-able description stored in checkpoint manifests: enough
        to rebuild the exact layout (``from_manifest``) plus the
        ``version`` digest resume compatibility is judged by."""
        return {"version": self.version, "words": self.words,
                "planes": [[k, list(s), n]
                           for k, s, n, _v in self.entries]}

    @classmethod
    def from_manifest(cls, mf):
        entries = []
        for key, shape, norm in mf["planes"]:
            shape = tuple(shape)
            if norm is None:
                bound = None
            elif norm and isinstance(norm[0], list):
                # per-column [lo, bits] pairs -> reconstruct (lo, hi)
                bound = [(lo, lo + (1 << b) - 1) if b < WORD_BITS
                         else None for lo, b in norm]
                # a raw column inside a per-column plane: widen to the
                # 32-bit sentinel range understood by _bits_for
                bound = [(0, (1 << 31)) if b is None else b
                         for b in bound]
            else:
                lo, b = norm
                bound = (lo, lo + (1 << b) - 1) if b < WORD_BITS \
                    else (0, 1 << 31)
            lo_vec, bits_vec, norm2 = _normalize_bounds(key, shape,
                                                        bound)
            entries.append((key, shape, norm2, (lo_vec, bits_vec)))
        spec = cls(entries)
        if spec.version != mf["version"] or spec.words != mf["words"]:
            raise TLAError(
                f"packing manifest is internally inconsistent "
                f"(version {mf['version']} / {mf['words']} words vs "
                f"rebuilt {spec.version} / {spec.words})")
        return spec

    # -- jnp pack/unpack (one row; vmap for batches) -------------------
    def pack(self, state):
        """Dense per-row state dict (int32 leaves, per-plane shapes)
        -> ``[words]`` uint32 row.  Pure jnp; call under jit/vmap."""
        import jax
        import jax.numpy as jnp
        parts = [jnp.asarray(state[k], jnp.int32).reshape(-1)
                 for k, _s, _p0, _p1 in self._splits_iter()]
        flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        v = (flat - jnp.asarray(self._lo)).astype(jnp.uint32) \
            & jnp.asarray(self._mask)
        off = jnp.asarray(self._off)
        lo_w = jnp.left_shift(v, off)
        hi_w = jnp.right_shift(
            jnp.right_shift(v, jnp.asarray(self._hishift)), 1)
        widx = jnp.asarray(self._widx)
        words = jax.ops.segment_sum(
            jnp.concatenate([lo_w, hi_w]),
            jnp.concatenate([widx, widx + 1]),
            num_segments=self.words + 1)
        return words[:self.words].astype(jnp.uint32)

    def unpack(self, row):
        """``[words]`` uint32 row -> dense per-row state dict."""
        import jax.numpy as jnp
        w = jnp.asarray(row, jnp.uint32)
        widx = jnp.asarray(self._widx)
        w0 = w[widx]
        w1 = w[jnp.minimum(widx + 1, self.words - 1)]
        off = jnp.asarray(self._off)
        v = (jnp.right_shift(w0, off)
             | jnp.left_shift(
                 jnp.left_shift(w1, jnp.asarray(self._hishift)), 1)) \
            & jnp.asarray(self._mask)
        flat = v.astype(jnp.int32) + jnp.asarray(self._lo)
        return {k: flat[a:b].reshape(s)
                for k, s, a, b in self._splits}

    def _splits_iter(self):
        return self._splits

    # -- numpy twins (batched; host-side spill/checkpoint work) --------
    def pack_np(self, batch):
        """Dense batch dict (``[N, ...plane]`` arrays) -> ``[N, words]``
        uint32.  Bit-identical to the jnp ``pack``."""
        first = batch[self._splits[0][0]]
        n = np.asarray(first).shape[0]
        flat = np.concatenate(
            [np.asarray(batch[k], np.int32).reshape(n, -1)
             for k, _s, _a, _b in self._splits], axis=1)
        v = (flat.astype(np.int64) - self._lo[None, :]).astype(
            np.uint32) & self._mask[None, :]
        lo_w = np.left_shift(v, self._off[None, :])
        hi_w = np.right_shift(
            np.right_shift(v, self._hishift[None, :]), 1)
        out = np.zeros((n, self.words + 1), np.uint32)
        np.add.at(out, (slice(None),
                        np.concatenate([self._widx, self._widx + 1])),
                  np.concatenate([lo_w, hi_w], axis=1))
        return out[:, :self.words]

    def unpack_np(self, rows):
        """``[N, words]`` uint32 -> dense batch dict of int32 arrays."""
        w = np.asarray(rows, np.uint32)
        if w.ndim == 1:
            w = w[None]
            squeeze = True
        else:
            squeeze = False
        w0 = w[:, self._widx]
        w1 = w[:, np.minimum(self._widx + 1, self.words - 1)]
        v = (np.right_shift(w0, self._off[None, :])
             | np.left_shift(
                 np.left_shift(w1, self._hishift[None, :]), 1)) \
            & self._mask[None, :]
        flat = v.astype(np.uint32).view(np.int32) + self._lo[None, :]
        out = {}
        for k, s, a, b in self._splits:
            arr = flat[:, a:b].reshape((w.shape[0],) + s)
            out[k] = arr[0] if squeeze else arr
        return out

    def unpack_row_np(self, row):
        """One ``[words]`` row -> per-row dense dict (numpy): plane
        shapes WITHOUT a leading batch axis (the 1-D input takes
        ``unpack_np``'s squeeze path)."""
        return self.unpack_np(np.asarray(row).reshape(-1))


def build_pack_spec(codec, spec=None, ranges=None, force=False,
                    tighten=None):
    """Derive the :class:`PackSpec` for a codec binding.

    ``ranges`` is the widths-pass field-range table
    (``analysis.passes.widths.derive_ranges``); when absent it is
    derived from ``spec`` — the ONE declared-range source the lint
    table, the codecs' ``plane_bounds`` hooks and the bounds pass all
    read (ISSUE 13 satellite).  Codecs that declare no
    ``plane_bounds`` return None (dense is already optimal
    knowledge-free) unless ``force`` — then every lane keeps 32 bits
    (ratio 1.0) so the interchange format still exists.

    ``tighten`` is the bounds pass's reachable-interval map
    (``BoundsFacts.plane_tighten()``, ISSUE 13): plane keys matching a
    tightened state variable have their declared bound INTERSECTED
    with the reachable interval — fewer bits per lane, and since the
    intervals over-approximate reachability the round trip stays
    exact for every reachable state (the bit-identity oracle in
    tests/test_bounds.py).  Only uniform (or absent) declared bounds
    tighten; per-column planes keep their declared table."""
    bounds = {}
    if hasattr(codec, "plane_bounds"):
        if ranges is None and spec is not None:
            from ..analysis.passes.widths import derive_ranges
            ranges = derive_ranges(spec)
        bounds = codec.plane_bounds(ranges or {})
    elif not force:
        return None
    zero = codec.zero_state()
    if tighten:
        bounds = dict(bounds)
        for key, (tlo, thi) in tighten.items():
            if key not in zero:
                continue                    # not a plane of this codec
            cur = bounds.get(key)
            if cur is None:
                bounds[key] = (int(tlo), int(thi))
            elif isinstance(cur, tuple) and len(cur) == 2 and \
                    not isinstance(cur[0], (tuple, list)):
                lo, hi = max(cur[0], int(tlo)), min(cur[1], int(thi))
                if lo <= hi:
                    bounds[key] = (lo, hi)  # reachable ∩ declared
            # per-column declared tables keep their own budgets
    entries = []
    for key, z in zero.items():
        shape = tuple(np.shape(z))
        lo_vec, bits_vec, norm = _normalize_bounds(
            key, shape, bounds.get(key))
        entries.append((key, shape, norm, (lo_vec, bits_vec)))
    return PackSpec(entries)
