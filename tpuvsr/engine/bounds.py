"""Engine-side consumption of the speclint bounds pass (ISSUE 13).

``analysis/passes/bounds.py`` computes the facts; this module is the
seam through which the engines trust them:

* :func:`resolve_bounds` — the one policy switch.  ``"auto"`` (every
  engine's default) consumes the facts iff the speclint gate is live:
  ``TPUVSR_LINT=off`` / ``-lint=off`` disables consumption too, because
  tightened packing derived from an unverified spec is exactly the
  silent-wrap hazard speclint exists to prevent.  Forcing ``True``
  under a disabled gate is a loud error (the CLI rejects the flag
  combination at parse time; this guards library callers).
* :func:`prune_kernel` — wraps a device kernel with the statically
  dead actions removed: the action list, guard/action function lists,
  flat lane tables and ``step_all`` rows all shrink, so the fused
  commit's chunk-wide guard matrix and the per-action staging queue
  never evaluate a guard that constant-folds to FALSE.  Dead actions
  are never enabled, so counts, level sizes, verdicts and traces are
  BIT-IDENTICAL to the unpruned kernel (the ``tests/test_bounds.py``
  oracles); only the ``action_expansions`` gauge loses its
  all-zero rows.

Checkpoint seam: engines record ``BoundsFacts.digest`` in snapshot
manifests and refuse to resume under a flipped ``-bounds`` or changed
facts (mirroring the pack/canon rules) — the packed frontier layout
and the lane-id space both depend on the facts.
"""

from __future__ import annotations

import numpy as np

from ..core.values import TLAError


def resolve_bounds(spec, req="auto"):
    """The engines' bounds switch -> :class:`BoundsFacts` or None.

    ``req``: ``"auto"`` (on iff the speclint gate is live) |
    True/"on" (forced; error when the gate is off) | False/"off"."""
    if req is False or req == "off":
        return None
    from ..analysis import lint_enabled
    if not lint_enabled():
        if req is True or req == "on":
            raise TLAError(
                "bounds=on requires the speclint gate: TPUVSR_LINT=off "
                "/ -lint=off disables the static analysis the "
                "tightened packing and pruned action lists would "
                "trust (drop -bounds on or re-enable lint)")
        return None
    from ..analysis.passes.bounds import analyze
    return analyze(spec)


class PrunedKernel:
    """A device kernel with statically dead actions removed.

    Implements exactly the attribute contract the engines consume
    (``action_names`` / ``n_lanes`` / ``_lane_count`` / ``_guard_fns``
    / ``_action_fns`` / ``lane_action`` / ``lane_param`` /
    ``step_all``); everything else (fingerprinting, invariants,
    symmetry tables, key tables) delegates to the wrapped kernel."""

    def __init__(self, kern, dead):
        names = list(kern.action_names)
        dead = [n for n in dead if n in names]
        keep = [n for n in names if n not in dead]
        if not keep:
            raise TLAError("prune_kernel: every action is dead — the "
                           "engine needs at least one live action "
                           "(run bounds=off to inspect the space)")
        self._base = kern
        self.pruned_actions = dead
        self.action_names = keep
        keep_aids = np.asarray([names.index(n) for n in keep],
                               np.int32)
        # flat lane tables: keep the lanes of live actions, renumber
        # action ids onto the filtered list (lane params unchanged)
        la = np.asarray(kern.lane_action, np.int32)
        self._lane_keep = np.where(np.isin(la, keep_aids))[0]
        remap = np.full(len(names), -1, np.int32)
        remap[keep_aids] = np.arange(len(keep), dtype=np.int32)
        self.lane_action = remap[la[self._lane_keep]]
        self.lane_param = np.asarray(kern.lane_param,
                                     np.int32)[self._lane_keep]
        self.n_lanes = int(self._lane_keep.shape[0])
        self._keep_idx = [names.index(n) for n in keep]

    def _lane_count(self, name):
        return self._base._lane_count(name)

    def _guard_fns(self):
        fns = self._base._guard_fns()
        return [fns[i] for i in self._keep_idx]

    def _action_fns(self):
        fns = self._base._action_fns()
        return [fns[i] for i in self._keep_idx]

    def step_all(self, st):
        succs, ens = self._base.step_all(st)
        idx = self._lane_keep
        return ({k: v[idx] for k, v in succs.items()}, ens[idx])

    def __getattr__(self, name):
        return getattr(self.__dict__["_base"], name)


def prune_kernel(kern, dead):
    """Wrap `kern` with the `dead` action names removed (no-op pass
    back when nothing would change)."""
    dead = [n for n in dead if n in kern.action_names]
    if not dead:
        return kern
    return PrunedKernel(kern, dead)
