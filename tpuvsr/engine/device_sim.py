"""Device simulation mode: vectorized random walks (TLC's simulator,
README:22, rebuilt as a vmapped XLA program; BASELINE.json configs[2]).

Semantics match TLC's SimulationWorker: each walk starts at the initial
state and repeatedly jumps to a successor chosen uniformly at random
from the full (action x binding) successor list — which is exactly the
kernel's lane space — checking invariants at every visited state, up to
a depth bound.  A walker with no enabled successor stays put (TLC ends
the walk; with -deadlock it is reported).

W walkers advance in lockstep inside one jitted step: expand all lanes,
draw an argmax-of-masked-uniforms lane (uniform over enabled lanes),
gather the chosen successor, and evaluate the invariants.  Per-walker
histories are kept host-side as (action id, lane param) pairs — stable
across message-table growth — so a violating walk replays through the
materialize kernels into a full TRACE-format counterexample.  On bag
overflow the message table grows in place (zero padding changes no
state content) and the erroring step is redrawn.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from ..models.vsr import VSRCodec
from ..models.vsr_kernel import ACTION_NAMES, VSRKernel
from .device_bfs import _value_perm_table
from .simulate import SimResult
from .spec import SpecModel
from .trace import TraceEntry

_MSG_KEYS = ("m_present", "m_count", "m_hdr", "m_entry", "m_log",
             "m_log_len", "m_has_log")


class DeviceSimulator:
    def __init__(self, spec: SpecModel, max_msgs=None, walkers=256):
        self.spec = spec
        self.W = walkers
        self.inv_names = list(spec.cfg.invariants)
        self._build(max_msgs)

    def _build(self, max_msgs):
        spec = self.spec
        self.codec = VSRCodec(spec.ev.constants, max_msgs=max_msgs)
        self.kern = VSRKernel(self.codec,
                              perms=_value_perm_table(spec, self.codec))
        inv = self.kern.invariant_fn(self.inv_names)
        kern = self.kern

        def step(states, keys):
            def one(st, key):
                succs, en = kern.step_all(st)
                u = jax.random.uniform(key, en.shape)
                lane = jnp.argmax(jnp.where(en, u, -1.0))
                alive = en.any()
                succ = {k: jnp.where(alive, v[lane], st[k])
                        for k, v in succs.items()}
                bad = alive & ~inv(succ)
                err = alive & (succ["err"] != 0)
                return succ, lane, alive, bad, err
            return jax.vmap(one)(states, keys)

        self._step = jax.jit(step)
        self._mat = {}

    def _grow_msgs(self, batches):
        """Double MAX_MSGS and pad the given dense batches."""
        old = self.codec.shape.MAX_MSGS
        self._build(old * 2)

        def pad(d):
            out = dict(d)
            for k in _MSG_KEYS:
                v = np.asarray(d[k])
                shape = list(v.shape)
                shape[1] = old
                out[k] = np.concatenate(
                    [v, np.zeros(shape, v.dtype)], axis=1)
            return out
        return [pad(b) for b in batches]

    def _materialize_one(self, st, aid, param):
        fn = self._mat.get(aid)
        if fn is None:
            fn = jax.jit(jax.vmap(self.kern._action_fns()[aid],
                                  in_axes=(0, 0)))
            self._mat[aid] = fn
        batch = {k: np.asarray(v)[None] for k, v in st.items()}
        succ, en = fn(batch, jnp.asarray([param], jnp.int32))
        assert bool(np.asarray(en)[0]), "replay chose a disabled lane"
        return {k: np.asarray(v)[0] for k, v in succ.items()
                if not k.startswith("_")}

    def run(self, num=1000, depth=100, seed=0, check_deadlock=False,
            log=None, max_seconds=None) -> SimResult:
        """Run `num` walks of `depth` steps (W at a time)."""
        spec, codec = self.spec, self.codec
        res = SimResult()
        t0 = time.time()
        init_dense = [codec.encode(st) for st in spec.init_states()]
        init = {k: np.repeat(np.stack([d[k] for d in init_dense])[:1],
                             self.W, axis=0) for k in init_dense[0]}
        bad0 = spec.check_invariants(
            codec.decode({k: np.asarray(v[0]) for k, v in init.items()}))
        if bad0:
            res.ok = False
            res.violated_invariant = bad0
            res.elapsed = time.time() - t0
            return res
        key = jax.random.PRNGKey(seed)
        stop = False
        while res.walks < num and not stop:
            states = {k: np.asarray(v) for k, v in init.items()}
            hist_aid = np.full((self.W, depth), -1, np.int32)
            hist_par = np.zeros((self.W, depth), np.int32)
            was_alive = np.ones((self.W,), bool)
            for d in range(depth):
                key, sub = jax.random.split(key)
                keys = jax.random.split(sub, self.W)
                while True:
                    out = self._step(
                        {k: jnp.asarray(v) for k, v in states.items()},
                        keys)
                    nstates, lanes, alive, bad, err = out
                    if np.asarray(err).any():
                        # bag overflow in some successor: grow the table,
                        # pad walker states, and redraw this step
                        init, states = self._grow_msgs([init, states])
                        if log:
                            log(f"message table grown to "
                                f"{self.codec.shape.MAX_MSGS} slots")
                        continue
                    break
                lanes = np.asarray(lanes)
                alive_np = np.asarray(alive)
                hist_aid[:, d] = np.where(
                    alive_np, self.kern.lane_action[lanes], -1)
                hist_par[:, d] = np.where(
                    alive_np, self.kern.lane_param[lanes], 0)
                states = {k: np.asarray(v) for k, v in nstates.items()}
                res.steps += int(alive_np.sum())
                if check_deadlock and (was_alive & ~alive_np).any():
                    w = int(np.argmax(was_alive & ~alive_np))
                    res.ok = False
                    res.deadlocks += 1
                    res.trace = self._replay(init, hist_aid[w], hist_par[w])
                    res.violated_invariant = None
                    res.elapsed = time.time() - t0
                    return res
                was_alive = alive_np
                bad_np = np.asarray(bad)
                if bad_np.any():
                    w = int(np.argmax(bad_np))
                    res.ok = False
                    res.trace = self._replay(init, hist_aid[w], hist_par[w])
                    res.violated_invariant = self.spec.check_invariants(
                        res.trace[-1].state) or self.inv_names[0]
                    res.elapsed = time.time() - t0
                    return res
                if max_seconds and time.time() - t0 > max_seconds:
                    stop = True
                    break
            res.walks += self.W
            if log:
                el = time.time() - t0
                log(f"{res.walks} walks, {res.steps / el:.0f} steps/s")
        res.elapsed = time.time() - t0
        return res

    def _replay(self, init, aids, params):
        """Re-execute one walk's (action, param) choices into a trace."""
        st = {k: np.asarray(v[0]) for k, v in init.items()}
        loc = {a.name: a.location for a in self.spec.actions}
        out = [TraceEntry(position=1, action_name=None, location=None,
                          state=self.codec.decode(st))]
        for i in range(len(aids)):
            if aids[i] < 0:
                break
            st = self._materialize_one(st, int(aids[i]), int(params[i]))
            name = ACTION_NAMES[aids[i]]
            out.append(TraceEntry(position=i + 2, action_name=name,
                                  location=loc.get(name),
                                  state=self.codec.decode(st)))
        return out


def device_simulate(spec: SpecModel, num=1000, depth=100, seed=0,
                    walkers=256, max_msgs=None, check_deadlock=False,
                    log=None, max_seconds=None) -> SimResult:
    sim = DeviceSimulator(spec, max_msgs=max_msgs, walkers=walkers)
    return sim.run(num=num, depth=depth, seed=seed,
                   check_deadlock=check_deadlock, log=log,
                   max_seconds=max_seconds)
