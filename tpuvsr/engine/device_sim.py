"""Device simulation mode: vectorized random walks (TLC's simulator,
README:22, rebuilt as a scan-based XLA program; BASELINE.json
configs[2]).

SUPERSEDED as the simulation backend by the sharded walker fleet
(``tpuvsr/sim``, ISSUE 7): the CLI ``-simulate`` path, ``bench.py``'s
``sim_scale``/``defect_hunt`` probes and the service ``kind="sim"``
jobs all run the fleet — per-(seed, walk-id) deterministic draws,
shard_map across the mesh, the ``engine/pipeline.py`` dispatch window,
and importance splitting over a fingerprint-novelty seen-set.  This
class remains the single-device scan oracle (its chunk kernel is the
shape the fleet's was grown from) and the backend for callers that
want the legacy shared-key-stream draw; ``device_simulate(...,
fleet=True)`` delegates to the fleet.

Semantics match TLC's SimulationWorker: each walk starts at the initial
state and repeatedly jumps to a successor chosen uniformly at random
from the full (action x binding) successor list — which is exactly the
kernel's lane space — checking invariants at every visited state, up to
a depth bound.  A walker with no enabled successor stays put (TLC ends
the walk; with -deadlock it is reported).

TPU structure (one host sync per CHUNK of steps, not per step):

* enabledness comes from the cheap guard pass over all lanes
  (vsr_kernel guard fns) — successors are never materialized for the
  draw;
* the drawn lane is applied with ``lax.switch`` over the 19 action
  bodies, one successor per walker;
* ``lax.scan`` advances all W walkers CHUNK steps inside one jit,
  recording (action id, lane param) histories as scan outputs that stay
  on device unless a violation needs replaying;
* on bag overflow the message table grows in place (zero padding
  changes no state content) and the chunk is re-run from its saved
  entry states — the walk segment is simply redrawn under the larger
  layout.

A violating walk replays its recorded (action, param) chain through the
materialize kernel into a full TRACE-format counterexample
(state_transfer_violation_trace.txt:3-7 format).
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from ..models import registry
from ..obs import RunObserver, closes_observer
from .simulate import SimResult
from .spec import SpecModel
from .trace import TraceEntry

I32 = jnp.int32


def materialize_walk(kern, codec, spec, st0, aids, prms, n_steps,
                     cache=None):
    """Re-execute a recorded (action id, lane param) choice sequence
    from dense state `st0` through the materialize kernel into a
    TRACE-format counterexample — the ONE replay used by both the
    single-device simulator and the walker fleet (tpuvsr/sim).  Stops
    at `n_steps` or the first ``-1`` action (a frozen walker).
    `cache` maps action id -> jitted single-state materializer (pass
    the caller's dict to reuse compilations across replays)."""
    cache = {} if cache is None else cache
    loc = {a.name: a.location for a in spec.actions}
    st = {k: np.asarray(v) for k, v in st0.items()}
    out = [TraceEntry(position=1, action_name=None, location=None,
                      state=codec.decode(st))]
    for i in range(min(int(n_steps), len(aids))):
        aid = int(aids[i])
        if aid < 0:
            break
        fn = cache.get(aid)
        if fn is None:
            fn = jax.jit(jax.vmap(kern._action_fns()[aid],
                                  in_axes=(0, 0)))
            cache[aid] = fn
        batch = {k: np.asarray(v)[None] for k, v in st.items()}
        succ, en = fn(batch, jnp.asarray([int(prms[i])], jnp.int32))
        if not bool(np.asarray(en)[0]):
            raise AssertionError("replay chose a disabled lane")
        st = {k: np.asarray(v)[0] for k, v in succ.items()
              if not k.startswith("_")}
        name = kern.action_names[aid]
        out.append(TraceEntry(position=i + 2, action_name=name,
                              location=loc.get(name),
                              state=codec.decode(st)))
    return out


class DeviceSimulator:
    """``action_weights``: optional per-action sampling weights (dict
    action-name -> weight, or array over kernel action order).  When set,
    each step samples in two stages — an enabled *action* with
    probability proportional to its weight, then a uniformly random
    enabled lane within it — instead of TLC's uniform-over-successors
    draw.  With an unbounded bag the successor list is dominated by
    message-delivery lanes, so uniform-over-successors walks almost
    never exercise rare guard-windows like the SendGetState truncation
    (VSR.tla:491-516); action-stage weighting is the scheduler-bias
    knob that makes deep defect hunts tractable.

    ``swarm_sigma``: standard deviation of per-walker log-normal noise
    multiplied onto the weights, resampled every walk round — a swarm
    of differently-biased schedulers instead of one (diversifies the
    explored interleaving distribution at zero cost).

    ``guided``: importance splitting for rare-violation hunts.  At
    every chunk boundary the walker population is resampled with
    probability proportional to ``exp(beta * kern.hunt_score(state))``
    — walkers that progressed toward the violation are cloned, walkers
    that didn't are culled (their recorded histories are permuted
    consistently, so a violating clone still replays into a full
    counterexample trace).  A multilevel-splitting rare-event search
    the reference's checker has no analog of; it trades the uniform
    walk distribution for a massively higher hit rate on deep defects
    like the state-transfer data loss."""

    def __init__(self, spec: SpecModel, max_msgs=None, walkers=256,
                 chunk_steps=32, action_weights=None, swarm_sigma=0.0,
                 guided=False, split_beta=1.5, dispatch="grouped",
                 group_caps=None, model_factory=None):
        # model_factory(spec, max_msgs=..) -> (codec, kernel); default
        # is the hand-kernel registry (DeviceBFS parity)
        self._model_factory = model_factory or registry.make_model
        self.spec = spec
        self.W = walkers
        self.chunk = chunk_steps
        self.inv_names = list(spec.cfg.invariants)
        self.swarm_sigma = float(swarm_sigma)
        self._action_weights = action_weights
        self.guided = bool(guided)
        self.split_beta = float(split_beta)
        # "grouped": gather walkers by chosen action and apply each
        # action body only to its group (adaptive per-action caps,
        # grown on overflow) — ~n_actions/avg_groups times less action
        # compute per step than "dense", which evaluates every action
        # body for every walker (the round-3 profile bottleneck,
        # VERDICT item 4).
        self.dispatch = dispatch
        self.group_caps = group_caps      # per-action gather capacities
        self.log_w = None           # resolved against the kernel in _build
        self._build(max_msgs)

    def _build(self, max_msgs):
        spec = self.spec
        self.codec, self.kern = self._model_factory(spec,
                                                    max_msgs=max_msgs)
        kern = self.kern
        names = kern.action_names
        aw = self._action_weights
        if aw is None:
            self.log_w = None
        else:
            if isinstance(aw, dict):
                w = np.ones(len(names))
                for name, x in aw.items():
                    w[names.index(name)] = x
            else:
                w = np.asarray(aw, float)
            if w.shape != (len(names),) or (w <= 0).any():
                raise ValueError("action_weights must be positive, one "
                                 "per action")
            self.log_w = np.log(w)
        inv = kern.invariant_fn(self.inv_names)
        lane_aid = jnp.asarray(kern.lane_action)
        lane_prm = jnp.asarray(kern.lane_param)
        guards = kern._guard_fns()
        fns = kern._action_fns()

        def guard_all(st):
            outs = []
            for name, g in zip(names, guards):
                lanes = jnp.arange(kern._lane_count(name), dtype=I32)
                outs.append(jax.vmap(lambda ln, g=g: g(st, ln))(lanes))
            return jnp.concatenate(outs)

        W = self.W
        if self.group_caps is None:
            # starting caps: an even split plus slack; overflow at a
            # chunk grows the overflowing action's cap and redraws
            self.group_caps = [min(W, max(32, W // 4))] * len(names)

        def apply_dense(states, aid, prm, alive):
            """Per-walker successor for the chosen (action, param).

            Explicit compute-all-actions + mask-select.  A vmapped
            ``lax.switch`` lowers to the same all-branches select_n, but
            that lowering produced wrong bag contents on the TPU backend
            (headers lost while present/count landed — caught by the
            interpreter-confirmation check); the hand-rolled select is
            the same cost and lowers through plain jnp.where."""
            out = None
            for a, f in enumerate(fns):
                s_a, _en = jax.vmap(f, in_axes=(0, 0))(states, prm)
                m = aid == a
                if out is None:
                    out = {k: jnp.where(
                        m.reshape((-1,) + (1,) * (v.ndim - 1)), v, states[k])
                        for k, v in s_a.items() if not k.startswith("_")}
                else:
                    out = {k: jnp.where(
                        m.reshape((-1,) + (1,) * (s_a[k].ndim - 1)),
                        s_a[k], v) for k, v in out.items()}
            return out, jnp.zeros((len(names),), bool)

        caps = list(self.group_caps)

        def apply_grouped(states, aid, prm, alive):
            """Guard-gathered grouped dispatch: for each action, gather
            just the walkers that chose it (<= its cap), run that one
            action body on the small batch, scatter the successors
            back.  Action-body compute per step is sum(group sizes)
            ~= W instead of W x n_actions.  Per-action overflow is
            reported so the host can grow the cap and redraw the chunk
            deterministically (same keys -> same draws)."""
            out = {k: v for k, v in states.items()}
            ovf = []
            for a, f in enumerate(fns):
                C = caps[a]
                m = (aid == a) & alive
                ovf.append(m.sum() > C)
                (sel,) = jnp.nonzero(m, size=C, fill_value=W)
                ok = sel < W
                idx = jnp.clip(sel, 0, W - 1)
                st_a = {k: v[idx] for k, v in states.items()}
                s_a, _en = jax.vmap(f, in_axes=(0, 0))(st_a, prm[idx])
                dest = jnp.where(ok, sel, W).astype(I32)  # OOB drops
                for k in out:
                    out[k] = out[k].at[dest].set(s_a[k], mode="drop")
            return out, jnp.stack(ovf)

        apply_chosen = (apply_grouped if self.dispatch == "grouped"
                        else apply_dense)

        weighted = self.log_w is not None
        n_act = len(names)

        def chunk_fn(states, was_alive, keys, logw):
            def step(carry, key):
                (states, was_alive, bad, dead, err_any, ovf,
                 steps, d) = carry
                en = jax.vmap(guard_all)(states)          # [W, L]
                if weighted:
                    # stage 1: enabled action ~ weights (Gumbel-max);
                    # stage 2: uniform enabled lane within it
                    k1, k2 = jax.random.split(key)
                    act_en = jnp.zeros((en.shape[0], n_act), bool) \
                        .at[:, lane_aid].max(en)
                    g = jax.random.gumbel(k1, act_en.shape) + logw
                    a_star = jnp.argmax(jnp.where(act_en, g, -jnp.inf),
                                        axis=1)
                    v = jax.random.uniform(k2, en.shape)
                    in_act = en & (lane_aid[None, :] == a_star[:, None])
                    lane = jnp.argmax(jnp.where(in_act, v, -1.0), axis=1)
                else:
                    u = jax.random.uniform(key, en.shape)
                    lane = jnp.argmax(jnp.where(en, u, -1.0), axis=1)
                alive = en.any(axis=1)
                aid = lane_aid[lane]
                prm = lane_prm[lane]
                succ, ovf_a = apply_chosen(states, aid, prm, alive)
                sel = {k: alive.reshape((-1,) + (1,) * (v.ndim - 1))
                       for k, v in states.items()}
                states = {k: jnp.where(sel[k], succ[k], v)
                          for k, v in states.items()}
                err = alive & (succ["err"] != 0)
                iok = jax.vmap(inv)(succ)
                badw = alive & ~iok & ~err
                hit = badw.any() & (bad[0] < 0)
                bad = jnp.where(hit, jnp.stack(
                    [jnp.argmax(badw).astype(I32), d]), bad)
                dw = was_alive & ~alive
                hitd = dw.any() & (dead[0] < 0)
                dead = jnp.where(hitd, jnp.stack(
                    [jnp.argmax(dw).astype(I32), d]), dead)
                err_any = err_any | err.any()
                steps = steps + alive.sum()
                hist = (jnp.where(alive, aid, -1).astype(I32),
                        jnp.where(alive, prm, 0).astype(I32))
                return (states, alive, bad, dead, err_any,
                        ovf | ovf_a, steps, d + 1), hist

            init = (states, was_alive, jnp.full((2,), -1, I32),
                    jnp.full((2,), -1, I32), jnp.asarray(False),
                    jnp.zeros((n_act,), bool),
                    jnp.asarray(0, I32), jnp.asarray(0, I32))
            (states, alive, bad, dead, err_any, ovf, steps, _d), hist = \
                jax.lax.scan(step, init, keys)
            return states, alive, bad, dead, err_any, ovf, steps, hist

        self._chunk = jax.jit(chunk_fn)
        self._fresh_jit = True   # first dispatch after a (re)build is
        #                          charged to the "compile" phase
        if self.guided:
            if not hasattr(kern, "hunt_score"):
                raise ValueError(
                    "guided simulation needs a kernel hunt_score")
            self._score = jax.jit(jax.vmap(kern.hunt_score))
        self._mat = {}

    def _resample(self, rng, states, was_alive, hists):
        """Importance-splitting step: draw W walker indices with
        probability ~ exp(beta * hunt_score), permute walker state AND
        every recorded history chunk by the draw (clones inherit their
        parent's past, so traces replay exactly)."""
        scores = np.asarray(self._score(states)).astype(np.float64)
        if scores.max() == scores.min():
            return states, was_alive, hists, scores.max()
        z = self.split_beta * (scores - scores.max())
        p = np.exp(z)
        p /= p.sum()
        sel = jnp.asarray(rng.choice(self.W, size=self.W, p=p), jnp.int32)
        states = {k: v[sel] for k, v in states.items()}
        was_alive = was_alive[sel]
        hists = [(ha[:, sel], hp[:, sel]) for ha, hp in hists]
        return states, was_alive, hists, scores.max()

    def _round_logw(self, key):
        """Per-walker action log-weights for one walk round (base
        weights + optional swarm noise), or a dummy scalar when
        running TLC-uniform."""
        if self.log_w is None:
            return jnp.zeros(())
        logw = jnp.asarray(self.log_w, jnp.float32)[None, :]
        logw = jnp.broadcast_to(logw, (self.W, logw.shape[1]))
        if self.swarm_sigma > 0.0:
            noise = jax.random.normal(key, logw.shape) * self.swarm_sigma
            logw = logw + noise
        return logw

    def _grow_msgs(self, batches):
        """Double MAX_MSGS and pad the given dense batches."""
        old = self.codec.shape.MAX_MSGS
        self._build(old * 2)
        return [self.codec.pad_msgs(b, old) for b in batches]

    @closes_observer
    def run(self, num=1000, depth=100, seed=0, check_deadlock=False,
            log=None, max_seconds=None, obs=None) -> SimResult:
        """Run `num` walks of `depth` steps (W at a time, `chunk` steps
        per device sync)."""
        obs = RunObserver.ensure(obs, "device-sim", self.spec, log=log)
        self._obs_active = obs          # closes_observer finalizes it
        spec, codec = self.spec, self.codec
        res = SimResult()
        t0 = time.time()
        obs.start(t0, backend=jax.default_backend())
        init_dense = [codec.encode(st) for st in spec.init_states()]
        init = {k: np.repeat(np.stack([d[k] for d in init_dense])[:1],
                             self.W, axis=0) for k in init_dense[0]}
        bad0 = spec.check_invariants(
            codec.decode({k: np.asarray(v[0]) for k, v in init.items()}))
        if bad0:
            res.ok = False
            res.violated_invariant = bad0
            return obs.finish(res)
        key = jax.random.PRNGKey(seed)
        rng = np.random.default_rng(seed ^ 0x5EED)
        init = {k: jnp.asarray(v) for k, v in init.items()}
        stop = False
        best_score = 0
        while res.walks < num and not stop:
            states = init
            was_alive = jnp.ones((self.W,), bool)
            hists = []          # [(ha [k, W], hp [k, W])] device arrays
            d = 0
            key, wkey = jax.random.split(key)
            logw = self._round_logw(wkey)
            while d < depth:
                k = min(self.chunk, depth - d)
                key, sub = jax.random.split(key)
                keys = jax.random.split(sub, k)
                while True:
                    phase = "compile" if self._fresh_jit else "dispatch"
                    with obs.timer(phase), obs.annotate(
                            f"sim chunk (depth {d}) {phase}"):
                        (nstates, alive, bad, dead, err_any, ovf, steps,
                         hist) = self._chunk(states, was_alive, keys,
                                             logw)
                        err_any.block_until_ready()
                    self._fresh_jit = False
                    obs.count("dispatches")
                    with obs.timer("host_sync"):
                        err_any_h = bool(err_any)
                        ovf = np.asarray(ovf)
                    if err_any_h:
                        # bag overflow inside the chunk: grow the table,
                        # pad saved entry states, redraw the chunk
                        init, states = self._grow_msgs([init, states])
                        obs.grow("message_table",
                                 self.codec.shape.MAX_MSGS)
                        if log:
                            log(f"message table grown to "
                                f"{self.codec.shape.MAX_MSGS} slots")
                        continue
                    if ovf.any():
                        # a dispatch group overflowed its gather cap:
                        # double the caps of the flagged actions and
                        # redraw the chunk (same keys, same draws —
                        # deterministic, so the grown caps now fit)
                        for a in np.nonzero(ovf)[0]:
                            self.group_caps[a] = min(
                                self.W, self.group_caps[a] * 2)
                            obs.grow("dispatch_group",
                                     self.group_caps[a])
                            if log:
                                log(f"dispatch group for "
                                    f"{self.kern.action_names[a]} grown "
                                    f"to {self.group_caps[a]} "
                                    f"(recompiling)")
                        self._build(self.codec.shape.MAX_MSGS)
                        continue
                    break
                hists.append(hist)
                with obs.timer("host_sync"):
                    res.steps += int(steps)
                    bad = np.asarray(bad)
                    dead = np.asarray(dead)
                # report whichever event happened at the earlier step of
                # the chunk; within one step deadlocks are checked first
                # (matching the per-step engine semantics)
                dead_first = (check_deadlock and dead[0] >= 0
                              and (bad[0] < 0 or dead[1] <= bad[1]))
                if dead_first:
                    w, ds = int(dead[0]), int(dead[1])
                    res.ok = False
                    res.deadlocks += 1
                    res.trace = self._replay(init, hists, w, d + ds)
                    res.violated_invariant = None
                    return obs.finish(res)
                if bad[0] >= 0:
                    w, ds = int(bad[0]), int(bad[1])
                    res.ok = False
                    res.trace = self._replay(init, hists, w, d + ds + 1)
                    confirmed = spec.check_invariants(res.trace[-1].state)
                    if confirmed is None:
                        # The device invariant kernel flagged a state the
                        # interpreter (the semantic oracle) accepts: an
                        # engine bug, never a spec violation — fail loudly
                        # rather than emit a bogus counterexample.
                        from ..core.values import TLAError
                        err = TLAError(
                            "device/interpreter divergence: device "
                            "invariant kernel reported a violation at "
                            f"walker {w} depth {d + ds + 1}, but the "
                            "interpreter accepts the replayed state")
                        err.trace = res.trace
                        raise err
                    res.violated_invariant = confirmed
                    return obs.finish(res)
                states, was_alive = nstates, alive
                d += k
                if self.guided and d < depth:
                    states, was_alive, hists, sc = self._resample(
                        rng, states, was_alive, hists)
                    best_score = max(best_score, int(sc))
                if max_seconds and time.time() - t0 > max_seconds:
                    stop = True
                    break
            res.walks += self.W
            obs.progress(walks=res.walks, steps=res.steps,
                         extra=(f"best score {best_score}"
                                if self.guided else None))
        return obs.finish(res)

    def _replay(self, init, hists, w, n_steps):
        """Re-execute walker `w`'s first `n_steps` recorded choices into
        a TRACE-format counterexample."""
        aids = np.concatenate([np.asarray(ha)[:, w] for ha, _hp in hists])
        prms = np.concatenate([np.asarray(hp)[:, w] for _ha, hp in hists])
        st = {k: np.asarray(v[w]) for k, v in init.items()}
        return materialize_walk(self.kern, self.codec, self.spec, st,
                                aids, prms, n_steps, cache=self._mat)


def device_simulate(spec: SpecModel, num=1000, depth=100, seed=0,
                    walkers=256, max_msgs=None, check_deadlock=False,
                    log=None, max_seconds=None, chunk_steps=32,
                    action_weights=None, swarm_sigma=0.0,
                    guided=False, split_beta=1.5, obs=None,
                    fleet=False) -> SimResult:
    if fleet:
        # delegate to the sharded walker fleet (tpuvsr/sim): guided
        # maps onto fingerprint-novelty importance splitting
        from ..sim import fleet_simulate
        return fleet_simulate(spec, num=num, depth=depth, seed=seed,
                              walkers=walkers, max_msgs=max_msgs,
                              chunk_steps=chunk_steps,
                              action_weights=action_weights,
                              swarm_sigma=swarm_sigma,
                              split=True if guided else None,
                              check_deadlock=check_deadlock, log=log,
                              max_seconds=max_seconds, obs=obs)
    sim = DeviceSimulator(spec, max_msgs=max_msgs, walkers=walkers,
                          chunk_steps=chunk_steps,
                          action_weights=action_weights,
                          swarm_sigma=swarm_sigma, guided=guided,
                          split_beta=split_beta)
    return sim.run(num=num, depth=depth, seed=seed,
                   check_deadlock=check_deadlock, log=log,
                   max_seconds=max_seconds, obs=obs)
