"""Device simulation mode: vectorized random walks (TLC's simulator,
README:22, rebuilt as a scan-based XLA program; BASELINE.json
configs[2]).

Semantics match TLC's SimulationWorker: each walk starts at the initial
state and repeatedly jumps to a successor chosen uniformly at random
from the full (action x binding) successor list — which is exactly the
kernel's lane space — checking invariants at every visited state, up to
a depth bound.  A walker with no enabled successor stays put (TLC ends
the walk; with -deadlock it is reported).

TPU structure (one host sync per CHUNK of steps, not per step):

* enabledness comes from the cheap guard pass over all lanes
  (vsr_kernel guard fns) — successors are never materialized for the
  draw;
* the drawn lane is applied with ``lax.switch`` over the 19 action
  bodies, one successor per walker;
* ``lax.scan`` advances all W walkers CHUNK steps inside one jit,
  recording (action id, lane param) histories as scan outputs that stay
  on device unless a violation needs replaying;
* on bag overflow the message table grows in place (zero padding
  changes no state content) and the chunk is re-run from its saved
  entry states — the walk segment is simply redrawn under the larger
  layout.

A violating walk replays its recorded (action, param) chain through the
materialize kernel into a full TRACE-format counterexample
(state_transfer_violation_trace.txt:3-7 format).
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from ..models.vsr import VSRCodec
from ..models.vsr_kernel import ACTION_NAMES, VSRKernel
from .device_bfs import _value_perm_table
from .simulate import SimResult
from .spec import SpecModel
from .trace import TraceEntry

I32 = jnp.int32


class DeviceSimulator:
    def __init__(self, spec: SpecModel, max_msgs=None, walkers=256,
                 chunk_steps=32):
        self.spec = spec
        self.W = walkers
        self.chunk = chunk_steps
        self.inv_names = list(spec.cfg.invariants)
        self._build(max_msgs)

    def _build(self, max_msgs):
        spec = self.spec
        self.codec = VSRCodec(spec.ev.constants, max_msgs=max_msgs)
        self.kern = VSRKernel(self.codec,
                              perms=_value_perm_table(spec, self.codec))
        inv = self.kern.invariant_fn(self.inv_names)
        kern = self.kern
        lane_aid = jnp.asarray(kern.lane_action)
        lane_prm = jnp.asarray(kern.lane_param)
        guards = kern._guard_fns()
        fns = kern._action_fns()

        def guard_all(st):
            outs = []
            for name, g in zip(ACTION_NAMES, guards):
                lanes = jnp.arange(kern._lane_count(name), dtype=I32)
                outs.append(jax.vmap(lambda ln, g=g: g(st, ln))(lanes))
            return jnp.concatenate(outs)

        def apply_chosen(states, aid, prm):
            """Per-walker successor for the chosen (action, param).

            Explicit compute-all-actions + mask-select.  A vmapped
            ``lax.switch`` lowers to the same all-branches select_n, but
            that lowering produced wrong bag contents on the TPU backend
            (headers lost while present/count landed — caught by the
            interpreter-confirmation check); the hand-rolled select is
            the same cost and lowers through plain jnp.where."""
            out = None
            for a, f in enumerate(fns):
                s_a, _en = jax.vmap(f, in_axes=(0, 0))(states, prm)
                m = aid == a
                if out is None:
                    out = {k: jnp.where(
                        m.reshape((-1,) + (1,) * (v.ndim - 1)), v, states[k])
                        for k, v in s_a.items() if not k.startswith("_")}
                else:
                    out = {k: jnp.where(
                        m.reshape((-1,) + (1,) * (s_a[k].ndim - 1)),
                        s_a[k], v) for k, v in out.items()}
            return out

        def chunk_fn(states, was_alive, keys):
            def step(carry, key):
                states, was_alive, bad, dead, err_any, steps, d = carry
                en = jax.vmap(guard_all)(states)          # [W, L]
                u = jax.random.uniform(key, en.shape)
                lane = jnp.argmax(jnp.where(en, u, -1.0), axis=1)
                alive = en.any(axis=1)
                aid = lane_aid[lane]
                prm = lane_prm[lane]
                succ = apply_chosen(states, aid, prm)
                sel = {k: alive.reshape((-1,) + (1,) * (v.ndim - 1))
                       for k, v in states.items()}
                states = {k: jnp.where(sel[k], succ[k], v)
                          for k, v in states.items()}
                err = alive & (succ["err"] != 0)
                iok = jax.vmap(inv)(succ)
                badw = alive & ~iok & ~err
                hit = badw.any() & (bad[0] < 0)
                bad = jnp.where(hit, jnp.stack(
                    [jnp.argmax(badw).astype(I32), d]), bad)
                dw = was_alive & ~alive
                hitd = dw.any() & (dead[0] < 0)
                dead = jnp.where(hitd, jnp.stack(
                    [jnp.argmax(dw).astype(I32), d]), dead)
                err_any = err_any | err.any()
                steps = steps + alive.sum()
                hist = (jnp.where(alive, aid, -1).astype(I32),
                        jnp.where(alive, prm, 0).astype(I32))
                return (states, alive, bad, dead, err_any, steps,
                        d + 1), hist

            init = (states, was_alive, jnp.full((2,), -1, I32),
                    jnp.full((2,), -1, I32), jnp.asarray(False),
                    jnp.asarray(0, I32), jnp.asarray(0, I32))
            (states, alive, bad, dead, err_any, steps, _d), hist = \
                jax.lax.scan(step, init, keys)
            return states, alive, bad, dead, err_any, steps, hist

        self._chunk = jax.jit(chunk_fn)
        self._mat = {}

    def _grow_msgs(self, batches):
        """Double MAX_MSGS and pad the given dense batches."""
        old = self.codec.shape.MAX_MSGS
        self._build(old * 2)
        return [self.codec.pad_msgs(b, old) for b in batches]

    def _materialize_one(self, st, aid, param):
        fn = self._mat.get(aid)
        if fn is None:
            fn = jax.jit(jax.vmap(self.kern._action_fns()[aid],
                                  in_axes=(0, 0)))
            self._mat[aid] = fn
        batch = {k: np.asarray(v)[None] for k, v in st.items()}
        succ, en = fn(batch, jnp.asarray([param], jnp.int32))
        assert bool(np.asarray(en)[0]), "replay chose a disabled lane"
        return {k: np.asarray(v)[0] for k, v in succ.items()
                if not k.startswith("_")}

    def run(self, num=1000, depth=100, seed=0, check_deadlock=False,
            log=None, max_seconds=None) -> SimResult:
        """Run `num` walks of `depth` steps (W at a time, `chunk` steps
        per device sync)."""
        spec, codec = self.spec, self.codec
        res = SimResult()
        t0 = time.time()
        init_dense = [codec.encode(st) for st in spec.init_states()]
        init = {k: np.repeat(np.stack([d[k] for d in init_dense])[:1],
                             self.W, axis=0) for k in init_dense[0]}
        bad0 = spec.check_invariants(
            codec.decode({k: np.asarray(v[0]) for k, v in init.items()}))
        if bad0:
            res.ok = False
            res.violated_invariant = bad0
            res.elapsed = time.time() - t0
            return res
        key = jax.random.PRNGKey(seed)
        init = {k: jnp.asarray(v) for k, v in init.items()}
        stop = False
        while res.walks < num and not stop:
            states = init
            was_alive = jnp.ones((self.W,), bool)
            hists = []          # [(ha [k, W], hp [k, W])] device arrays
            d = 0
            while d < depth:
                k = min(self.chunk, depth - d)
                key, sub = jax.random.split(key)
                keys = jax.random.split(sub, k)
                while True:
                    (nstates, alive, bad, dead, err_any, steps,
                     hist) = self._chunk(states, was_alive, keys)
                    if bool(err_any):
                        # bag overflow inside the chunk: grow the table,
                        # pad saved entry states, redraw the chunk
                        init, states = self._grow_msgs([init, states])
                        if log:
                            log(f"message table grown to "
                                f"{self.codec.shape.MAX_MSGS} slots")
                        continue
                    break
                hists.append(hist)
                res.steps += int(steps)
                bad = np.asarray(bad)
                dead = np.asarray(dead)
                # report whichever event happened at the earlier step of
                # the chunk; within one step deadlocks are checked first
                # (matching the per-step engine semantics)
                dead_first = (check_deadlock and dead[0] >= 0
                              and (bad[0] < 0 or dead[1] <= bad[1]))
                if dead_first:
                    w, ds = int(dead[0]), int(dead[1])
                    res.ok = False
                    res.deadlocks += 1
                    res.trace = self._replay(init, hists, w, d + ds)
                    res.violated_invariant = None
                    res.elapsed = time.time() - t0
                    return res
                if bad[0] >= 0:
                    w, ds = int(bad[0]), int(bad[1])
                    res.ok = False
                    res.trace = self._replay(init, hists, w, d + ds + 1)
                    confirmed = spec.check_invariants(res.trace[-1].state)
                    if confirmed is None:
                        # The device invariant kernel flagged a state the
                        # interpreter (the semantic oracle) accepts: an
                        # engine bug, never a spec violation — fail loudly
                        # rather than emit a bogus counterexample.
                        from ..core.values import TLAError
                        err = TLAError(
                            "device/interpreter divergence: device "
                            "invariant kernel reported a violation at "
                            f"walker {w} depth {d + ds + 1}, but the "
                            "interpreter accepts the replayed state")
                        err.trace = res.trace
                        raise err
                    res.violated_invariant = confirmed
                    res.elapsed = time.time() - t0
                    return res
                states, was_alive = nstates, alive
                d += k
                if max_seconds and time.time() - t0 > max_seconds:
                    stop = True
                    break
            res.walks += self.W
            if log:
                el = time.time() - t0
                log(f"{res.walks} walks, {res.steps / el:.0f} steps/s")
        res.elapsed = time.time() - t0
        return res

    def _replay(self, init, hists, w, n_steps):
        """Re-execute walker `w`'s first `n_steps` recorded choices into
        a TRACE-format counterexample."""
        aids = np.concatenate([np.asarray(ha)[:, w] for ha, _hp in hists])
        prms = np.concatenate([np.asarray(hp)[:, w] for _ha, hp in hists])
        st = {k: np.asarray(v[w]) for k, v in init.items()}
        loc = {a.name: a.location for a in self.spec.actions}
        out = [TraceEntry(position=1, action_name=None, location=None,
                          state=self.codec.decode(st))]
        for i in range(min(n_steps, len(aids))):
            if aids[i] < 0:
                break
            st = self._materialize_one(st, int(aids[i]), int(prms[i]))
            name = ACTION_NAMES[aids[i]]
            out.append(TraceEntry(position=i + 2, action_name=name,
                                  location=loc.get(name),
                                  state=self.codec.decode(st)))
        return out


def device_simulate(spec: SpecModel, num=1000, depth=100, seed=0,
                    walkers=256, max_msgs=None, check_deadlock=False,
                    log=None, max_seconds=None, chunk_steps=32) -> SimResult:
    sim = DeviceSimulator(spec, max_msgs=max_msgs, walkers=walkers,
                          chunk_steps=chunk_steps)
    return sim.run(num=num, depth=depth, seed=seed,
                   check_deadlock=check_deadlock, log=log,
                   max_seconds=max_seconds)
