"""Breadth-first exhaustive model checking (interpreter backend).

The reference's runtime is TLC's BFS worker loop (SURVEY.md §3.1):
dequeue -> enumerate successors over every Next disjunct -> invariant
check -> VIEW projection -> symmetry canonicalization -> fingerprint
dedup -> enqueue, with parent pointers for trace reconstruction.  This
module is the faithful single-host implementation used as the oracle for
the TPU engine; states are deduplicated on the exact canonical view value
(collision-free, unlike TLC's 64-bit fingerprints).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..core.values import TLAError
from ..resilience.faults import fault_point
from .spec import SpecModel
from .trace import TraceEntry, reconstruct_trace


@dataclass
class CheckResult:
    ok: bool = True
    distinct_states: int = 0
    states_generated: int = 0
    diameter: int = 0
    violated_invariant: str = None
    deadlock_state: dict = None
    trace: list = field(default_factory=list)
    # timing/trajectory fields set uniformly by RunObserver.finish —
    # engines never patch them post hoc (ISSUE 2 satellite)
    elapsed: float = 0.0
    states_per_sec: float = 0.0
    levels: list = None       # per-level frontier sizes, init included
    metrics: dict = None      # tpuvsr-metrics/1 document for this run
    error: str = None
    exchange: dict = None     # sharded-engine ICI exchange metrics


def bfs_check(spec: SpecModel, check_deadlock: bool = False,
              max_states: int = None, progress_every: float = 10.0,
              log=None, obs=None) -> CheckResult:
    from ..analysis import preflight
    from ..obs import RunObserver
    preflight(spec, log=log)      # speclint gate (TPUVSR_LINT=off skips)
    obs = RunObserver.ensure(obs, "interp", spec, log=log,
                             progress_every=progress_every)
    res = CheckResult()
    t0 = time.time()
    obs.start(t0, backend="host")
    seen = {}           # canonical view value -> state id
    parents = {}        # state id -> (parent id, action name, action location)
    states = []         # state id -> state dict (kept for trace replay)
    frontier = []
    level_sizes = []

    def finish(depth):
        res.distinct_states = len(states)
        res.diameter = depth
        return obs.finish(res, levels=level_sizes)

    def register(state, parent_id, action):
        key = spec.view_value(state)
        sid = seen.get(key)
        if sid is None:
            sid = len(states)
            seen[key] = sid
            states.append(state)
            parents[sid] = (parent_id, action.name if action else None,
                            action.location if action else None)
            return sid, True
        return sid, False

    depth = 0
    try:
        for st in spec.init_states():
            res.states_generated += 1
            sid, fresh = register(st, None, None)
            if fresh:
                bad = spec.check_invariants(st)
                if bad:
                    res.ok = False
                    res.violated_invariant = bad
                    res.trace = reconstruct_trace(sid, parents, states)
                    return finish(depth)
                frontier.append(sid)
        level_sizes.append(len(frontier))

        while frontier:
            depth += 1
            fault_point("level", depth=depth, obs=obs)
            next_frontier = []
            with obs.annotate(f"level {depth}"):
                for sid in frontier:
                    state = states[sid]
                    n_succ = 0
                    for action, succ in spec.successors(state):
                        n_succ += 1
                        res.states_generated += 1
                        tid, fresh = register(succ, sid, action)
                        if fresh:
                            bad = spec.check_invariants(succ)
                            if bad:
                                res.ok = False
                                res.violated_invariant = bad
                                res.trace = reconstruct_trace(
                                    tid, parents, states)
                                return finish(depth)
                            next_frontier.append(tid)
                    if n_succ == 0 and check_deadlock:
                        res.ok = False
                        res.error = "deadlock"
                        res.deadlock_state = state
                        res.trace = reconstruct_trace(
                            sid, parents, states)
                        return finish(depth)
                    if max_states and len(states) >= max_states:
                        res.error = f"state limit {max_states} reached"
                        return finish(depth)
                    obs.progress(depth=depth, distinct=len(states),
                                 generated=res.states_generated)
            if next_frontier:
                level_sizes.append(len(next_frontier))
            obs.level_done(depth, frontier=len(frontier),
                           distinct=len(states),
                           generated=res.states_generated)
            frontier = next_frontier
    except TLAError as e:
        res.ok = False
        res.error = str(e)
    return finish(depth)
