"""Breadth-first exhaustive model checking (interpreter backend).

The reference's runtime is TLC's BFS worker loop (SURVEY.md §3.1):
dequeue -> enumerate successors over every Next disjunct -> invariant
check -> VIEW projection -> symmetry canonicalization -> fingerprint
dedup -> enqueue, with parent pointers for trace reconstruction.  This
module is the faithful single-host implementation used as the oracle for
the TPU engine; states are deduplicated on the exact canonical view value
(collision-free, unlike TLC's 64-bit fingerprints).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..core.values import TLAError
from .spec import SpecModel
from .trace import TraceEntry, reconstruct_trace


@dataclass
class CheckResult:
    ok: bool = True
    distinct_states: int = 0
    states_generated: int = 0
    diameter: int = 0
    violated_invariant: str = None
    deadlock_state: dict = None
    trace: list = field(default_factory=list)
    elapsed: float = 0.0
    error: str = None
    exchange: dict = None     # sharded-engine ICI exchange metrics

    @property
    def states_per_sec(self):
        return self.states_generated / self.elapsed if self.elapsed > 0 else 0.0


def bfs_check(spec: SpecModel, check_deadlock: bool = False,
              max_states: int = None, progress_every: float = 10.0,
              log=None) -> CheckResult:
    from ..analysis import preflight
    preflight(spec, log=log)      # speclint gate (TPUVSR_LINT=off skips)
    res = CheckResult()
    t0 = time.time()
    seen = {}           # canonical view value -> state id
    parents = {}        # state id -> (parent id, action name, action location)
    states = []         # state id -> state dict (kept for trace replay)
    frontier = []

    def emit(msg):
        if log:
            log(msg)

    def register(state, parent_id, action):
        key = spec.view_value(state)
        sid = seen.get(key)
        if sid is None:
            sid = len(states)
            seen[key] = sid
            states.append(state)
            parents[sid] = (parent_id, action.name if action else None,
                            action.location if action else None)
            return sid, True
        return sid, False

    try:
        for st in spec.init_states():
            res.states_generated += 1
            sid, fresh = register(st, None, None)
            if fresh:
                bad = spec.check_invariants(st)
                if bad:
                    res.ok = False
                    res.violated_invariant = bad
                    res.trace = reconstruct_trace(sid, parents, states)
                    res.distinct_states = len(states)
                    res.elapsed = time.time() - t0
                    return res
                frontier.append(sid)

        depth = 0
        last_progress = t0
        while frontier:
            depth += 1
            next_frontier = []
            for sid in frontier:
                state = states[sid]
                n_succ = 0
                for action, succ in spec.successors(state):
                    n_succ += 1
                    res.states_generated += 1
                    tid, fresh = register(succ, sid, action)
                    if fresh:
                        bad = spec.check_invariants(succ)
                        if bad:
                            res.ok = False
                            res.violated_invariant = bad
                            res.trace = reconstruct_trace(tid, parents, states)
                            res.distinct_states = len(states)
                            res.diameter = depth
                            res.elapsed = time.time() - t0
                            return res
                        next_frontier.append(tid)
                if n_succ == 0 and check_deadlock:
                    res.ok = False
                    res.error = "deadlock"
                    res.deadlock_state = state
                    res.trace = reconstruct_trace(sid, parents, states)
                    res.distinct_states = len(states)
                    res.diameter = depth
                    res.elapsed = time.time() - t0
                    return res
                if max_states and len(states) >= max_states:
                    res.error = f"state limit {max_states} reached"
                    res.distinct_states = len(states)
                    res.diameter = depth
                    res.elapsed = time.time() - t0
                    return res
                now = time.time()
                if now - last_progress >= progress_every:
                    last_progress = now
                    emit(f"depth {depth}: {len(states)} distinct, "
                         f"{res.states_generated} generated, "
                         f"{res.states_generated / (now - t0):.0f} states/s")
            frontier = next_frontier
        res.diameter = depth
    except TLAError as e:
        res.ok = False
        res.error = str(e)
    res.distinct_states = len(states)
    res.elapsed = time.time() - t0
    return res
