"""Simulation mode: depth-bounded random walks (interpreter backend).

The reference prescribes simulation as the practical route to the deep
state-transfer violation (README:22, SURVEY.md §3.5): random walks of
TLC-default depth 100, evaluating invariants at every visited state, no
fingerprint set or queue.  The TPU engine vectorizes this embarrassingly
parallel loop; this host implementation is its semantic oracle and the
fallback for specs not yet lowered.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field

from ..core.values import TLAError
from .spec import SpecModel
from .trace import TraceEntry


@dataclass
class SimResult:
    ok: bool = True
    walks: int = 0
    steps: int = 0
    violated_invariant: str = None
    trace: list = field(default_factory=list)
    elapsed: float = 0.0
    deadlocks: int = 0
    metrics: dict = None      # tpuvsr-metrics/1 document for this run
    walkers: int = 0          # fleet size of the run (tpuvsr/sim)
    violations: list = None   # hunt mode: unique-violation records

    @property
    def steps_per_sec(self):
        return self.steps / self.elapsed if self.elapsed > 0 else 0.0

    @property
    def walks_per_sec(self):
        return self.walks / self.elapsed if self.elapsed > 0 else 0.0


def simulate(spec: SpecModel, num: int = 100, depth: int = 100,
             seed: int = 0, check_deadlock: bool = False,
             log=None, time_budget: float = None, obs=None) -> SimResult:
    from ..obs import RunObserver
    obs = RunObserver.ensure(obs, "interp-sim", spec, log=log)
    rng = random.Random(seed)
    res = SimResult()
    t0 = time.time()
    obs.start(t0, backend="host")
    inits = list(spec.init_states())
    for w in range(num):
        res.walks = w + 1
        state = rng.choice(inits)
        walk = [(None, state)]
        bad = spec.check_invariants(state)
        for _d in range(depth):
            succs = list(spec.successors(state))
            if not succs:
                if check_deadlock:
                    res.ok = False
                res.deadlocks += 1
                break
            action, state = rng.choice(succs)
            walk.append((action, state))
            res.steps += 1
            bad = spec.check_invariants(state)
            if bad:
                break
        if bad:
            res.ok = False
            res.violated_invariant = bad
            res.trace = [
                TraceEntry(position=i + 1,
                           action_name=a.name if a else None,
                           location=a.location if a else None,
                           state=s)
                for i, (a, s) in enumerate(walk)]
            break
        if (w + 1) % 10 == 0:
            obs.progress(walks=res.walks, steps=res.steps)
        if time_budget and time.time() - t0 > time_budget:
            break
    return obs.finish(res)
