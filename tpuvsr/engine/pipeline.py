"""Pipelined dispatch window shared by the BFS engines (ISSUE 4).

The synchronous engine loops ran ``dispatch -> block_until_ready ->
device_get(control scalars) -> handle`` — the device idled through a
full host round-trip (plus journal/metrics/spill bookkeeping) after
EVERY level-kernel dispatch, which on a tunneled TPU is most of the
runtime (BENCH_r05: ~1,348 distinct/s shipped-pin, ~917 distinct/s on
the RR05 deep run, both host-sync bound).  This module keeps a bounded
window of K dispatches in flight instead:

* **launch** enqueues a dispatch and returns its (asynchronous) output
  structure immediately; the engine chains the control scalars the
  next dispatch needs (``start_t`` / ``nn`` for the level kernel)
  straight off that structure as DEVICE arrays, so filling the window
  costs zero host syncs;
* **collect** blocks on the OLDEST in-flight dispatch only, then pulls
  its control scalars; the host handles them (journal, metrics,
  growth decisions, spill compaction, checkpoint staging) while the
  K-1 newer dispatches keep the device busy;
* **drain** discards every still-in-flight ticket without accumulating
  its deltas.  That is SAFE and exact because of the level kernel's
  pause protocol: a dispatch chained after a paused one re-attempts
  the same tile, commits nothing, and re-fails identically (committed
  lanes dedup against the FPSet), and a dispatch chained after the
  level's last tile is an empty while_loop that passes its buffers
  through untouched.  So the tickets behind a pause, a stop, or a
  level end are replays/no-ops whose host-visible deltas must NOT be
  double-counted — dropping them keeps counts, level sizes and traces
  bit-identical to the synchronous (K=1) path.

Window semantics: ``window=1`` reproduces today's behavior exactly,
including the phase accounting (the dispatch blocks inside the
``dispatch``/``compile`` timer, the scalar pull inside ``host_sync``).
With ``window>1`` the enqueue cost lands in ``dispatch``/``compile``,
the blocking wait on the oldest ticket in a new ``inflight`` phase,
and the scalar pull in ``host_sync`` — the phases stay disjoint and
still sum to the run's wall-clock (tpuvsr/obs/SCHEMA.md).  The
``overlap_saved_s`` gauge reports host time spent OUTSIDE pipeline
calls while at least one dispatch was in flight — the work the window
actually hid behind device compute.

Drained-but-unconsumed replay dispatches still run on device (they
were already enqueued); their FPSet inserts are idempotent, so only
the end-of-run occupancy gauge can read marginally high after a
time-budget stop.  Engines drain the window at every level boundary,
so rescue checkpoints (resilience supervisor / PreemptionGuard) never
race an in-flight dispatch.
"""

from __future__ import annotations

import time
from collections import deque


class DispatchPipeline:
    """A bounded window of in-flight jitted dispatches.

    ``ready(out)`` must return a device array of the dispatch output to
    block on (the control-scalar leaf every engine already syncs on).
    One instance rides one engine run; the window gauges
    (``pipeline_depth``, ``overlap_saved_s``) are stamped on the
    observer incrementally, so every engine return path sees them.
    """

    def __init__(self, window, obs, ready):
        self.window = max(1, int(window))
        self.obs = obs
        self._ready = ready
        self._q = deque()            # (out, enqueue perf_counter)
        self._overlap = 0.0          # host-work seconds hidden by the window
        self._free_since = None      # host running free with work in flight
        # gauges are stamped incrementally (last-write-wins) so the
        # run's metrics document carries them no matter which engine
        # return path finalizes the observer first
        obs.gauge("pipeline_depth", self.window)

    @property
    def in_flight(self):
        return len(self._q)

    def has_room(self):
        return len(self._q) < self.window

    def launch(self, fn, *args, fresh=False, label=""):
        """Enqueue ``fn(*args)``; returns the (async) output structure.

        The first dispatch after a (re)jit compiles synchronously at
        call time and is charged to the ``compile`` phase; at window 1
        the dispatch also blocks to completion here (synchronous-path
        parity)."""
        obs = self.obs
        # host work done since the last pipeline call counts as
        # overlapped when something was in flight through it (the
        # collect->handle->launch span is where the hidden work lives)
        self._credit_overlap()
        with obs.timer("compile" if fresh else "dispatch"), \
                obs.annotate(label):
            out = fn(*args)
            if self.window == 1:
                self._ready(out).block_until_ready()
        obs.count("dispatches")
        self._q.append((out, time.perf_counter()))
        self._free_since = time.perf_counter()
        return out

    def _credit_overlap(self):
        if self._free_since is None:
            return
        self._overlap += time.perf_counter() - self._free_since
        self._free_since = None
        if self.window > 1:
            self.obs.gauge("overlap_saved_s", round(self._overlap, 6))

    def collect(self, pull):
        """Block on the OLDEST in-flight dispatch, pull its control
        scalars with ``pull(out)``, and return ``(out, scalars)``."""
        out, _t_push = self._q.popleft()
        obs = self.obs
        self._credit_overlap()
        if self.window > 1:
            with obs.timer("inflight"):
                self._ready(out).block_until_ready()
        with obs.timer("host_sync"):
            sc = pull(out)
        if self._q:
            self._free_since = time.perf_counter()
        return out, sc

    def drain(self):
        """Discard every still-in-flight ticket (see module docstring:
        everything behind a pause, stop, or level end is a replay/no-op
        whose deltas must not be re-counted).  Returns the number of
        tickets dropped."""
        n = len(self._q)
        if n:
            self.obs.count("pipeline_replays", n)
            self._q.clear()
        self._free_since = None
        return n
