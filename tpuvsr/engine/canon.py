"""Device-native symmetry reduction: orbit-canonical state images
(ISSUE 11 tentpole).

TLC's SYMMETRY optimization — the single biggest algorithmic lever on
the reference corpus (VSR.cfg ``Permutations``, PAPER.md capability
#4) — stores one fingerprint per symmetry ORBIT instead of one per
state: before fingerprinting, a state is mapped to the least element
of its orbit under the cfg-declared permutation group, so every
orbit-mate dedups against the same FPSet entry and the reachable set
shrinks by up to |group| (6x at ``|Values| = 3``).  The host
interpreter has always done this (``spec.py:view_value`` takes the
min permuted image over ``value_key`` order); this module is the
device-side seam: a vmapped, jittable canonicalization kernel every
engine applies PRE-FINGERPRINT inside its jitted level/step/chunk
pass — no host round-trip per state.

Semantics (exactly TLC's): the canonical image is only used to
COMPUTE the fingerprint.  The frontier keeps the actually-generated
successor (one representative per orbit — the first one committed),
so trace replay walks real reachable states and counterexamples stay
valid; verdicts are orbit-level and engine-independent (the
federated-dispatch framing of arxiv 2606.02019 is why they must be).
Soundness requires the evaluated permutation set plus identity to be
a CLOSED group (orbit-mates must produce the same image set — TLC's
``Permutations(S)`` always is); ``group_table`` enforces it here and
the speclint symmetry pass (pass 4) reports it statically.

The permutation action on an encoded SoA state row is pure value-id
relabeling: the corpus's symmetric sets are model-value universes
whose ids live in specific planes (or plane columns) of the dense
layout.  Which planes, and how a permutation reaches them, is the
kernel's knowledge:

* kernels with a ``_permuted(st, perm)`` method (the whole registry
  family — it already backs their symmetry-folded hashing) supply the
  action directly, packed-entry encodings included;
* simpler layouts declare a ``SYM_PLANES`` table
  (``{plane: "all" | ("col", i)}``) and the generic table action
  applies ``perm[...]`` to the named planes/columns.

``orbit_planes`` derives the plane -> orbit table from those same
class attributes — it is what the speclint pass EMITS and what this
kernel CONSUMES, so lint and kernel can never disagree (ISSUE 11
satellite).

The minimization itself is a small sort-network over the group: the
(identity-first) ``[P, V+1]`` id table is enumerated per lane, each
image keyed by its flattened symmetric planes, and the lexicographic
least image wins — P is tiny (|Values|! <= 6 on the defect fixture),
so the whole pass is a handful of gathers and compares per state.
"""

from __future__ import annotations

import hashlib
import json

import numpy as np

import jax.numpy as jnp

from ..core.values import TLAError


def kernel_fold_order(kern):
    """Group order a kernel's OWN fingerprint already folds over (the
    pre-ISSUE-11 style: registry kernels built with a multi-row perm
    table take the min over P hashes).  1 = unfolded — the engines'
    expected shape, where the CanonSpec owns the reduction."""
    perms = getattr(kern, "perms", None)
    if perms is None:
        return 1
    return int(np.asarray(perms).shape[0])


def orbit_planes(kern):
    """The plane -> orbit-action table for a kernel (class or
    instance): which planes of the encoded layout a value permutation
    touches, and how.  ``{plane: "all"}`` remaps every lane of the
    plane through the id table; ``{plane: ("col", i)}`` remaps column
    ``i`` of the plane's last axis.  Derived from ``SYM_PLANES`` when
    declared, else from the registry family's ``PERM_REP_KEYS`` /
    ``PERM_MSG_KEYS``; None when the kernel declares nothing (no
    device canonicalization possible).  The speclint symmetry pass
    emits exactly this table, so lint and kernel share one source."""
    sp = getattr(kern, "SYM_PLANES", None)
    if sp:
        return dict(sp)
    rep = tuple(getattr(kern, "PERM_REP_KEYS", ()) or ())
    msg = tuple(getattr(kern, "PERM_MSG_KEYS", ()) or ())
    if not rep and not msg:
        return None
    return {k: "all" for k in rep + msg}


def group_closed(perms):
    """True iff {identity} + perms is closed under composition (each
    perm is a dict ModelValue -> ModelValue; identity pairs dropped).
    Orbit canonicalization by min-over-enumerated-perms is only
    orbit-invariant for a closed group — the same precondition the
    host interpreter's ``view_value`` min has always had."""
    frozen = {frozenset(p.items()) for p in perms}
    frozen.add(frozenset())
    for p in perms:
        for q in perms:
            comp = {}
            keys = set(p) | set(q)
            for k in keys:
                v = p.get(q.get(k, k), q.get(k, k))
                if v is not k:
                    comp[k] = v
            if frozenset(comp.items()) not in frozen:
                return False
    return True


def group_table(spec, codec):
    """The evaluated SYMMETRY group as an identity-first ``[P, V+1]``
    value-id table (registry.value_perm_table), with the closure
    precondition enforced loudly — lint reports it statically, but
    canonicalization soundness must not depend on the lint gate being
    armed (TPUVSR_LINT=off exists)."""
    from ..models.registry import value_perm_table
    if not group_closed(spec.symmetry_perms):
        raise TLAError(
            "SYMMETRY permutation set is not closed under composition "
            "(plus identity): orbit canonicalization would be "
            "orbit-dependent and the checker would under- or "
            "over-merge states.  TLC's Permutations(S) is always "
            "closed; hand-written SYMMETRY sets must be too")
    return value_perm_table(spec, codec)


def _lex_less(a, b):
    """Lexicographic a < b over two equal-length uint32 key vectors:
    find the first differing lane, compare there."""
    neq = a != b
    i = jnp.argmax(neq)
    return neq.any() & (a[i] < b[i])


class CanonSpec:
    """The canonicalization kernel for one (spec, codec, kernel)
    binding: ``canonicalize`` maps one dense SoA state row to the
    lexicographic least element of its symmetry orbit.  Pure jnp —
    jit/vmap composable, so the engines run it INSIDE their jitted
    level kernels (the acceptance criterion: no host round-trip per
    state)."""

    def __init__(self, group, planes, kern=None):
        self.group = np.asarray(group, np.int32)     # [P, V+1], id 1st
        self.planes = dict(planes)
        self.kern = kern
        self._jgroup = jnp.asarray(self.group)
        payload = json.dumps(
            {"group": self.group.tolist(),
             "planes": {k: list(v) if isinstance(v, tuple) else v
                        for k, v in sorted(self.planes.items())}},
            sort_keys=True)
        #: digest of (group table, orbit plane table) — the snapshot
        #: compatibility key (ISSUE 11 satellite: resuming under a
        #: changed group/table is a policy error)
        self.version = "canon/1:" + hashlib.sha256(
            payload.encode()).hexdigest()[:16]

    @property
    def perms(self):
        """Group order, identity included."""
        return int(self.group.shape[0])

    def manifest(self):
        """Checkpoint-manifest record of this canonicalization spec."""
        return {"version": self.version, "perms": self.perms,
                "planes": sorted(self.planes)}

    # ------------------------------------------------------------------
    def _apply(self, st, perm):
        """One permutation's action on one dense state row.  Prefers
        the kernel's own ``_permuted`` (packed-entry layouts override
        it); falls back to the declarative SYM_PLANES table action."""
        if self.kern is not None and hasattr(self.kern, "_permuted"):
            return self.kern._permuted(st, perm)
        out = dict(st)
        for k, how in self.planes.items():
            v = jnp.asarray(st[k])
            if how == "all":
                out[k] = perm[v].astype(v.dtype)
            else:
                col = int(how[1])
                out[k] = v.at[..., col].set(
                    perm[v[..., col]].astype(v.dtype))
        return out

    def _key(self, st):
        """The comparison key of one image: the flattened symmetric
        planes (untouched planes are identical across all images of a
        state — and across orbit-mates — so they never discriminate)."""
        return jnp.concatenate(
            [jnp.asarray(st[k], jnp.uint32).reshape(-1)
             for k in sorted(self.planes)])

    def canonicalize(self, st):
        """One dense state row -> the least element of its orbit (a
        small sort-network fold over the enumerated group)."""
        if self.perms == 1:
            return st
        best = self._apply(st, self._jgroup[0])      # identity image
        bkey = self._key(best)
        for p in range(1, self.perms):
            cand = self._apply(st, self._jgroup[p])
            ckey = self._key(cand)
            less = _lex_less(ckey, bkey)
            bkey = jnp.where(less, ckey, bkey)
            best = {k: jnp.where(less, cand[k], best[k]) for k in best}
        return best

    def fingerprint_fn(self, kern):
        """``st -> kern.fingerprint(canonicalize(st))`` — the one
        pre-fingerprint seam every engine hooks (fused/chunked commit
        stage 3, the paged insert path, the sharded pre-bucketing
        step, the fleet novelty set)."""
        return lambda st: kern.fingerprint(self.canonicalize(st))


def build_canon_spec(spec, codec, kern, symmetry="auto"):
    """Resolve the engine-level ``symmetry`` switch into a CanonSpec
    (or None).

    ``"auto"`` (every engine's default): canonicalize iff the cfg
    declares SYMMETRY — mirroring TLC, where declaring Permutations IS
    turning the optimization on.  ``True`` insists (a cfg without
    SYMMETRY is a loud error — there is no group to reduce by);
    ``False`` disables reduction entirely (the A/B leg: the engines
    then run identity-only fingerprints and store every orbit member).
    """
    enabled = (bool(spec.symmetry_perms) if symmetry == "auto"
               else bool(symmetry))
    if not enabled:
        return None
    if not spec.symmetry_perms:
        raise TLAError(
            "symmetry canonicalization requested (-symmetry on) but "
            "the cfg declares no SYMMETRY — there is no permutation "
            "group to reduce by")
    planes = orbit_planes(kern)
    if planes is None:
        raise TLAError(
            f"kernel {type(kern).__name__} declares no orbit plane "
            f"table (SYM_PLANES or PERM_REP_KEYS/PERM_MSG_KEYS): the "
            f"device canonicalization pass cannot know which planes "
            f"a value permutation touches.  Run -symmetry off or add "
            f"the table")
    missing = [k for k in planes if k not in codec.zero_state()]
    if missing:
        raise TLAError(
            f"orbit plane table names planes {missing} the codec "
            f"layout does not declare (lint/kernel drift)")
    return CanonSpec(group_table(spec, codec), planes, kern=kern)
