"""Counterexample trace reconstruction and TLC-compatible printing.

Reproduces the artifact format of the recorded violation trace
(state_transfer_violation_trace.txt): per-state ``_TEAction`` records
with position / action name / source location, followed by the full
variable assignment in TLC syntax.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..core.values import fmt


@dataclass
class TraceEntry:
    position: int          # 1-based
    action_name: str       # None for the initial state
    location: str
    state: dict


def reconstruct_trace(sid, parents, states):
    chain = []
    cur = sid
    while cur is not None:
        parent, aname, aloc = parents[cur]
        chain.append((cur, aname, aloc))
        cur = parent
    chain.reverse()
    out = []
    for i, (s, aname, aloc) in enumerate(chain):
        out.append(TraceEntry(position=i + 1, action_name=aname,
                              location=aloc, state=states[s]))
    return out


def trace_to_jsonable(trace):
    """Serialize a trace for job-result records — the ONE stable form
    every service/hunt bit-identity check compares (two runs are
    equivalent iff these lists are equal).  Shared by the dispatch
    worker and the fleet hunt; jax-free, so the service's fast verbs
    keep their no-jax import property."""
    out = []
    for e in trace:
        out.append({"position": int(e.position),
                    "action": e.action_name,
                    "state": {k: fmt(v)
                              for k, v in sorted(e.state.items())}})
    return out


def format_trace_te(trace, varnames=None) -> str:
    """Emit a trace in the reference's ``_TEAction`` record format
    (state_transfer_violation_trace.txt:3-26) — the format
    frontend.trace_parse reads back, so recorded counterexamples become
    replayable golden artifacts."""
    blocks = []
    for e in trace:
        name = e.action_name or "Initial predicate"
        loc = e.location or "Unknown location"
        lines = ["[", " _TEAction |-> [",
                 f"   position |-> {e.position},",
                 f'   name |-> "{name}",',
                 f'   location |-> "{loc}"', " ],"]
        names = varnames or sorted(e.state)
        lines.append(",\n".join(f"{n} |-> {fmt(e.state[n])}"
                                for n in names))
        lines.append("]")
        blocks.append("\n".join(lines))
    return "<<\n" + ",\n".join(blocks) + "\n>>\n"


def format_trace(trace, varnames=None) -> str:
    lines = []
    for e in trace:
        if e.action_name is None:
            header = f"State {e.position}: <Initial predicate>"
        else:
            header = (f"State {e.position}: "
                      f"<{e.action_name} {e.location or ''}>".rstrip() + ">")
            header = header.replace(">>", ">")
        lines.append(header)
        names = varnames or sorted(e.state)
        lines.append("/\\ " + "\n/\\ ".join(
            f"{n} = {fmt(e.state[n])}" for n in names))
        lines.append("")
    return "\n".join(lines)
