"""Device-driven behavior-graph construction for liveness checking.

STREAMED single pass (ISSUE 15, the default): the behavior graph flows
OUT of the safety BFS itself.  The fused commit's stage 3 already
holds (source gid, action id, successor fingerprint) for every enabled
lane, fresh *and* duplicate — the edge-emission mode
(``PagedBFS(edges=True)``) resolves those fingerprints to gids on
device through the gid-valued FPSet (``fpset.store_gids`` /
``lookup_gids``, the duplicate hit returning the stored winner's gid)
and appends (src gid, action, dst gid) triples to a device append
buffer, drained into the incremental host CSR builder
(``engine/spill.EdgeCSR``, with a disk tier for graphs past the RAM
budget) at chunk boundaries.  Graph construction cost beyond the
safety BFS collapses to the drains plus one CSR assembly — the
``graph_overhead_ratio`` gauge — instead of a second full expansion
of every retained level (BENCH_r05 `i01-v2t1`: 4,063 s of re-expansion
vs 2,872 s of BFS; the Trifecta paper, arxiv 2211.07216, frames
exactly this TLC bottleneck).

TWO-PASS (``mode="two-pass"``, kept as the bit-identity oracle the
streamed path is checked against, and the A/B leg of
``scripts/liveness_speedup.py``):

  pass 1  enumerate all reachable states with the paged BFS engine
          (``PagedBFS(retain_levels=True)``);
  pass 2  re-expand every level tile-by-tile through a jitted EDGE
          pass — the level kernel's guard + compaction + incremental-
          fingerprint phases, minus FPSet insert/scatter — resolving
          successor fingerprints through a separately built gid FPSet.

The two paths produce the SAME CSR modulo edge order within one
source's segment (both preserve commit order per source; the streamed
path interleaves actions per tile where the re-expansion batches by
action), identical verdicts and identical cycle traces — asserted by
``tests/test_device_liveness.py``.

Both modes retain the dense level blocks (``retain_levels=True``) —
property-leaf predicates evaluate on device over whole blocks, and
lasso traces decode states lazily.  Edge rows, the gid column and the
retained blocks all ride the rescue-checkpoint seam, so a SIGTERM'd
temporal run resumes to a bit-identical CSR and verdict.

The graph object plugs into ``liveness_check(spec, graph=...)``
unchanged: it quacks like the (states, edges, inits) triple via
``states`` (lazy decode), ``edges`` and ``inits`` attributes.

Liveness requires SYMMETRY off (A01 cfg:22-24), which also makes the
device fingerprint exact VIEW identity (single permutation); 128-bit
fingerprint collisions are the same vanishing risk the BFS engine
accepts (fpset.py docstring).
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from ..core.values import TLAError
from ..models.vsr import ERR_BAG_OVERFLOW
from .paged_bfs import PagedBFS

I32 = jnp.int32


class _LazyStates:
    """List-like view of the graph's states: decodes dense rows on
    demand and memoizes (property evaluation touches every state once;
    trace reconstruction a handful more)."""

    def __init__(self, graph):
        self.g = graph
        self._cache = {}

    def __len__(self):
        return self.g.n

    def __getitem__(self, sid):
        st = self._cache.get(sid)
        if st is None:
            st = self.g.codec.decode(self.g.dense_row(sid))
            self._cache[sid] = st
        return st


class DeviceGraph:
    """Behavior graph built by the device engines (states, edges,
    inits), with batched device predicate evaluation where possible."""

    def __init__(self, spec, tile_size=64, chunk_tiles=16,
                 max_states=None, log=None, engine=None, result=None,
                 mode="stream", edge_spill_dir=None,
                 checkpoint_path=None, checkpoint_every=None,
                 resume_from=None, obs=None, **eng_kwargs):
        """Pass a finished ``engine`` (a PagedBFS constructed with
        retain_levels=True whose run() returned ``result``) to reuse an
        enumeration that already happened — e.g. the CLI's safety BFS —
        instead of re-running it; a reused engine that ran with
        ``edges=True`` hands over its streamed CSR directly.

        ``mode`` picks the construction path: ``"stream"`` (default —
        the single-pass ISSUE 15 architecture) or ``"two-pass"`` (the
        historical retained-levels + re-expansion body, kept as the
        bit-identity oracle)."""
        if spec.symmetry_perms:
            raise TLAError("liveness checking requires SYMMETRY off "
                           "(reference cfg guidance, A01 cfg:22-24)")
        if mode not in ("stream", "two-pass"):
            raise ValueError(f"mode must be 'stream' or 'two-pass' "
                             f"(got {mode!r})")
        self.spec = spec
        t0 = time.time()
        if engine is not None:
            if result is None or not engine.retain_levels:
                raise ValueError("engine reuse needs retain_levels=True "
                                 "and the run's CheckResult")
            eng, res = engine, result
            # the handed-over run decides the mode: a sink means the
            # edges already streamed out of its commit
            mode = ("stream"
                    if getattr(eng, "edge_sink", None) is not None
                    else "two-pass")
        else:
            eng = PagedBFS(spec, tile_size=tile_size,
                           chunk_tiles=chunk_tiles, retain_levels=True,
                           edges=(mode == "stream"),
                           edge_spill_dir=edge_spill_dir,
                           **eng_kwargs)
            res = eng.run(max_states=max_states, log=log,
                          checkpoint_path=checkpoint_path,
                          checkpoint_every=checkpoint_every,
                          resume_from=resume_from, obs=obs)
        self.mode = mode
        if res.error is not None:
            raise TLAError(
                f"device liveness graph: BFS did not reach fixpoint "
                f"({res.error})")
        if not res.ok:
            raise TLAError(
                f"device liveness graph: safety violation "
                f"{res.violated_invariant} during state enumeration "
                f"(check invariants before properties)")
        self.eng = eng
        self.codec, self.kern = eng.codec, eng.kern
        self.n = res.distinct_states
        self.inits = list(range(eng.level_sizes[0]))
        self.blocks = eng.level_blocks
        self._block_base = np.cumsum(
            [0] + [b["status"].shape[0] for b in self.blocks])
        if self._block_base[-1] != self.n:
            raise TLAError(
                "device liveness graph: retained level blocks cover "
                f"{int(self._block_base[-1])} of {self.n} states — the "
                "engine was resumed from a checkpoint mid-enumeration; "
                "build the graph from a fresh (non-resumed) run")
        self.states = _LazyStates(self)
        self.bfs_elapsed = res.elapsed
        self.distinct_states = self.n
        self.states_generated = res.states_generated

        if mode == "stream":
            # the edges already streamed out of the fused commit —
            # all that is left is assembling the CSR arrays
            self.csr = eng.edge_sink.finalize(self.n)
            eng.edge_sink.drop()
        else:
            self._build_fp_index()
            self.csr = self._build_edges(log)
        self._edges_list = None
        self.build_elapsed = time.time() - t0
        # graph construction cost beyond the safety BFS itself, as a
        # fraction of the BFS wall-clock (the ISSUE 15 acceptance
        # gauge: ~100%+ under two-pass re-expansion, <= 25% streamed).
        # Clamped at 0 for resumed runs whose bfs_elapsed is
        # cumulative across the recover chain while build_elapsed is
        # this process's only
        bfs_s = max(self.bfs_elapsed, 1e-9)
        self.graph_overhead_ratio = round(
            max(0.0, self.build_elapsed - self.bfs_elapsed) / bfs_s, 4)
        # emission rate over the whole construction wall clock.  Under
        # engine hand-over (the CLI path) build_elapsed is only the
        # finalize sliver, so take the larger of the two clocks —
        # matching the SCHEMA.md "over the BFS wall clock" definition
        # instead of gauging finalize-timing noise
        self.edges_per_s = round(
            int(self.csr[1].shape[0])
            / max(self.build_elapsed, self.bfs_elapsed, 1e-9), 1)
        if log:
            log(f"device behavior graph ({mode}): {self.n} states, "
                f"{int(self.csr[1].shape[0])} edges in "
                f"{self.build_elapsed:.1f}s "
                f"(BFS {self.bfs_elapsed:.1f}s, graph overhead "
                f"{100 * self.graph_overhead_ratio:.0f}%)")

    # -- state access --------------------------------------------------
    def dense_row(self, sid):
        b = int(np.searchsorted(self._block_base, sid, side="right")) - 1
        i = sid - self._block_base[b]
        return {k: v[i] for k, v in self.blocks[b].items()}

    # -- fingerprint -> gid --------------------------------------------
    def _build_fp_index(self, batch=8192):
        """Device-resident gid-valued FPSet over all graph states: the
        fp->gid map pass 2 queries on device (fpset.insert_gids)."""
        from .fpset import empty_table, insert_gids
        cap = 1 << max(12, int(np.ceil(np.log2(max(self.n, 1) * 4))))
        self._gid_table = empty_table(cap)
        self._gid_vals = jnp.full((cap,), -1, jnp.int32)
        gid = 0
        insert = jax.jit(insert_gids, donate_argnums=(0, 1))
        zero = self.codec.zero_state()
        for blk in self.blocks:
            nb = blk["status"].shape[0]
            for off in range(0, nb, batch):
                m = min(batch, nb - off)
                # fixed-width padded batches: one compile for the whole
                # index build regardless of block sizes
                part = {k: np.zeros((batch,) + np.shape(zero[k]),
                                    np.int32) for k in zero}
                for k in part:
                    part[k][:m] = blk[k][off:off + m]
                fps = self.kern.fingerprint_batch(
                    {k: jnp.asarray(v) for k, v in part.items()})
                mask = jnp.arange(batch) < m
                gids = jnp.arange(gid, gid + batch, dtype=jnp.int32)
                self._gid_table, self._gid_vals, ovf, fresh = insert(
                    self._gid_table, self._gid_vals, fps, gids, mask)
                if bool(ovf):
                    raise TLAError("gid FPSet probe overflow (grow cap)")
                if int(fresh) != m:
                    raise TLAError(
                        "duplicate fingerprint across level blocks "
                        "(engine invariant broken)")
                gid += m

    # -- edge pass -----------------------------------------------------
    def _make_edge_pass(self):
        """Jitted: one tile of states -> (fp, src row, action id, ok)
        for every enabled lane, via per-action guard compaction and
        incremental fingerprints (the level kernel's phases 1-2 with
        recording instead of FPSet insertion)."""
        kern = self.eng.kern
        T = self.eng.tile
        incremental = (self.eng.hash_mode == "incremental"
                       and hasattr(kern, "parent_parts"))
        caps = [min(T * kern._lane_count(nm),
                    max(64, T * self.eng.expand_mults[a]))
                for a, nm in enumerate(kern.action_names)]

        def edge_pass(tile, n_valid):
            valid = jnp.arange(T, dtype=I32) < n_valid
            parts = (jax.vmap(kern.parent_parts)(tile)
                     if incremental else None)
            out_fp, out_src, out_aid, out_ok = [], [], [], []
            ovf = jnp.asarray(False)
            err_any = jnp.asarray(0, I32)
            for aid, (name, fn, guard) in enumerate(
                    zip(kern.action_names, kern._action_fns(),
                        kern._guard_fns())):
                L_a = kern._lane_count(name)
                TL = T * L_a
                E_a = caps[aid]
                lanes = jnp.arange(L_a, dtype=I32)
                en = jax.vmap(lambda st: jax.vmap(
                    lambda ln: guard(st, ln))(lanes))(tile)
                en = en & valid[:, None]
                ovf = ovf | (en.sum() > E_a)
                (sel,) = jnp.nonzero(en.reshape(TL), size=E_a,
                                     fill_value=TL)
                sel_ok = sel < TL
                pidx = jnp.clip(sel // L_a, 0, T - 1).astype(I32)
                lane_sel = (sel % L_a).astype(I32)
                st_sel = {k: v[pidx] for k, v in tile.items()}
                if incremental:
                    parts_sel = jax.tree_util.tree_map(
                        lambda v: v[pidx], parts)

                    def one(st, parts_one, lane, fn=fn, name=name):
                        succ, en1 = fn(kern.seed_touch(st), lane)
                        ri = kern.lane_replica(name, st, lane)
                        fp = kern.fingerprint_incremental(
                            succ, ri, parts_one, st)
                        return fp, en1, succ["err"]
                    fp, en1, errv = jax.vmap(one)(st_sel, parts_sel,
                                                  lane_sel)
                else:
                    def one(st, lane, fn=fn):
                        succ, en1 = fn(st, lane)
                        clean = {k: v for k, v in succ.items()
                                 if not k.startswith("_")}
                        return (kern.fingerprint(clean), en1,
                                clean["err"])
                    fp, en1, errv = jax.vmap(one)(st_sel, lane_sel)
                ok = en1 & sel_ok
                err_any = err_any | jnp.where(
                    ok, errv, 0).max(initial=0)
                out_fp.append(fp)
                out_src.append(pidx)
                out_aid.append(jnp.full((E_a,), aid, I32))
                out_ok.append(ok)
            return (jnp.concatenate(out_fp),
                    jnp.concatenate(out_src),
                    jnp.concatenate(out_aid),
                    jnp.concatenate(out_ok), ovf, err_any)
        return jax.jit(edge_pass)

    def _build_edges(self, log=None):
        """Pass 2 -> CSR (indptr[n+1], action_id[m], tid[m]): fp->gid
        resolution happens on device (lookup_gids); host work is array
        concatenation plus one argsort."""
        from .fpset import lookup_gids
        T = self.eng.tile
        edge_pass = self._make_edge_pass()
        lookup = jax.jit(lookup_gids)
        zero = self.codec.zero_state()
        src_parts, aid_parts, tid_parts = [], [], []
        for bi, blk in enumerate(self.blocks):
            base = int(self._block_base[bi])
            nb = blk["status"].shape[0]
            for off in range(0, nb, T):
                n_t = min(T, nb - off)
                tile = {k: np.zeros((T,) + np.shape(zero[k]), np.int32)
                        for k in zero}
                for k in tile:
                    tile[k][:n_t] = blk[k][off:off + n_t]
                fp, src, aid, ok, ovf, err = edge_pass(
                    {k: jnp.asarray(v) for k, v in tile.items()},
                    jnp.asarray(n_t, I32))
                tid = lookup(self._gid_table, self._gid_vals, fp, ok)
                tid, src, aid, ok, ovf, err = jax.device_get(
                    (tid, src, aid, ok, ovf, err))
                if bool(ovf):
                    raise TLAError(
                        "edge pass compaction overflow — pass 1 should "
                        "have calibrated expand_mults (engine bug)")
                if int(err):
                    kind = ("bag overflow"
                            if int(err) & ERR_BAG_OVERFLOW else
                            "slot error")
                    raise TLAError(
                        f"edge pass produced lane error ({kind}) on a "
                        f"successor pass 1 accepted (engine bug)")
                okm = np.asarray(ok)
                tids = np.asarray(tid)[okm]
                if (tids < 0).any():
                    raise TLAError(
                        "edge pass reached a state the BFS never "
                        "recorded (fingerprint mismatch)")
                src_parts.append(base + off
                                 + np.asarray(src)[okm].astype(np.int64))
                aid_parts.append(np.asarray(aid)[okm])
                tid_parts.append(tids)
        src = np.concatenate(src_parts) if src_parts else \
            np.zeros(0, np.int64)
        aid = np.concatenate(aid_parts) if aid_parts else \
            np.zeros(0, np.int32)
        tid = np.concatenate(tid_parts) if tid_parts else \
            np.zeros(0, np.int32)
        order = np.argsort(src, kind="stable")
        src, aid, tid = src[order], aid[order], tid[order]
        indptr = np.zeros(self.n + 1, np.int64)
        np.cumsum(np.bincount(src, minlength=self.n), out=indptr[1:])
        return indptr, aid, tid

    @property
    def edges(self):
        """List-of-lists [(action_name, tid)] view of the CSR arrays,
        materialized on first access (small graphs / legacy callers;
        the fair-SCC machinery reads .csr directly)."""
        if self._edges_list is None:
            indptr, aid, tid = self.csr
            names = self.kern.action_names
            self._edges_list = [
                [(names[int(aid[j])], int(tid[j]))
                 for j in range(indptr[u], indptr[u + 1])]
                for u in range(self.n)]
        return self._edges_list

    # -- batched predicate evaluation ----------------------------------
    def _run_batched(self, pred):
        fn = jax.jit(jax.vmap(pred))
        out = np.empty(self.n, bool)
        for bi, blk in enumerate(self.blocks):
            base = int(self._block_base[bi])
            nb = blk["status"].shape[0]
            vals = np.asarray(fn({k: jnp.asarray(v)
                                  for k, v in blk.items()}))
            out[base:base + nb] = vals
        return out

    def batch_predicate(self, name):
        """Evaluate a named predicate with a device kernel over all
        states; returns a bool array [n] or None if no kernel exists."""
        if name in getattr(self.kern, "INVARIANT_FNS", {}):
            return self._run_batched(self.kern.invariant_fn([name]))
        d = self.spec.module.defs.get(name)
        if d is not None and not d.params:
            return self.batch_expr(d.body, {})
        return None

    def batch_expr(self, expr, bindings):
        """Evaluate an arbitrary property-leaf expression over all
        states through the AST lowerer (available when the kernel is
        compiled-from-AST, lower/compile.py), with `bindings` mapping
        quantifier-bound names to static values.  Returns a bool array
        [n], or None when no lowerer exists or the expression uses a
        construct the lowerer cannot compile — callers fall back to the
        interpreter."""
        from ..lower.compile import Env, Lowerer, LowerError, d_static
        low = getattr(self.kern, "lowerer", None)
        if low is None:
            # hand kernels share the layout family; a lowerer over the
            # same codec serves predicate-only compilation
            try:
                low = Lowerer(self.spec, self.codec, self.kern)
            except Exception:  # noqa: BLE001 — unsupported family
                return None
            self.kern.lowerer = low

        def pred(st):
            env = Env({n: d_static(v) for n, v in bindings.items()})
            v = low.expr(expr, env, st)
            if v.kind == "static":
                return jnp.asarray(bool(v.v))
            return jnp.asarray(low.as_bool(v), bool)

        try:
            return self._run_batched(pred)
        except (LowerError, KeyError, AttributeError, TypeError,
                IndexError):
            # any lowering failure (including builtin exceptions from
            # encoding/field tables) means "no device evaluation" —
            # the caller falls back to the interpreter, matching the
            # pre-lowerer behavior
            return None
