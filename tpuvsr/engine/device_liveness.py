"""Device-driven behavior-graph construction for liveness checking.

Round-3 gap (VERDICT item 3): `engine/liveness.py` built its behavior
graph with the Python interpreter — orders of magnitude slower than
the device BFS — so liveness beyond toy constants could not terminate.
This module builds the SAME graph with the device engines and feeds it
to the unchanged host-side fair-SCC machinery:

  pass 1  enumerate all reachable states with the paged BFS engine
          (``PagedBFS(retain_levels=True)``): every level's dense
          states land on the host in gid order, with all growth /
          violation handling inherited.
  pass 2  re-expand every level tile-by-tile through a jitted EDGE
          pass — the level kernel's guard + compaction + incremental-
          fingerprint phases, minus FPSet insert/scatter — emitting
          (source row, action id, successor fingerprint) for EVERY
          enabled lane, not just fresh ones.  The host resolves
          successor fingerprints to gids through a dict built from the
          per-level fingerprint batches, yielding the edge list
          (sid, action name, tid) that TLC's behavior graph records
          (SURVEY.md §3.4).

Predicate evaluation for property leaves is batched: a leaf that names
a predicate with a device kernel (e.g. ``AllReplicasMoveToSameView``,
the `[]<>` body of ConvergenceToView, A01:770) is evaluated on device
over whole level blocks; other leaves (the quantified `~>` legs of
OpEventuallyAllOrNothing, A01:784-788) fall back to the interpreter on
decoded states, decoded once and memoized.

The graph object plugs into ``liveness_check(spec, graph=...)``
unchanged: it quacks like the (states, edges, inits) triple via
``states`` (lazy decode), ``edges`` and ``inits`` attributes.

Liveness requires SYMMETRY off (A01 cfg:22-24), which also makes the
device fingerprint exact VIEW identity (single permutation); 128-bit
fingerprint collisions are the same vanishing risk the BFS engine
accepts (fpset.py docstring).
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from ..core.values import TLAError
from ..models.vsr import ERR_BAG_OVERFLOW
from .paged_bfs import PagedBFS

I32 = jnp.int32


class _LazyStates:
    """List-like view of the graph's states: decodes dense rows on
    demand and memoizes (property evaluation touches every state once;
    trace reconstruction a handful more)."""

    def __init__(self, graph):
        self.g = graph
        self._cache = {}

    def __len__(self):
        return self.g.n

    def __getitem__(self, sid):
        st = self._cache.get(sid)
        if st is None:
            st = self.g.codec.decode(self.g.dense_row(sid))
            self._cache[sid] = st
        return st


class DeviceGraph:
    """Behavior graph built by the device engines (states, edges,
    inits), with batched device predicate evaluation where possible."""

    def __init__(self, spec, tile_size=64, chunk_tiles=16,
                 max_states=None, log=None, engine=None, result=None,
                 **eng_kwargs):
        """Pass a finished ``engine`` (a PagedBFS constructed with
        retain_levels=True whose run() returned ``result``) to reuse an
        enumeration that already happened — e.g. the CLI's safety BFS —
        instead of re-running pass 1."""
        if spec.symmetry_perms:
            raise TLAError("liveness checking requires SYMMETRY off "
                           "(reference cfg guidance, A01 cfg:22-24)")
        self.spec = spec
        t0 = time.time()
        if engine is not None:
            if result is None or not engine.retain_levels:
                raise ValueError("engine reuse needs retain_levels=True "
                                 "and the run's CheckResult")
            eng, res = engine, result
        else:
            eng = PagedBFS(spec, tile_size=tile_size,
                           chunk_tiles=chunk_tiles, retain_levels=True,
                           **eng_kwargs)
            res = eng.run(max_states=max_states, log=log)
        if res.error is not None:
            raise TLAError(
                f"device liveness graph: BFS did not reach fixpoint "
                f"({res.error})")
        if not res.ok:
            raise TLAError(
                f"device liveness graph: safety violation "
                f"{res.violated_invariant} during state enumeration "
                f"(check invariants before properties)")
        self.eng = eng
        self.codec, self.kern = eng.codec, eng.kern
        self.n = res.distinct_states
        self.inits = list(range(eng.level_sizes[0]))
        self.blocks = eng.level_blocks
        self._block_base = np.cumsum(
            [0] + [b["status"].shape[0] for b in self.blocks])
        if self._block_base[-1] != self.n:
            raise TLAError(
                "device liveness graph: retained level blocks cover "
                f"{int(self._block_base[-1])} of {self.n} states — the "
                "engine was resumed from a checkpoint mid-enumeration; "
                "build the graph from a fresh (non-resumed) run")
        self.states = _LazyStates(self)
        self.bfs_elapsed = res.elapsed
        self.distinct_states = self.n
        self.states_generated = res.states_generated

        self._fp2gid = self._build_fp_index()
        self.edges = self._build_edges(log)
        self.build_elapsed = time.time() - t0
        if log:
            n_edges = sum(len(e) for e in self.edges)
            log(f"device behavior graph: {self.n} states, {n_edges} "
                f"edges in {self.build_elapsed:.1f}s "
                f"(BFS {self.bfs_elapsed:.1f}s)")

    # -- state access --------------------------------------------------
    def dense_row(self, sid):
        b = int(np.searchsorted(self._block_base, sid, side="right")) - 1
        i = sid - self._block_base[b]
        return {k: v[i] for k, v in self.blocks[b].items()}

    # -- fingerprint -> gid --------------------------------------------
    def _build_fp_index(self, batch=4096):
        fp2gid = {}
        gid = 0
        for blk in self.blocks:
            nb = blk["status"].shape[0]
            for off in range(0, nb, batch):
                part = {k: jnp.asarray(v[off:off + batch])
                        for k, v in blk.items()}
                fps = np.asarray(self.kern.fingerprint_batch(part))
                for row in fps:
                    key = row.tobytes()
                    # first occurrence wins (gid order is BFS order;
                    # blocks contain each distinct state exactly once)
                    if key in fp2gid:
                        raise TLAError(
                            "duplicate fingerprint across level blocks "
                            "(engine invariant broken)")
                    fp2gid[key] = gid
                    gid += 1
        return fp2gid

    # -- edge pass -----------------------------------------------------
    def _make_edge_pass(self):
        """Jitted: one tile of states -> (fp, src row, action id, ok)
        for every enabled lane, via per-action guard compaction and
        incremental fingerprints (the level kernel's phases 1-2 with
        recording instead of FPSet insertion)."""
        kern = self.eng.kern
        T = self.eng.tile
        caps = [min(T * kern._lane_count(nm),
                    max(64, T * self.eng.expand_mults[a]))
                for a, nm in enumerate(kern.action_names)]

        def edge_pass(tile, n_valid):
            valid = jnp.arange(T, dtype=I32) < n_valid
            parts = jax.vmap(kern.parent_parts)(tile)
            out_fp, out_src, out_aid, out_ok = [], [], [], []
            ovf = jnp.asarray(False)
            err_any = jnp.asarray(0, I32)
            for aid, (name, fn, guard) in enumerate(
                    zip(kern.action_names, kern._action_fns(),
                        kern._guard_fns())):
                L_a = kern._lane_count(name)
                TL = T * L_a
                E_a = caps[aid]
                lanes = jnp.arange(L_a, dtype=I32)
                en = jax.vmap(lambda st: jax.vmap(
                    lambda ln: guard(st, ln))(lanes))(tile)
                en = en & valid[:, None]
                ovf = ovf | (en.sum() > E_a)
                (sel,) = jnp.nonzero(en.reshape(TL), size=E_a,
                                     fill_value=TL)
                sel_ok = sel < TL
                pidx = jnp.clip(sel // L_a, 0, T - 1).astype(I32)
                lane_sel = (sel % L_a).astype(I32)
                st_sel = {k: v[pidx] for k, v in tile.items()}
                parts_sel = jax.tree_util.tree_map(
                    lambda v: v[pidx], parts)

                def one(st, parts_one, lane, fn=fn, name=name):
                    succ, en1 = fn(kern.seed_touch(st), lane)
                    ri = kern.lane_replica(name, st, lane)
                    fp = kern.fingerprint_incremental(
                        succ, ri, parts_one, st)
                    return fp, en1, succ["err"]
                fp, en1, errv = jax.vmap(one)(st_sel, parts_sel,
                                              lane_sel)
                ok = en1 & sel_ok
                err_any = err_any | jnp.where(
                    ok, errv, 0).max(initial=0)
                out_fp.append(fp)
                out_src.append(pidx)
                out_aid.append(jnp.full((E_a,), aid, I32))
                out_ok.append(ok)
            return (jnp.concatenate(out_fp),
                    jnp.concatenate(out_src),
                    jnp.concatenate(out_aid),
                    jnp.concatenate(out_ok), ovf, err_any)
        return jax.jit(edge_pass)

    def _build_edges(self, log=None):
        T = self.eng.tile
        edge_pass = self._make_edge_pass()
        names = self.kern.action_names
        edges = [[] for _ in range(self.n)]
        zero = self.codec.zero_state()
        for bi, blk in enumerate(self.blocks):
            base = int(self._block_base[bi])
            nb = blk["status"].shape[0]
            for off in range(0, nb, T):
                n_t = min(T, nb - off)
                tile = {k: np.zeros((T,) + np.shape(zero[k]), np.int32)
                        for k in zero}
                for k in tile:
                    tile[k][:n_t] = blk[k][off:off + n_t]
                fp, src, aid, ok, ovf, err = jax.device_get(edge_pass(
                    {k: jnp.asarray(v) for k, v in tile.items()},
                    jnp.asarray(n_t, I32)))
                if bool(ovf):
                    raise TLAError(
                        "edge pass compaction overflow — pass 1 should "
                        "have calibrated expand_mults (engine bug)")
                if int(err):
                    kind = ("bag overflow"
                            if int(err) & ERR_BAG_OVERFLOW else
                            "slot error")
                    raise TLAError(
                        f"edge pass produced lane error ({kind}) on a "
                        f"successor pass 1 accepted (engine bug)")
                okm = np.asarray(ok)
                fps = np.asarray(fp)[okm]
                srcs = np.asarray(src)[okm]
                aids = np.asarray(aid)[okm]
                for i in range(fps.shape[0]):
                    tid = self._fp2gid.get(fps[i].tobytes())
                    if tid is None:
                        raise TLAError(
                            "edge pass reached a state the BFS never "
                            "recorded (fingerprint mismatch)")
                    edges[base + off + int(srcs[i])].append(
                        (names[int(aids[i])], tid))
        return edges

    # -- batched predicate evaluation ----------------------------------
    def batch_predicate(self, name):
        """Evaluate a named predicate with a device kernel over all
        states; returns a bool array [n] or None if no kernel exists."""
        if name not in getattr(self.kern, "INVARIANT_FNS", {}):
            return None
        fn = jax.jit(jax.vmap(self.kern.invariant_fn([name])))
        out = np.empty(self.n, bool)
        for bi, blk in enumerate(self.blocks):
            base = int(self._block_base[bi])
            nb = blk["status"].shape[0]
            vals = np.asarray(fn({k: jnp.asarray(v)
                                  for k, v in blk.items()}))
            out[base:base + nb] = vals
        return out
