"""Backend selection guard.

The image bakes ``JAX_PLATFORMS=axon`` plus a sitecustomize that
registers the tunneled-TPU PJRT plugin whenever ``PALLAS_AXON_POOL_IPS``
is set — and when the tunnel is down, *backend init hangs forever*,
taking any plain-python entry point with it.  Call ``force_cpu()``
before touching any engine module to pin the process to the CPU
backend regardless of what sitecustomize already did; call
``probe_tpu()`` to test the tunnel from a throwaway subprocess with a
hard timeout (the only safe way to ask).
"""

from __future__ import annotations

import os
import subprocess
import sys


def force_cpu():
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")


def ensure_backend(log=None, probe_timeout=60):
    """Pick a live backend for this process.  CPU is honored directly;
    anything else (explicit TPU/axon, or an unset environment where
    JAX would autodetect an accelerator) is probed from a throwaway
    subprocess first, falling back to CPU if backend init hangs or
    fails (a dead tunnel hangs forever in-process).  Returns the
    backend name in use."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        force_cpu()
        return "cpu"
    n = probe_tpu(probe_timeout)
    if n > 0:
        return os.environ.get("JAX_PLATFORMS") or "autodetect"
    if log:
        log("accelerator backend unreachable; falling back to CPU")
    force_cpu()
    return "cpu-fallback"


def probe_tpu(timeout=60):
    """Return the number of TPU devices visible through the tunnel, or
    0 if the probe fails/hangs (dead tunnel).

    The probe subprocess inherits the environment, so the child counts
    only non-CPU devices: a cpu-pinned parent (the documented hang
    workaround) or a bare environment with no accelerator plugin then
    probes 0 instead of reporting its own CPU devices as TPUs — seen
    live in r4 when a cpu-pinned dryrun parent probed "8 TPU devices"
    from its own virtual CPU mesh."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(sum(1 for d in jax.devices()"
             " if d.platform != 'cpu'))"],
            capture_output=True, text=True, timeout=timeout)
        if r.returncode == 0 and r.stdout.strip():
            return int(r.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, ValueError, IndexError):
        pass
    return 0
